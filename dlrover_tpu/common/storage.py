"""Pluggable checkpoint storage.

Reference analog: dlrover/python/common/storage.py (:23 CheckpointStorage,
:127 PosixDiskStorage). ``ClassMeta`` survives a process boundary so the
agent-side persister can reconstruct the trainer-configured storage backend
(the reference ships it through shared memory; we ship it as JSON).
"""

from __future__ import annotations

import dataclasses
import errno
import importlib
import os
import shutil
import time
from abc import ABC, abstractmethod
from typing import Any

from dlrover_tpu import chaos


@dataclasses.dataclass
class ClassMeta:
    module_path: str = ""
    class_name: str = ""
    kwargs: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ClassMeta":
        return cls(**d)


def build_storage(meta: ClassMeta) -> "CheckpointStorage":
    module = importlib.import_module(meta.module_path)
    klass = getattr(module, meta.class_name)
    if not (isinstance(klass, type) and issubclass(klass, CheckpointStorage)):
        raise TypeError(f"{meta.class_name} is not a CheckpointStorage")
    return klass(**meta.kwargs)


class CheckpointStorage(ABC):
    @abstractmethod
    def write(self, content: bytes | str, path: str) -> None: ...

    @abstractmethod
    def read(self, path: str) -> bytes: ...

    @abstractmethod
    def exists(self, path: str) -> bool: ...

    @abstractmethod
    def listdir(self, path: str) -> list[str]: ...

    @abstractmethod
    def makedirs(self, path: str) -> None: ...

    @abstractmethod
    def delete(self, path: str) -> None: ...

    def read_text(self, path: str) -> str:
        return self.read(path).decode("utf-8")

    def class_meta(self) -> ClassMeta:
        return ClassMeta(
            module_path=type(self).__module__,
            class_name=type(self).__name__,
            kwargs=self._init_kwargs(),
        )

    def _init_kwargs(self) -> dict[str, Any]:
        return {}


def _apply_write_fault(content: bytes | str, path: str
                       ) -> tuple[bytes | str, float]:
    """Injected storage faults (chaos plan ``storage_write`` point).

    ``bit_flip`` corrupts one bit of the payload (position drawn from
    the rule's seeded stream — the disk lies, the writer never knows),
    ``enospc`` raises the classic full-disk OSError, ``slow_fsync``
    returns an fsync delay (a sick device that still completes), and
    ``torn`` leaves a PARTIAL file at the final path and raises — the
    non-atomic crash mid-write the tmp+rename protocol exists to
    prevent, forced past it. Returns (possibly mutated content,
    fsync delay seconds).
    """
    fault = chaos.fire("storage_write", path=path)
    if fault is None:
        return content, 0.0
    if fault.action == "enospc":
        raise OSError(errno.ENOSPC,
                      f"chaos: no space left on device: {path}")
    if fault.action == "slow_fsync":
        return content, float(fault.args.get("s", 0.5))
    data = bytearray(
        content if isinstance(content, bytes) else content.encode("utf-8")
    )
    if fault.action == "bit_flip":
        if data:
            pos = int(fault.args.get("offset", -1))
            if pos < 0 or pos >= len(data):
                pos = int(fault.rand * len(data))
            data[pos] ^= 1 << (fault.seq % 8)
        return bytes(data), 0.0
    if fault.action == "torn":
        cut = max(0, min(len(data) - 1,
                         int(len(data) * float(fault.args.get("frac", 0.5)))))
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            f.write(bytes(data[:cut]))
        raise OSError(f"chaos: torn write of {path} "
                      f"({cut}/{len(data)} bytes)")
    return content, 0.0


def atomic_write_file(content: bytes | str, path: str) -> None:
    """Durable atomic file publish: tmp + fsync + rename. Without the
    fsync a crash right after the rename can publish a truncated file."""
    fsync_delay = 0.0
    if chaos.ENABLED:
        content, fsync_delay = _apply_write_fault(content, path)
    mode = "wb" if isinstance(content, bytes) else "w"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, mode) as f:
        f.write(content)
        f.flush()
        if fsync_delay > 0:
            time.sleep(fsync_delay)
        os.fsync(f.fileno())
    os.replace(tmp, path)


class PosixDiskStorage(CheckpointStorage):
    """Local/NFS filesystem storage with atomic writes."""

    def write(self, content: bytes | str, path: str) -> None:
        atomic_write_file(content, path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> list[str]:
        return sorted(os.listdir(path)) if os.path.isdir(path) else []

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.unlink(path)
