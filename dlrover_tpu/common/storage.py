"""Pluggable checkpoint storage.

Reference analog: dlrover/python/common/storage.py (:23 CheckpointStorage,
:127 PosixDiskStorage). ``ClassMeta`` survives a process boundary so the
agent-side persister can reconstruct the trainer-configured storage backend
(the reference ships it through shared memory; we ship it as JSON).
"""

from __future__ import annotations

import dataclasses
import errno
import importlib
import os
import shutil
import threading
import time
from abc import ABC, abstractmethod
from typing import Any

from dlrover_tpu import chaos


@dataclasses.dataclass
class ClassMeta:
    module_path: str = ""
    class_name: str = ""
    kwargs: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ClassMeta":
        return cls(**d)


def build_storage(meta: ClassMeta) -> "CheckpointStorage":
    module = importlib.import_module(meta.module_path)
    klass = getattr(module, meta.class_name)
    if not (isinstance(klass, type) and issubclass(klass, CheckpointStorage)):
        raise TypeError(f"{meta.class_name} is not a CheckpointStorage")
    return klass(**meta.kwargs)


class CheckpointStorage(ABC):
    """Pluggable checkpoint backend (object-store-grade interface).

    The six abstract methods are the minimum contract; the ranged /
    chunked operations below have whole-blob default implementations so
    a naive backend is correct, just not parallel. Backends over real
    object stores (GCS/S3 composite uploads) override ``write_parallel``
    with multi-part uploads and ``read_range`` with ranged GETs — the
    topology-changing restore reads only the byte ranges the local mesh
    needs through these. Contract tests:
    tests/test_parallel_ckpt.py::StorageContract runs any backend
    against the semantics the checkpoint layer assumes.
    """

    @abstractmethod
    def write(self, content: bytes | str, path: str) -> None: ...

    @abstractmethod
    def read(self, path: str) -> bytes: ...

    @abstractmethod
    def exists(self, path: str) -> bool: ...

    @abstractmethod
    def listdir(self, path: str) -> list[str]: ...

    @abstractmethod
    def makedirs(self, path: str) -> None: ...

    @abstractmethod
    def delete(self, path: str) -> None: ...

    def read_text(self, path: str) -> str:
        return self.read(path).decode("utf-8")

    # ------------------------------------------- object-store-grade ops

    def size(self, path: str) -> int:
        return len(self.read(path))

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        """``length`` bytes at ``offset``; short only at end-of-object
        (mirrors ranged-GET semantics)."""
        return self.read(path)[offset:offset + length]

    def write_parallel(self, content: bytes | memoryview, path: str,
                       chunk_bytes: int = 64 << 20,
                       workers: int = 4) -> None:
        """Publish one blob with chunked concurrent I/O; atomic — a
        reader never observes a partial object at ``path``. Default
        degrades to the plain atomic write."""
        self.write(bytes(content), path)

    def class_meta(self) -> ClassMeta:
        return ClassMeta(
            module_path=type(self).__module__,
            class_name=type(self).__name__,
            kwargs=self._init_kwargs(),
        )

    def _init_kwargs(self) -> dict[str, Any]:
        return {}


def _apply_write_fault(content: bytes | str, path: str
                       ) -> tuple[bytes | str, float]:
    """Injected storage faults (chaos plan ``storage_write`` point).

    ``bit_flip`` corrupts one bit of the payload (position drawn from
    the rule's seeded stream — the disk lies, the writer never knows),
    ``enospc`` raises the classic full-disk OSError, ``slow_fsync``
    returns an fsync delay (a sick device that still completes), and
    ``torn`` leaves a PARTIAL file at the final path and raises — the
    non-atomic crash mid-write the tmp+rename protocol exists to
    prevent, forced past it. Returns (possibly mutated content,
    fsync delay seconds).
    """
    fault = chaos.fire("storage_write", path=path)
    if fault is None:
        return content, 0.0
    if fault.action == "enospc":
        raise OSError(errno.ENOSPC,
                      f"chaos: no space left on device: {path}")
    if fault.action == "slow_fsync":
        return content, float(fault.args.get("s", 0.5))
    data = bytearray(
        content if isinstance(content, bytes) else content.encode("utf-8")
    )
    if fault.action == "bit_flip":
        if data:
            pos = int(fault.args.get("offset", -1))
            if pos < 0 or pos >= len(data):
                pos = int(fault.rand * len(data))
            data[pos] ^= 1 << (fault.seq % 8)
        return bytes(data), 0.0
    if fault.action == "torn":
        cut = max(0, min(len(data) - 1,
                         int(len(data) * float(fault.args.get("frac", 0.5)))))
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        with open(path, "wb") as f:
            f.write(bytes(data[:cut]))
        raise OSError(f"chaos: torn write of {path} "
                      f"({cut}/{len(data)} bytes)")
    return content, 0.0


def _apply_read_fault(data: bytes, path: str) -> bytes:
    """Injected storage faults on the READ side (chaos plan
    ``storage_read`` point), mirroring ``storage_write``:

    ``bit_flip`` corrupts one bit of the returned bytes (the medium
    rotted after a clean write — the CRC layer must catch it),
    ``missing`` raises FileNotFoundError (an object-store eventual-
    consistency hole or deleted shard), and ``slow`` sleeps before
    returning (a degraded disk / throttled bucket). The fault applies
    to what the CALLER sees; the bytes on storage stay intact, so a
    retry or a twin read can succeed — exactly the transient-read
    failure class the per-shard rollback exists for.
    """
    fault = chaos.fire("storage_read", path=path)
    if fault is None:
        return data
    if fault.action == "missing":
        raise FileNotFoundError(f"chaos: missing object: {path}")
    if fault.action == "slow":
        time.sleep(float(fault.args.get("s", 0.5)))
        return data
    if fault.action == "bit_flip" and data:
        out = bytearray(data)
        pos = int(fault.args.get("offset", -1))
        if pos < 0 or pos >= len(out):
            pos = int(fault.rand * len(out))
        out[pos] ^= 1 << (fault.seq % 8)
        return bytes(out)
    return data


def atomic_write_file(content: bytes | str, path: str) -> None:
    """Durable atomic file publish: tmp + fsync + rename. Without the
    fsync a crash right after the rename can publish a truncated file."""
    fsync_delay = 0.0
    if chaos.ENABLED:
        content, fsync_delay = _apply_write_fault(content, path)
    mode = "wb" if isinstance(content, bytes) else "w"
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # pid alone is not unique enough: two threads of one process
    # publishing the same path (the master's periodic state loop vs an
    # on-demand snapshot) would share a tmp name and race the rename
    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
    with open(tmp, mode) as f:
        f.write(content)
        f.flush()
        if fsync_delay > 0:
            time.sleep(fsync_delay)
        os.fsync(f.fileno())
    os.replace(tmp, path)


class PosixDiskStorage(CheckpointStorage):
    """Local/NFS filesystem storage with atomic writes."""

    def write(self, content: bytes | str, path: str) -> None:
        atomic_write_file(content, path)

    def read(self, path: str) -> bytes:
        with open(path, "rb") as f:
            data = f.read()
        if chaos.ENABLED:
            data = _apply_read_fault(data, path)
        return data

    def size(self, path: str) -> int:
        return os.path.getsize(path)

    def read_range(self, path: str, offset: int, length: int) -> bytes:
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(length)
        if chaos.ENABLED:
            data = _apply_read_fault(data, path)
        return data

    def write_parallel(self, content: bytes | memoryview, path: str,
                       chunk_bytes: int = 64 << 20,
                       workers: int = 4) -> None:
        """Chunked concurrent pwrite into a tmp file, then fsync +
        rename — same atomicity as ``atomic_write_file``, but the body
        lands through ``workers`` parallel writers (one core sees no
        gain; NFS/FUSE object mounts and multi-queue NVMe do). The
        chaos ``storage_write`` fault applies to the WHOLE blob before
        chunking, so write-side bit flips stay byte-deterministic
        regardless of worker interleaving."""
        view = memoryview(content)
        fsync_delay = 0.0
        if chaos.ENABLED:
            mutated, fsync_delay = _apply_write_fault(bytes(view), path)
            view = memoryview(
                mutated if isinstance(mutated, bytes)
                else mutated.encode("utf-8")
            )
        total = len(view)
        workers = max(1, int(workers))
        chunk_bytes = max(1 << 20, int(chunk_bytes))
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
        try:
            os.truncate(fd, total)
            offsets = list(range(0, total, chunk_bytes))
            if len(offsets) <= 1 or workers == 1:
                off = 0
                while off < total:
                    off += os.pwrite(fd, view[off:off + chunk_bytes], off)
            else:
                from concurrent.futures import ThreadPoolExecutor

                def _put(off: int) -> None:
                    end = min(off + chunk_bytes, total)
                    while off < end:
                        off += os.pwrite(fd, view[off:end], off)

                with ThreadPoolExecutor(max_workers=workers) as pool:
                    # list() re-raises the first worker error here
                    list(pool.map(_put, offsets))
            if fsync_delay > 0:
                time.sleep(fsync_delay)
            os.fsync(fd)
        except BaseException:
            os.close(fd)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        os.close(fd)
        os.replace(tmp, path)

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def listdir(self, path: str) -> list[str]:
        return sorted(os.listdir(path)) if os.path.isdir(path) else []

    def makedirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def delete(self, path: str) -> None:
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.unlink(path)
