"""Raw-array message framing shared by the hot TCP paths.

One message = JSON header (op, meta, array manifest) + concatenated raw
array bytes, carried inside common/rpc's length-prefixed frame. No
pickle anywhere (the reference's pickled-dataclass RPC is the one design
choice SURVEY §7 explicitly refuses to port); arrays travel as raw
buffers so multi-MB embedding rows / model weights don't pay a JSON
float tax.

Users: the sharded embedding service (embedding/service.py) and the
disaggregated RLHF serving worker (rl/serving_worker.py).
"""

from __future__ import annotations

import json
import struct

import numpy as np

_HLEN = struct.Struct("<I")


def encode_msg(op: str, meta: dict | None = None,
               arrays: dict[str, np.ndarray] | None = None) -> bytes:
    manifest = {}
    chunks = []
    off = 0
    for name, arr in (arrays or {}).items():
        arr = np.ascontiguousarray(arr)
        manifest[name] = {
            "shape": list(arr.shape), "dtype": str(arr.dtype), "offset": off,
        }
        chunks.append(arr.tobytes())
        off += arr.nbytes
    header = json.dumps(
        {"op": op, "meta": meta or {}, "arrays": manifest}
    ).encode()
    return b"".join([_HLEN.pack(len(header)), header] + chunks)


def decode_msg(payload: bytes) -> tuple[str, dict, dict[str, np.ndarray]]:
    (hlen,) = _HLEN.unpack(payload[:_HLEN.size])
    header = json.loads(payload[_HLEN.size:_HLEN.size + hlen])
    base = _HLEN.size + hlen
    arrays = {}
    for name, info in header["arrays"].items():
        dtype = np.dtype(info["dtype"])
        count = int(np.prod(info["shape"]))
        arrays[name] = np.frombuffer(
            payload, dtype=dtype, count=count, offset=base + info["offset"]
        ).reshape(info["shape"]).copy()
    return header["op"], header["meta"], arrays


def flatten_tree(tree: dict, prefix: str = "") -> dict[str, np.ndarray]:
    """Flatten a nested-dict pytree of arrays into {slash/path: array}.
    Dict-only trees (the model-parameter shape) — lists/tuples are not
    wire-representable here on purpose: a path round-trip must be
    unambiguous."""
    out: dict[str, np.ndarray] = {}
    for k, v in tree.items():
        path = f"{prefix}/{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(flatten_tree(v, path))
        else:
            out[path] = np.asarray(v)
    return out


def unflatten_tree(flat: dict[str, np.ndarray]) -> dict:
    """Rebuild the nested dict from {slash/path: array}."""
    root: dict = {}
    for path, arr in flat.items():
        parts = path.split("/")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = arr
    return root
