"""Shared consistent-hash ring (blake2s points, virtual nodes).

One construction, two consumers with very different key shapes:

- ``gateway/router.ShardRing`` routes *prompts* (a prefix-family key is
  a short token tuple) to gateway shards — one ``owner_of`` call per
  request, hashed with blake2s like the ring points themselves.
- ``embedding/fabric.OwnerRing`` routes *feature ids* (int64 arrays,
  millions per second) to embedding shard servers — per-id blake2s in
  Python would dominate the lookup path, so id positions come from the
  vectorized splitmix64 finalizer (the same avalanche-quality mixer
  ``embedding/service.shard_owner`` already used) and land on the ring
  via one ``np.searchsorted``.

Both agree on the ring itself: ``vnodes`` points per member at
``blake2s("{member}#{v}")`` over a 64-bit keyspace, ownership =
clockwise successor (``bisect_right`` with wraparound), first owner
keeps a collided point. That is byte-for-byte the PR-12 ``ShardRing``
construction, factored here so a membership change moves ~1/N of the
keyspace for every consumer — the property the embedding fabric's
bounded-migration scale events (DESIGN.md §25) and the gateway's
cache-locality-preserving scale-outs (§23) both rest on.
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Sequence

import numpy as np


def hash_point(data: bytes) -> int:
    """64-bit ring position of an arbitrary byte key."""
    return int.from_bytes(
        hashlib.blake2s(data, digest_size=8).digest(), "big"
    )


def id_points(ids: np.ndarray) -> np.ndarray:
    """Vectorized 64-bit ring positions for int64 feature ids
    (splitmix64 finalizer — raw ids would put every hot contiguous id
    range on one arc)."""
    x = np.asarray(ids, dtype=np.uint64)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return x ^ (x >> np.uint64(31))


class HashRing:
    """Consistent hashing over opaque member ids. Thread-safe; the
    vectorized path works on an immutable snapshot so the hot lookup
    loop never takes the membership lock."""

    def __init__(self, members: Sequence[str] = (), *,
                 vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self._vnodes = vnodes
        self._lock = threading.Lock()
        self._points: list[int] = []          # sorted ring positions
        self._owner: dict[int, str] = {}      # point -> member id
        for member in members:
            self.add(member)

    # ---------------------------------------------------------- membership

    def add(self, member: str) -> None:
        with self._lock:
            for v in range(self._vnodes):
                point = hash_point(f"{member}#{v}".encode())
                if point in self._owner:        # vanishing collision:
                    continue                    # first owner keeps it
                self._owner[point] = member
                bisect.insort(self._points, point)

    def remove(self, member: str) -> None:
        with self._lock:
            dead = [p for p, m in self._owner.items() if m == member]
            for point in dead:
                del self._owner[point]
                idx = bisect.bisect_left(self._points, point)
                del self._points[idx]

    def members(self) -> list[str]:
        with self._lock:
            return sorted(set(self._owner.values()))

    # ------------------------------------------------------------- routing

    def owner_of_point(self, point: int) -> str | None:
        """Member owning one ring position; None on an empty ring."""
        with self._lock:
            if not self._points:
                return None
            idx = bisect.bisect_right(self._points, point)
            if idx == len(self._points):
                idx = 0                          # wrap around the ring
            return self._owner[self._points[idx]]

    def owner_of(self, key: bytes) -> str | None:
        return self.owner_of_point(hash_point(key))

    def snapshot(self, members: Sequence[str]
                 ) -> tuple[np.ndarray, np.ndarray]:
        """(sorted ring points, owner index into ``members`` per point)
        — the immutable arrays ``owner_indices`` resolves against, taken
        once per route version rather than per batch."""
        order = {m: i for i, m in enumerate(members)}
        with self._lock:
            points = np.asarray(self._points, dtype=np.uint64)
            owners = np.asarray(
                [order[self._owner[int(p)]] for p in self._points],
                dtype=np.int64,
            ) if len(self._points) else np.zeros(0, np.int64)
        return points, owners

    @staticmethod
    def owner_indices(points: np.ndarray, owners: np.ndarray,
                      positions: np.ndarray) -> np.ndarray:
        """Vectorized clockwise-successor lookup: for each 64-bit
        ``positions`` entry, the owning member's index per a
        ``snapshot``. ``searchsorted(side='right')`` + wraparound is
        exactly the scalar ``owner_of_point`` bisect."""
        if points.size == 0:
            raise ValueError("empty ring")
        idx = np.searchsorted(points, positions, side="right")
        idx[idx == points.size] = 0
        return owners[idx]
