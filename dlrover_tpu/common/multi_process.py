"""Cross-process primitives: SharedLock / SharedQueue / SharedDict / SharedMemory.

Reference analog: dlrover/python/common/multi_process.py (:225 SharedLock,
:346 SharedQueue, :453 SharedDict, :537 SharedMemory). Same architecture:
the *owner* process (the agent) hosts each primitive behind a unix-domain
socket; *client* processes (training workers) connect by name. Payloads are
typed JSON frames, never pickle.

SharedMemory differs from the stdlib in one crucial way (as in the
reference's ``_make_filename`` patch): segments are detached from the
resource tracker so they survive the death of whichever process touched them
— the point of flash checkpoint is that the agent can persist a worker's
snapshot after the worker crashed.
"""

from __future__ import annotations

import json
import os
import queue as _queue
import socket
import socketserver
import threading
import time
from multiprocessing import resource_tracker, shared_memory
from typing import Any, Optional

from dlrover_tpu.common.constants import Defaults, EnvKey
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.rpc import recv_frame, send_frame

logger = get_logger(__name__)


def _socket_dir() -> str:
    d = os.environ.get(
        EnvKey.IPC_DIR, os.path.join("/tmp", Defaults.SHM_PREFIX + "_ipc")
    )
    os.makedirs(d, exist_ok=True)
    return d


def _socket_path(name: str) -> str:
    return os.path.join(_socket_dir(), f"{name}.sock")


class _LocalServer:
    """Unix-socket server hosting one shared primitive in the owner process."""

    def __init__(self, name: str, handler):
        path = _socket_path(name)
        if os.path.exists(path):
            os.unlink(path)
        outer_handler = handler

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                try:
                    while True:
                        raw = recv_frame(self.request)
                        req = json.loads(raw.decode("utf-8"))
                        resp = outer_handler(req)
                        send_frame(
                            self.request, json.dumps(resp).encode("utf-8")
                        )
                except (ConnectionError, OSError):
                    pass

        class _Server(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True

        self._server = _Server(path, _Handler)
        self._thread = threading.Thread(
            target=self._server.serve_forever, name=f"ipc-{name}", daemon=True
        )
        self._thread.start()
        self._path = path

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if os.path.exists(self._path):
            os.unlink(self._path)


class _LocalClient:
    def __init__(self, name: str, timeout: float = 60.0):
        self._path = _socket_path(name)
        self._timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _connect(self) -> socket.socket:
        if self._sock is None:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            sock.settimeout(self._timeout)
            sock.connect(self._path)
            self._sock = sock
        return self._sock

    def request(self, req: dict) -> dict:
        with self._lock:
            try:
                sock = self._connect()
                send_frame(sock, json.dumps(req).encode("utf-8"))
                return json.loads(recv_frame(sock).decode("utf-8"))
            except (ConnectionError, OSError):
                if self._sock is not None:
                    self._sock.close()
                    self._sock = None
                raise

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                self._sock.close()
                self._sock = None


class SharedLock:
    """A lock shared between the owner process and client processes."""

    def __init__(self, name: str, create: bool = False):
        self._name = f"lock_{name}"
        self._create = create
        if create:
            self._local = threading.Lock()
            self._server = _LocalServer(self._name, self._handle)
        else:
            self._client = _LocalClient(self._name)

    def _handle(self, req: dict) -> dict:
        op = req["op"]
        if op == "acquire":
            ok = self._local.acquire(blocking=req.get("blocking", True),
                                     timeout=req.get("timeout", -1))
            return {"ok": ok}
        if op == "release":
            try:
                self._local.release()
                return {"ok": True}
            except RuntimeError:
                return {"ok": False}
        if op == "locked":
            return {"ok": self._local.locked()}
        return {"ok": False, "error": f"bad op {op}"}

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if self._create:
            return self._local.acquire(blocking=blocking, timeout=timeout)
        return self._client.request(
            {"op": "acquire", "blocking": blocking, "timeout": timeout}
        )["ok"]

    def release(self) -> bool:
        if self._create:
            try:
                self._local.release()
                return True
            except RuntimeError:
                return False
        return self._client.request({"op": "release"})["ok"]

    def locked(self) -> bool:
        if self._create:
            return self._local.locked()
        return self._client.request({"op": "locked"})["ok"]

    def reset(self) -> None:
        """Force-release an orphaned hold (owner side only).

        A client that dies between acquire and release would otherwise pin
        the lock forever; the agent calls this when it restarts the worker.
        """
        if not self._create:
            raise RuntimeError("only the lock owner can reset it")
        if self._local.locked():
            try:
                self._local.release()
            except RuntimeError:
                pass

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def close(self) -> None:
        if self._create:
            self._server.stop()
        else:
            self._client.close()


class SharedQueue:
    """A FIFO queue shared between processes (JSON-serializable items)."""

    def __init__(self, name: str, create: bool = False, maxsize: int = 0):
        self._name = f"queue_{name}"
        self._create = create
        if create:
            self._local: _queue.Queue = _queue.Queue(maxsize)
            self._server = _LocalServer(self._name, self._handle)
        else:
            self._client = _LocalClient(self._name)

    def _handle(self, req: dict) -> dict:
        op = req["op"]
        try:
            if op == "put":
                self._local.put(
                    req["item"], timeout=req.get("timeout") or None
                )
                return {"ok": True}
            if op == "get":
                item = self._local.get(
                    block=req.get("block", True),
                    timeout=req.get("timeout") or None,
                )
                return {"ok": True, "item": item}
            if op == "qsize":
                return {"ok": True, "size": self._local.qsize()}
        except (_queue.Empty, _queue.Full) as e:
            return {"ok": False, "error": type(e).__name__}
        return {"ok": False, "error": f"bad op {op}"}

    def put(self, item: Any, timeout: float | None = None) -> None:
        if self._create:
            self._local.put(item, timeout=timeout)
        else:
            resp = self._client.request(
                {"op": "put", "item": item, "timeout": timeout}
            )
            if not resp["ok"]:
                raise _queue.Full()

    def get(self, block: bool = True, timeout: float | None = None) -> Any:
        if self._create:
            return self._local.get(block=block, timeout=timeout)
        resp = self._client.request(
            {"op": "get", "block": block, "timeout": timeout}
        )
        if not resp["ok"]:
            raise _queue.Empty()
        return resp["item"]

    def qsize(self) -> int:
        if self._create:
            return self._local.qsize()
        return self._client.request({"op": "qsize"})["size"]

    def empty(self) -> bool:
        return self.qsize() == 0

    def close(self) -> None:
        if self._create:
            self._server.stop()
        else:
            self._client.close()


class SharedDict:
    """A dict shared between processes (JSON-serializable values).

    Clients write with ``set``/``update`` and read a full snapshot with
    ``get`` — matching how the reference shares checkpoint tensor metas
    between trainer and agent (common/multi_process.py:453).
    """

    def __init__(self, name: str, create: bool = False):
        self._name = f"dict_{name}"
        self._create = create
        if create:
            self._store: dict = {}
            self._mutex = threading.Lock()
            self._server = _LocalServer(self._name, self._handle)
        else:
            self._client = _LocalClient(self._name)

    def _handle(self, req: dict) -> dict:
        op = req["op"]
        with self._mutex:
            if op == "set":
                self._store[req["key"]] = req["value"]
                return {"ok": True}
            if op == "update":
                self._store.update(req["items"])
                return {"ok": True}
            if op == "get":
                return {"ok": True, "value": dict(self._store)}
            if op == "pop":
                return {"ok": True, "value": self._store.pop(req["key"], None)}
        return {"ok": False, "error": f"bad op {op}"}

    def set(self, key: str, value: Any) -> None:
        if self._create:
            with self._mutex:
                self._store[key] = value
        else:
            self._client.request({"op": "set", "key": key, "value": value})

    def update(self, items: dict) -> None:
        if self._create:
            with self._mutex:
                self._store.update(items)
        else:
            self._client.request({"op": "update", "items": items})

    def get(self) -> dict:
        if self._create:
            with self._mutex:
                return dict(self._store)
        return self._client.request({"op": "get"})["value"]

    def pop(self, key: str) -> Any:
        if self._create:
            with self._mutex:
                return self._store.pop(key, None)
        return self._client.request({"op": "pop", "key": key})["value"]

    def close(self) -> None:
        if self._create:
            self._server.stop()
        else:
            self._client.close()


def _untrack(shm: shared_memory.SharedMemory) -> None:
    """Detach a segment from the resource tracker.

    Without this, whichever process merely *opened* the segment unlinks it at
    exit, destroying the snapshot the agent still needs (the problem the
    reference solves by patching ``_make_filename``).
    """
    try:
        resource_tracker.unregister("/" + shm.name, "shared_memory")
    except Exception:  # noqa: BLE001 - tracker internals vary by version
        pass


class SharedMemoryArena:
    """Named POSIX shared memory that survives process death.

    ``open_or_create`` grows the segment if an existing one is too small.
    """

    def __init__(self, name: str, shm: shared_memory.SharedMemory):
        self.name = name
        self._shm = shm

    @property
    def buf(self) -> memoryview:
        return self._shm.buf

    @property
    def size(self) -> int:
        return self._shm.size

    @classmethod
    def open_or_create(cls, name: str, size: int) -> "SharedMemoryArena":
        full = f"{Defaults.SHM_PREFIX}_{name}"
        try:
            shm = shared_memory.SharedMemory(name=full, create=False)
            if shm.size < size:
                shm.unlink()
                shm.close()
                shm = shared_memory.SharedMemory(
                    name=full, create=True, size=size
                )
        except FileNotFoundError:
            shm = shared_memory.SharedMemory(name=full, create=True, size=size)
        _untrack(shm)
        return cls(full, shm)

    @classmethod
    def open(cls, name: str) -> Optional["SharedMemoryArena"]:
        full = f"{Defaults.SHM_PREFIX}_{name}"
        try:
            shm = shared_memory.SharedMemory(name=full, create=False)
        except FileNotFoundError:
            return None
        _untrack(shm)
        return cls(full, shm)

    def close(self) -> None:
        self._shm.close()

    def unlink(self) -> None:
        try:
            self._shm.unlink()
        except FileNotFoundError:
            pass


def wait_for_path(path: str, timeout: float = 30.0) -> bool:
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.05)
    return False


def client_socket_ready(name: str) -> bool:
    return os.path.exists(_socket_path(f"{name}"))
