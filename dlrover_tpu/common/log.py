"""Structured logging for all framework processes.

Reference analog: dlrover/python/common/log.py.
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = (
    "[%(asctime)s] [%(levelname)s] "
    "[%(name)s:%(lineno)d] %(message)s"
)


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(handler)
        logger.setLevel(os.environ.get("DLROVER_TPU_LOG_LEVEL", "INFO"))
        logger.propagate = False
    return logger
