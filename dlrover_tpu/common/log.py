"""Structured logging for all framework processes.

Reference analog: dlrover/python/common/log.py. With
``DLROVER_TPU_LOG_JSON=1`` records render as one JSON object per line
carrying ``node_id`` and ``trace_id`` (injected by a ``logging.Filter``
from the agent/master environment), so logs join cleanly with the event
journal (telemetry/journal.py) on the same ids.
"""

from __future__ import annotations

import json
import logging
import os
import sys
import time

from dlrover_tpu.common.constants import EnvKey

_FORMAT = (
    "[%(asctime)s] [%(levelname)s] "
    "[%(name)s:%(lineno)d] %(message)s"
)


class ContextFilter(logging.Filter):
    """Stamp every record with the process's node and trace identity.

    Read per-record, not cached: the trace id arrives via the rendezvous
    payload *after* most loggers are created.
    """

    def filter(self, record: logging.LogRecord) -> bool:
        record.node_id = os.environ.get(EnvKey.NODE_ID, "-")
        record.trace_id = os.environ.get(EnvKey.TRACE_ID, "-")
        return True


class JsonFormatter(logging.Formatter):
    def format(self, record: logging.LogRecord) -> str:
        entry = {
            "ts": round(record.created, 3),
            "time": time.strftime(
                "%Y-%m-%dT%H:%M:%S", time.localtime(record.created)
            ),
            "level": record.levelname,
            "logger": record.name,
            "line": record.lineno,
            "node_id": getattr(record, "node_id", "-"),
            "trace_id": getattr(record, "trace_id", "-"),
            "msg": record.getMessage(),
        }
        if record.exc_info:
            entry["exc"] = self.formatException(record.exc_info)
        return json.dumps(entry, ensure_ascii=False)


def _make_formatter() -> logging.Formatter:
    if os.environ.get(EnvKey.LOG_JSON, "") == "1":
        return JsonFormatter()
    return logging.Formatter(_FORMAT)


def get_logger(name: str) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(_make_formatter())
        handler.addFilter(ContextFilter())
        logger.addHandler(handler)
        logger.setLevel(os.environ.get(EnvKey.LOG_LEVEL, "INFO"))
        logger.propagate = False
    return logger
