"""Threaded TCP server base for the raw-array message protocol.

One accept loop + one thread per connection, each request a
``common/array_wire`` message inside a ``common/rpc`` length-prefixed
frame; handler errors travel back as structured ``err`` messages.
Shared by the sharded embedding service (embedding/service.py) and the
disaggregated RLHF serving worker (rl/serving_worker.py) so protocol
fixes (timeouts, stop semantics, error framing) land in exactly one
place.
"""

from __future__ import annotations

import socket
import threading

from dlrover_tpu.common.array_wire import decode_msg, encode_msg
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.rpc import recv_frame, send_frame

logger = get_logger(__name__)


class MsgError(RuntimeError):
    """Structured protocol error: ``code`` + message + optional meta,
    serialized as an ``err`` response and re-raised client-side."""

    def __init__(self, code: str, message: str, meta: dict | None = None):
        super().__init__(f"{code}: {message}")
        self.code = code
        self.meta = meta or {}


def call_msg(sock: socket.socket, op: str, meta: dict | None = None,
             arrays: dict | None = None,
             error_cls: type = MsgError) -> tuple[dict, dict]:
    """One request/response over an open socket; ``err`` responses are
    raised as ``error_cls(code, message, meta)``."""
    send_frame(sock, encode_msg(op, meta, arrays))
    rop, rmeta, rarrays = decode_msg(recv_frame(sock))
    if rop == "err":
        raise error_cls(rmeta.get("code", "error"),
                        rmeta.get("message", ""), rmeta)
    return rmeta, rarrays


class ArrayMsgServer:
    """Subclass and implement ``_handle(op, meta, arrays) -> bytes``
    (raise ``MsgError``/subclass for structured failures)."""

    #: error class whose instances are serialized with their code/meta
    error_cls: type = MsgError

    def __init__(self, host: str = "0.0.0.0", port: int = 0,
                 name: str = "msg-server"):
        self._stop = threading.Event()
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.5)
        # live accepted connections, closed on stop(): without this a
        # stopped server still answers one in-flight request per open
        # socket (the per-conn loop re-checks the stop event only after
        # a full serve iteration), so kill-based tests and drains would
        # see a half-dead server instead of a dead one
        self._conns: set[socket.socket] = set()
        self._conns_lock = threading.Lock()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True, name=name,
        )

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def start(self):
        self._accept_thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            self._serve_conn_inner(conn)
        finally:
            with self._conns_lock:
                self._conns.discard(conn)

    def _serve_conn_inner(self, conn: socket.socket) -> None:
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass  # already closed by a racing stop()
        with conn:
            while not self._stop.is_set():
                try:
                    op, meta, arrays = decode_msg(recv_frame(conn))
                except (ConnectionError, OSError, ValueError):
                    return
                try:
                    resp = self._handle(op, meta, arrays)
                except MsgError as e:
                    resp = encode_msg("err", {
                        "code": e.code, "message": str(e), **e.meta,
                    })
                except Exception as e:  # noqa: BLE001 - report to caller
                    logger.exception("op %s failed", op)
                    resp = encode_msg("err", {
                        "code": "internal",
                        "message": f"{type(e).__name__}: {e}",
                    })
                try:
                    send_frame(conn, resp)
                except (ConnectionError, OSError):
                    return

    def _handle(self, op: str, meta: dict, arrays: dict) -> bytes:
        raise NotImplementedError
