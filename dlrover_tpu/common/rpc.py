"""Length-prefixed TCP RPC for the control plane.

Reference analog: the master gRPC service with generic ``get``/``report``
methods (dlrover/proto/elastic_training.proto:28, master/servicer.py:62).
Here a request is one typed message (common/serde.py) and the response is
another; dispatch happens on the message type. The control plane is cold-path
(heartbeats, rendezvous, shard requests), so a simple threaded TCP server is
plenty and keeps the framework dependency-free.
"""

from __future__ import annotations

import json
import random
import socket
import socketserver
import struct
import threading
import time
import uuid
from collections import OrderedDict
from typing import Any, Callable, Optional

from dlrover_tpu import chaos
from dlrover_tpu.chaos import partition as net_partition
from dlrover_tpu.common import serde
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.journal import adopt_remote_ctx, current_ctx
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_retry_total = registry().counter(
    "dlrover_tpu_rpc_retry_total",
    "client rpc attempts retried after a transport error",
)
_deadline_total = registry().counter(
    "dlrover_tpu_rpc_retry_deadline_exceeded_total",
    "client rpc calls abandoned at the per-call deadline",
)

_LEN = struct.Struct(">I")
MAX_FRAME = 256 * 1024 * 1024


def backoff_jitter_s(base_s: float, max_s: float, attempt: int,
                     rng=random) -> float:
    """Full-jitter exponential backoff: uniform over [0, cap) where cap
    doubles from ``base_s`` up to ``max_s``. Full jitter (not equal
    jitter) on purpose: a 1k-agent herd re-dialing after a partition
    heal all sits at the same attempt count, and equal jitter packs the
    whole herd into the top half of the window — the fleetsim reconnect
    burst measures the difference (DESIGN.md §30). Shared with the
    simulator so the modeled herd uses the production formula."""
    cap = min(max_s, base_s * (2 ** max(0, attempt - 1)))
    return rng.uniform(0.0, cap)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def send_frame(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def recv_frame(sock: socket.socket) -> bytes:
    (length,) = _LEN.unpack(_recv_exact(sock, _LEN.size))
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    return _recv_exact(sock, length)


@serde.register_message
class RpcError:
    error: str = ""


class RpcServer:
    """Threaded TCP server dispatching typed messages to a handler.

    ``handler(msg) -> response message or None``.
    """

    def __init__(self, handler: Callable[[Any], Any], host: str = "0.0.0.0",
                 port: int = 0,
                 epoch_fn: Callable[[], int] | None = None):
        self._handler = handler
        # epoch fence (DESIGN.md §26): when set, every response envelope
        # is stamped with the master's current epoch (`"me"` key, the
        # response-side mirror of the request's `"rid"`), so a client
        # detects a master restart on its very next RPC of ANY type —
        # not just the messages that carry an explicit epoch field.
        self._epoch_fn = epoch_fn
        # Replay cache: request-id -> encoded response. A client retry after
        # a lost *response* must not re-apply non-idempotent messages
        # (TaskResult completions, KV barrier increments). Large responses
        # (shard tasks with record indices) are not cached — re-fetching a
        # read is safe; only small non-idempotent acks need replay cover.
        self._replay: OrderedDict[str, bytes] = OrderedDict()
        self._replay_bytes = 0
        self._replay_lock = threading.Lock()
        outer = self

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self) -> None:
                sock = self.request
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                try:
                    while True:
                        try:
                            raw = recv_frame(sock)
                        except ValueError as e:
                            # framing violation (oversized declared
                            # length): answer with a structured error
                            # and drop — without this catch the trace
                            # lands in socketserver's handle_error and
                            # a hostile peer can spam the master's
                            # stderr with raw tracebacks
                            try:
                                send_frame(sock, serde.encode(
                                    RpcError(error=f"bad frame: {e}")
                                ))
                            except (ConnectionError, OSError):
                                pass
                            return
                        resp = outer._dispatch(raw)
                        send_frame(sock, resp)
                except (ConnectionError, OSError):
                    pass

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def _dispatch(self, raw: bytes) -> bytes:
        try:
            obj = json.loads(raw.decode("utf-8"))
            rid = obj.pop("rid", None)
            # span context (DESIGN.md §27): the caller's trace:span,
            # riding beside rid/me — adopt it for the handler so every
            # journal emission inside is a child of the caller's span
            sctx = obj.pop("sctx", "")
            if rid is not None:
                with self._replay_lock:
                    cached = self._replay.get(rid)
                if cached is not None:
                    return cached
            msg = serde.decode_obj(obj)
            with adopt_remote_ctx(sctx):
                resp = self._handler(msg)
            if resp is None:
                resp = RpcError()
            out = serde.encode_obj(resp)
            if self._epoch_fn is not None:
                out["me"] = int(self._epoch_fn())
            encoded = json.dumps(out).encode("utf-8")
            if rid is not None and len(encoded) <= 64 * 1024:
                with self._replay_lock:
                    self._replay[rid] = encoded
                    self._replay_bytes += len(encoded)
                    while (
                        len(self._replay) > 4096
                        or self._replay_bytes > 64 * 1024 * 1024
                    ):
                        _, old = self._replay.popitem(last=False)
                        self._replay_bytes -= len(old)
            return encoded
        except Exception as e:  # noqa: BLE001 - report errors to the caller
            logger.exception("rpc dispatch failed")
            return serde.encode(RpcError(error=f"{type(e).__name__}: {e}"))

    def start(self) -> None:
        # 50 ms shutdown poll (socketserver default: 500 ms): stop()
        # blocks until serve_forever notices, and every master/test
        # teardown pays it — at ~0.5 s per server it was a measurable
        # slice of the tier-1 envelope
        self._thread = threading.Thread(
            target=lambda: self._server.serve_forever(poll_interval=0.05),
            name="rpc-server", daemon=True,
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()


class RpcClient:
    """Persistent-connection client with reconnect + jittered-backoff retry.

    Retry policy: exponential backoff from ``backoff_base_s`` doubling
    up to ``backoff_max_s``, with FULL jitter (uniform over the whole
    window, ``backoff_jitter_s``) so N agents reconnecting after a
    master restart or partition heal spread out instead of thundering
    in lockstep — equal jitter packed the herd into the top half of
    each window and the fleetsim reconnect-burst p99 showed it
    clustering (§30). ``deadline_s`` bounds one ``call`` end to end
    regardless of how many attempts fit; both abandonment paths are
    counted (``dlrover_tpu_rpc_retry_total`` /
    ``..._retry_deadline_exceeded_total``).
    """

    def __init__(self, addr: str, timeout: float = 30.0, retries: int = 8,
                 retry_interval: float | None = None,
                 backoff_base_s: float = 0.1, backoff_max_s: float = 3.0,
                 deadline_s: float = 60.0,
                 link: tuple[str, str] | None = None):
        host, _, port = addr.rpartition(":")
        self._host = host or "127.0.0.1"
        self._port = int(port)
        # which control-plane edge this client crosses, for the
        # net_partition chaos domain (§30): (caller tier, callee tier).
        # Owners that know better (sub-master upstream, rack-attached
        # agents, the gateway) override the default.
        self.link = tuple(link) if link else ("agent", "root")
        self._timeout = timeout
        self._retries = max(1, retries)
        if retry_interval is not None:
            # legacy fixed-interval knob: honored as the backoff ceiling
            backoff_max_s = max(backoff_base_s, retry_interval)
        self._backoff_base_s = backoff_base_s
        self._backoff_max_s = backoff_max_s
        self._deadline_s = deadline_s
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()
        # epoch fence (§26): invoked with the epoch stamped on each
        # response envelope (outside the socket lock); the owner —
        # MasterClient — decides whether it changed and reconciles.
        self.on_epoch: Optional[Callable[[int], None]] = None

    @property
    def addr(self) -> str:
        return f"{self._host}:{self._port}"

    def clone(self, addr: str) -> "RpcClient":
        """A fresh client to ``addr`` with this one's retry/deadline
        configuration — the re-dial path after a master restart moved
        the port (the epoch hook is NOT copied; the owner rewires it)."""
        return RpcClient(
            addr, timeout=self._timeout, retries=self._retries,
            backoff_base_s=self._backoff_base_s,
            backoff_max_s=self._backoff_max_s,
            deadline_s=self._deadline_s,
            link=self.link,
        )

    def _connect(self) -> socket.socket:
        if self._sock is None:
            sock = socket.create_connection(
                (self._host, self._port), timeout=self._timeout
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = sock
        return self._sock

    def close(self) -> None:
        with self._lock:
            if self._sock is not None:
                try:
                    self._sock.close()
                finally:
                    self._sock = None

    def call(self, msg: Any) -> Any:
        """Send one message, wait for the typed response.

        Raises RuntimeError if the server reported an error, ConnectionError
        if the master is unreachable after retries or past the per-call
        deadline.
        """
        env = serde.encode_obj(msg)
        env["rid"] = uuid.uuid4().hex
        sctx = current_ctx()
        if sctx:
            env["sctx"] = sctx
        payload = json.dumps(env).encode("utf-8")
        deadline = time.monotonic() + self._deadline_s
        last_err: Exception | None = None
        attempt = 0
        while True:
            try:
                if chaos.ENABLED:
                    # request direction of the link: an open partition
                    # drops the request before it is sent
                    if net_partition.check(
                        self.link[0], self.link[1],
                        msg=type(msg).__name__, addr=self.addr,
                    ) is not None:
                        raise ConnectionError(
                            f"chaos: net partition open "
                            f"({self.link[0]}->{self.link[1]})"
                        )
                    fault = chaos.fire(
                        "rpc_call", msg=type(msg).__name__,
                        addr=self.addr, attempt=attempt,
                    )
                    if fault is not None:
                        self._apply_rpc_fault(fault)
                with self._lock:
                    sock = self._connect()
                    send_frame(sock, payload)
                    raw = recv_frame(sock)
                if chaos.ENABLED:
                    # response direction: an asymmetric split can lose
                    # the ACK of a request the server DID apply — the
                    # redelivery + rid-dedup machinery must absorb the
                    # replay (DESIGN.md §30)
                    if net_partition.check(
                        self.link[1], self.link[0],
                        msg=type(msg).__name__, addr=self.addr,
                    ) is not None:
                        raise ConnectionError(
                            f"chaos: net partition open "
                            f"({self.link[1]}->{self.link[0]}, "
                            f"response lost)"
                        )
                obj = json.loads(raw.decode("utf-8"))
                epoch = obj.pop("me", None)
                resp = serde.decode_obj(obj)
                if epoch is not None and self.on_epoch is not None:
                    # outside the lock: the hook may issue its own
                    # calls through this client (reconcile)
                    self.on_epoch(int(epoch))
                if isinstance(resp, RpcError) and resp.error:
                    raise RuntimeError(f"rpc error: {resp.error}")
                return resp
            except (ConnectionError, OSError, TimeoutError) as e:
                last_err = e
                self.close()
                attempt += 1
                now = time.monotonic()
                if now >= deadline:
                    _deadline_total.inc()
                    raise ConnectionError(
                        f"rpc to {self.addr} exceeded its "
                        f"{self._deadline_s:.0f}s deadline after {attempt} "
                        f"tries: {last_err}"
                    ) from e
                if attempt >= self._retries:
                    raise ConnectionError(
                        f"rpc to {self.addr} failed after {attempt} "
                        f"tries: {last_err}"
                    ) from e
                _retry_total.inc()
                sleep_s = backoff_jitter_s(
                    self._backoff_base_s, self._backoff_max_s, attempt
                )
                time.sleep(max(0.0, min(sleep_s, deadline - now)))

    def _apply_rpc_fault(self, fault: chaos.Fault) -> None:
        """Injected transport faults (chaos plan ``rpc_call`` point):
        ``delay`` (sleep), ``drop`` (request never sent), ``reset``
        (connection torn down mid-call), ``garble`` (a corrupt frame —
        oversized declared length — reaches the server, exercising its
        framing guard). All but ``delay`` surface as the transport
        errors the retry loop already handles."""
        if fault.action == "delay":
            time.sleep(float(fault.args.get("s", 0.05)))
        elif fault.action == "drop":
            raise ConnectionError("chaos: rpc request dropped")
        elif fault.action == "reset":
            self.close()
            raise ConnectionResetError("chaos: connection reset")
        elif fault.action == "garble":
            with self._lock:
                sock = self._connect()
                sock.sendall(_LEN.pack(MAX_FRAME + 1) + b"\xde\xad\xbe\xef")
            self.close()
            raise ConnectionError("chaos: garbled frame sent")
        else:
            logger.warning("chaos: unknown rpc_call action %r", fault.action)


def find_free_port(host: str = "127.0.0.1") -> int:
    with socket.socket() as s:
        s.bind((host, 0))
        return s.getsockname()[1]
