"""Typed, pickle-free message serialization.

The reference ships ~40 pickled Python dataclasses over two generic gRPC
methods (dlrover/python/common/grpc.py, master/servicer.py:88-130). Pickle
over the wire is unsafe and version-brittle (SURVEY.md §7 "Master protocol"),
so here every message is a registered dataclass encoded as JSON with a type
tag. Only registered types can be decoded, and field reconstruction goes
through the dataclass constructor with type-directed coercion (enums, nested
dataclasses, tuples) — never arbitrary object construction.
"""

from __future__ import annotations

import dataclasses
import enum
import json
import typing
from typing import Any, Type, TypeVar

T = TypeVar("T")

_REGISTRY: dict[str, type] = {}


def register_message(cls: Type[T]) -> Type[T]:
    """Class decorator: make a dataclass wire-encodable."""
    if not dataclasses.is_dataclass(cls):
        cls = dataclasses.dataclass(cls)
    _REGISTRY[cls.__name__] = cls
    return cls


def _to_jsonable(value: Any) -> Any:
    if isinstance(value, enum.Enum):
        return value.value
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        out = {}
        for f in dataclasses.fields(value):
            out[f.name] = _to_jsonable(getattr(value, f.name))
        if type(value).__name__ in _REGISTRY:
            out["__type__"] = type(value).__name__
        return out
    if isinstance(value, dict):
        return {str(k): _to_jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_to_jsonable(v) for v in value]
    if isinstance(value, bytes):
        return {"__bytes__": value.hex()}
    return value


def _coerce(hint: Any, value: Any) -> Any:
    """Coerce a decoded JSON value to the annotated type."""
    if value is None:
        return None
    origin = typing.get_origin(hint)
    if origin is typing.Union or str(origin) == "types.UnionType":
        for arg in typing.get_args(hint):
            if arg is type(None):
                continue
            try:
                return _coerce(arg, value)
            except (TypeError, ValueError, KeyError):
                continue
        return value
    if isinstance(hint, type) and issubclass(hint, enum.Enum):
        return hint(value)
    if dataclasses.is_dataclass(hint) and isinstance(value, dict):
        return _from_fields(hint, value)
    if origin in (list, tuple):
        args = typing.get_args(hint)
        elem = args[0] if args else Any
        seq = [_coerce(elem, v) for v in value]
        return tuple(seq) if origin is tuple else seq
    if origin is dict:
        args = typing.get_args(hint)
        vt = args[1] if len(args) == 2 else Any
        kt = args[0] if len(args) == 2 else str
        def _key(k: str) -> Any:
            return int(k) if kt is int else k
        return {_key(k): _coerce(vt, v) for k, v in value.items()}
    if isinstance(value, dict) and "__bytes__" in value:
        return bytes.fromhex(value["__bytes__"])
    return value


def _from_fields(cls: type, data: dict) -> Any:
    hints = typing.get_type_hints(cls)
    kwargs = {}
    for f in dataclasses.fields(cls):
        if f.name in data:
            kwargs[f.name] = _coerce(hints.get(f.name, Any), data[f.name])
    return cls(**kwargs)


def encode_obj(msg: Any) -> dict:
    name = type(msg).__name__
    if name not in _REGISTRY:
        raise TypeError(f"message type {name} is not registered")
    payload = _to_jsonable(msg)
    payload.pop("__type__", None)
    return {"type": name, "data": payload}


def encode(msg: Any) -> bytes:
    return json.dumps(encode_obj(msg)).encode("utf-8")


def decode(raw: bytes) -> Any:
    return decode_obj(json.loads(raw.decode("utf-8")))


def decode_obj(obj: dict) -> Any:
    name = obj.get("type")
    cls = _REGISTRY.get(name)
    if cls is None:
        raise TypeError(f"unknown message type {name!r}")
    return _from_fields(cls, obj.get("data", {}))
