"""Framework-wide constants.

Mirrors the capability surface of the reference constants module
(dlrover/python/common/constants.py) with TPU-native vocabulary: node types
are TPU hosts rather than PS/worker pods, accelerators are TPU chips, and the
distribution strategies are mesh-axis based rather than PS/AllReduce based.
"""

from __future__ import annotations

import enum
import os


class PlatformType(str, enum.Enum):
    LOCAL = "local"
    KUBERNETES = "k8s"
    RAY = "ray"


class NodeType(str, enum.Enum):
    MASTER = "master"
    HOST = "host"  # a TPU host VM (runs one agent + one training process)
    CPU_WORKER = "cpu_worker"  # auxiliary CPU pod (data preprocessing)


class NodeStatus(str, enum.Enum):
    INITIAL = "initial"
    PENDING = "pending"
    RUNNING = "running"
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    DELETED = "deleted"
    UNKNOWN = "unknown"

    @classmethod
    def terminal(cls) -> set["NodeStatus"]:
        return {cls.SUCCEEDED, cls.FAILED, cls.DELETED}


class NodeEventType(str, enum.Enum):
    ADDED = "added"
    MODIFIED = "modified"
    DELETED = "deleted"


class NodeExitReason(str, enum.Enum):
    SUCCEEDED = "succeeded"
    KILLED = "killed"
    OOM = "oom"
    FATAL_ERROR = "fatal_error"
    HARDWARE_ERROR = "hardware_error"
    PREEMPTED = "preempted"
    UNKNOWN = "unknown"


class JobExitReason(str, enum.Enum):
    SUCCEEDED = "succeeded"
    NODE_OOM = "node_oom"
    NODE_ERROR = "node_error"
    RDZV_TIMEOUT = "rdzv_timeout"
    HANG_ERROR = "hang_error"
    UNCOMPLETED_TIMEOUT = "uncompleted_timeout"
    EARLY_STOP = "early_stop"
    UNKNOWN = "unknown"


class RendezvousName(str, enum.Enum):
    TRAINING = "training"
    NETWORK_CHECK = "network-check"


class TaskType(str, enum.Enum):
    TRAINING = "training"
    EVALUATION = "evaluation"
    PREDICTION = "prediction"


class CheckpointStorageType(str, enum.Enum):
    MEMORY = "memory"
    DISK = "disk"


class ParallelAxis(str, enum.Enum):
    """Named mesh axes for the parallel layer.

    The reference builds torch process groups per named dim
    (atorch/atorch/distributed/distributed.py:321 create_parallel_group);
    here axes are dims of one ``jax.sharding.Mesh``.
    """

    DATA = "data"
    FSDP = "fsdp"
    TENSOR = "tensor"
    SEQUENCE = "sequence"
    EXPERT = "expert"
    PIPELINE = "pipeline"


class TrainingExceptionLevel(str, enum.Enum):
    PROCESS_ERROR = "process_error"
    NODE_ERROR = "node_error"
    RDZV_ERROR = "rdzv_error"
    WARNING = "warning"
    INFO = "info"


# Agent <-> training-process environment variable contract.
class EnvKey:
    JOB_NAME = "DLROVER_TPU_JOB_NAME"
    MASTER_ADDR = "DLROVER_TPU_MASTER_ADDR"
    NODE_ID = "DLROVER_TPU_NODE_ID"
    NODE_RANK = "DLROVER_TPU_NODE_RANK"
    NODE_NUM = "DLROVER_TPU_NODE_NUM"
    COORDINATOR = "DLROVER_TPU_COORDINATOR"
    RESTART_COUNT = "DLROVER_TPU_RESTART_COUNT"
    PARAL_CONFIG_PATH = "DLROVER_TPU_PARAL_CONFIG"
    CKPT_META_DIR = "DLROVER_TPU_CKPT_META_DIR"
    MOCK_ERR_RANK = "DLROVER_TPU_MOCK_ERR_RANK"
    DEVICE_COUNT_OVERRIDE = "DLROVER_TPU_DEVICE_COUNT"
    COMPILE_CACHE_DIR = "DLROVER_TPU_COMPILE_CACHE"
    # escape hatch: pin the ONE compile-cache directory every
    # incarnation, parked standby, and serving replica on this node
    # shares (XLA persistent cache + serialized AOT executables). The
    # default derives from the job name for the same sharing property;
    # this exists for operators who must place the cache explicitly
    # (job-shared NFS, a ramdisk, a pre-warmed image path).
    COMPILE_CACHE_SHARED_DIR = "DLROVER_TPU_COMPILE_CACHE_DIR"
    # coordination-service join timeout (seconds) for
    # jax.distributed.initialize — the launcher scales it with the node
    # count (reference analog: auto_configure_params' comm timeouts,
    # dlrover/python/elastic_agent/torch/training.py:143)
    INIT_TIMEOUT = "DLROVER_TPU_INIT_TIMEOUT"
    ACCELERATOR = "DLROVER_TPU_ACCELERATOR"
    # telemetry (dlrover_tpu/telemetry/): exposition port (unset = fully
    # off), event-journal directory (unset = no journal), the job trace
    # id the master mints, and JSON log format
    METRICS_PORT = "DLROVER_TPU_METRICS_PORT"
    JOURNAL_DIR = "DLROVER_TPU_JOURNAL_DIR"
    TRACE_ID = "DLROVER_TPU_TRACE_ID"
    LOG_JSON = "DLROVER_TPU_LOG_JSON"
    # causal trace fabric (DESIGN.md §27): head-sampling rate for
    # per-request serving traces (incidents/control-plane are always
    # sampled), the seed that makes span ids deterministic under the
    # chaos/fleetsim replay discipline, and the spawn-time span context
    # an agent hands its children so trainer-side recovery spans attach
    # under the incident that respawned them
    TRACE_SAMPLE = "DLROVER_TPU_TRACE_SAMPLE"
    TRACE_SEED = "DLROVER_TPU_TRACE_SEED"
    SPAN_CTX = "DLROVER_TPU_SPAN_CTX"
    # span-id namespace: disambiguates co-located processes that would
    # otherwise share a deterministic id stream under TRACE_SEED (the
    # standalone master and the agent both run with no NODE_ID)
    SPAN_NS = "DLROVER_TPU_SPAN_NS"
    # flight recorder (telemetry/bundle.py, telemetry/journal.py): where
    # crash/hang debug bundles land (default <journal dir>/bundles), the
    # journal size cap in MB (0/unset = unbounded), and the "1"-default
    # switch for automatic bundles on hang/crash verdicts
    BUNDLE_DIR = "DLROVER_TPU_BUNDLE_DIR"
    JOURNAL_MAX_MB = "DLROVER_TPU_JOURNAL_MAX_MB"
    BUNDLES = "DLROVER_TPU_BUNDLES"
    # chaos harness (dlrover_tpu/chaos/): a JSON fault plan (file path
    # or inline JSON). Unset = injection compiled out to one boolean
    # check at every point (read once, at chaos package import).
    CHAOS = "DLROVER_TPU_CHAOS"
    # warm recovery (agent/standby.py): "0" disables the pre-spawned
    # standby trainer the agent promotes on worker death; STANDBY_FILE
    # is the internal handshake path the agent hands a standby child
    STANDBY = "DLROVER_TPU_STANDBY"
    STANDBY_FILE = "DLROVER_TPU_STANDBY_FILE"
    # "auto" lets the master's Young-Daly tuner
    # (checkpoint/interval_tuner.py) drive the shm snapshot cadence via
    # the paral-config push; unset/other keeps the trainer's CLI value
    SNAPSHOT_INTERVAL = "DLROVER_TPU_SNAPSHOT_INTERVAL"
    # delta-compressed metrics-snapshot pushes
    # (telemetry/snapshot_delta.py): every Kth push is a full snapshot,
    # the ones between suppress unchanged families; 0/1 = always full
    SNAPSHOT_FULL_EVERY = "DLROVER_TPU_SNAPSHOT_FULL_EVERY"
    # platform/backend selection (run.py --platform mirror; "cpu"
    # forces JAX_PLATFORMS=cpu in children)
    PLATFORM = "DLROVER_TPU_PLATFORM"
    # directory for cross-process handshake files (standby promotion
    # payloads, paral-config mirror, chaos scenario legs); default
    # tempdir — co-hosted jobs override to avoid collisions
    IPC_DIR = "DLROVER_TPU_IPC_DIR"
    SHM_PREFIX = "DLROVER_TPU_SHM_PREFIX"
    # serialized-AOT-executable cache ("0" disables; DESIGN.md §17) and
    # the example's force-switch for the fallback-topology precompiler
    AOT_CACHE = "DLROVER_TPU_AOT_CACHE"
    FALLBACK_AOT = "DLROVER_TPU_FALLBACK_AOT"
    # efficiency observatory (DESIGN.md §18): per-step phase split
    # ("0" restores fire-and-forget dispatch) and the journal cadence
    # of metrics_sample/step_phase points
    STEP_PHASES = "DLROVER_TPU_STEP_PHASES"
    EFFICIENCY_JOURNAL_EVERY = "DLROVER_TPU_EFFICIENCY_JOURNAL_EVERY"
    # buddy-replication of shm snapshots (checkpoint/buddy.py): "0"
    # disables, interval between pushes, per-push byte cap
    BUDDY = "DLROVER_TPU_BUDDY"
    BUDDY_INTERVAL = "DLROVER_TPU_BUDDY_INTERVAL"
    BUDDY_MAX_BYTES = "DLROVER_TPU_BUDDY_MAX_BYTES"
    # network-check probe budget (agent/node_check.py, read at import)
    # and the probe child's rank assignment
    PROBE_TIMEOUT = "DLROVER_TPU_PROBE_TIMEOUT"
    GLOBAL_RANK = "DLROVER_TPU_GLOBAL_RANK"
    LOG_LEVEL = "DLROVER_TPU_LOG_LEVEL"
    # preemption/maintenance-notice sources (agent/preemption.py)
    PREEMPTION_FILE = "DLROVER_TPU_PREEMPTION_FILE"
    PREEMPTION_URL = "DLROVER_TPU_PREEMPTION_URL"
    # per-host parallel checkpoint persist (DESIGN.md §20): how many
    # DP replicas of each shard are written to storage (2 enables
    # per-shard twin rollback), the concurrent chunk writers per host,
    # and the chunk size for the chunked object-store writes
    CKPT_PERSIST_REPLICAS = "DLROVER_TPU_CKPT_PERSIST_REPLICAS"
    CKPT_PERSIST_WORKERS = "DLROVER_TPU_CKPT_PERSIST_WORKERS"
    CKPT_PERSIST_CHUNK_MB = "DLROVER_TPU_CKPT_PERSIST_CHUNK_MB"
    # strategy autopilot (DESIGN.md §24): the stated per-device memory
    # envelope for backends whose runtime reports none (CPU/tunneled —
    # the planner's feasibility filter), and the per-job bound on
    # closed-loop retunes the master-side controller may apply
    DEVICE_HBM_BYTES = "DLROVER_TPU_DEVICE_HBM_BYTES"
    AUTOPILOT_MAX_RETUNES = "DLROVER_TPU_AUTOPILOT_MAX_RETUNES"
    # elastic embedding fabric (DESIGN.md §25): the async-apply
    # staleness bound (steps of un-flushed gradient the trainer may run
    # ahead; back-pressures the step past it), the checkpoint replica
    # count (2 writes each shard block to its ring successor too,
    # enabling per-shard twin rollback at restore), the background
    # flusher's idle poll interval, and the bounded send-queue depth
    EMBEDDING_MAX_STALENESS = "DLROVER_TPU_EMBEDDING_MAX_STALENESS"
    EMBEDDING_REPLICAS = "DLROVER_TPU_EMBEDDING_REPLICAS"
    EMBEDDING_FLUSH_MS = "DLROVER_TPU_EMBEDDING_FLUSH_MS"
    EMBEDDING_QUEUE = "DLROVER_TPU_EMBEDDING_QUEUE"
    # master crash-failover (DESIGN.md §26): where the master persists
    # its full-state snapshot (unset = snapshots off), the atomic port
    # file agents re-resolve a restarted master's address from, the
    # agent-side redelivery queue bound for unacked one-way reports,
    # and the rate limit on "master unreachable" warnings while degraded
    MASTER_STATE_DIR = "DLROVER_TPU_MASTER_STATE_DIR"
    MASTER_PORT_FILE = "DLROVER_TPU_MASTER_PORT_FILE"
    REDELIVERY_QUEUE = "DLROVER_TPU_REDELIVERY_QUEUE"
    DEGRADED_WARN_S = "DLROVER_TPU_DEGRADED_WARN_S"
    # hierarchical control plane (DESIGN.md §28): the rack this agent
    # belongs to (assigns it to a rack sub-master), the sub-master's
    # own atomic port file (target-keyed re-dial, same mechanism as the
    # root's), the byte bound on the rack-local compile-cache mirror,
    # and the sub-master's merged-upstream-push cadence
    RACK_ID = "DLROVER_TPU_RACK_ID"
    RACK_PORT_FILE = "DLROVER_TPU_RACK_PORT_FILE"
    RACK_CACHE_MB = "DLROVER_TPU_RACK_CACHE_MB"
    RACK_FLUSH_S = "DLROVER_TPU_RACK_FLUSH_S"
    RACK_WORLD_CHUNK = "DLROVER_TPU_RACK_WORLD_CHUNK"
    RACK_MERGE_MAX = "DLROVER_TPU_RACK_MERGE_MAX"
    # partition tolerance (DESIGN.md §30): the rack lease the merge
    # tick refreshes (expiry fails the sub-master closed and lets the
    # root expire the rack), the jittered re-probe cadence of a
    # fallback-pinned agent's rack target, and the degraded-mode bound
    # after which mirrored config is too stale to act on
    RACK_LEASE_S = "DLROVER_TPU_RACK_LEASE_S"
    RACK_RETRY_S = "DLROVER_TPU_RACK_RETRY_S"
    LINK_STALE_S = "DLROVER_TPU_LINK_STALE_S"
    # serving memory observatory (DESIGN.md §29): the measure-only
    # off-switch, the kv_pool sample cadence (decode steps), and the
    # n-gram order of the draft-acceptance shadow predictor
    SERVING_OBSERVATORY = "DLROVER_TPU_SERVING_OBSERVATORY"
    OBSERVATORY_SAMPLE_EVERY = "DLROVER_TPU_OBSERVATORY_SAMPLE_EVERY"
    SHADOW_ORDER = "DLROVER_TPU_SHADOW_ORDER"
    # serving raw speed (DESIGN.md §31): copy-on-write page sharing in
    # the paged KV pool, and the max self-drafted speculative-decode
    # verify depth (0 = plain decode)
    KV_COW = "DLROVER_TPU_KV_COW"
    SPEC_DEPTH = "DLROVER_TPU_SPEC_DEPTH"


class Defaults:
    MASTER_PORT = 0  # 0 -> pick a free port
    HEARTBEAT_INTERVAL_S = 15.0
    HEARTBEAT_DEAD_WINDOW_S = 300.0
    RDZV_WAIT_TIMEOUT_S = 600.0
    RDZV_POLL_INTERVAL_S = 0.2
    MONITOR_INTERVAL_S = 1.0
    MAX_RESTARTS = 3
    SPEED_WINDOW_S = 6.0
    RPC_TIMEOUT_S = 30.0
    # overridable so parallel test runs / co-hosted jobs can't collide on
    # POSIX shm names (children inherit the env, so agent+trainer agree).
    # Import-time read by design (envspec marks it restart_required):
    # every shm name derives from it, so it must be frozen per process.
    SHM_PREFIX = os.environ.get(EnvKey.SHM_PREFIX, "dlrover_tpu")
