"""Node model: what the master knows about each TPU host.

Reference analog: ``Node``/``NodeResource`` in dlrover/python/common/node.py
(:149, :37). TPU-native differences: resources track TPU chips/topology
instead of GPU count, and one node == one host VM running a single JAX
process that owns all local chips (the torch reference runs one process per
GPU; see SURVEY.md §7 "Process model").
"""

from __future__ import annotations

import dataclasses
import time

from dlrover_tpu.common.constants import NodeExitReason, NodeStatus, NodeType


@dataclasses.dataclass
class NodeResource:
    cpu: float = 0.0
    memory_mb: int = 0
    tpu_chips: int = 0
    tpu_topology: str = ""  # e.g. "2x2x1"
    # runtime usage stats (reported by the agent resource monitor)
    used_cpu: float = 0.0
    used_memory_mb: int = 0
    used_hbm_mb: int = 0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "NodeResource":
        return cls(**d)


@dataclasses.dataclass
class Node:
    node_type: NodeType
    node_id: int
    rank: int = -1
    name: str = ""
    status: NodeStatus = NodeStatus.INITIAL
    addr: str = ""
    resource: NodeResource = dataclasses.field(default_factory=NodeResource)
    exit_reason: NodeExitReason = NodeExitReason.UNKNOWN
    # node-level relaunches (host replaced) — distinct from the agent's
    # in-place process restarts, which the agent reports via heartbeat
    relaunch_count: int = 0
    max_relaunch_count: int = 3
    process_restarts: int = 0
    create_time: float = dataclasses.field(default_factory=time.time)
    heartbeat_time: float = 0.0
    # topology hints for rank sorting (reference:
    # dlrover/python/master/elastic_training/net_topology.py:61)
    topology_key: str = ""
    # wall time a maintenance/preemption notice arrived (0 = none);
    # armed nodes get the master's short dead-window until the arm
    # expires (the node survived the event, e.g. a live migration)
    preempting_since: float = 0.0
    preempt_deadline_s: float = 0.0  # advertised time-to-kill (0 = unknown)

    def update_status(self, status: NodeStatus) -> None:
        self.status = status

    def is_alive(self, dead_window_s: float, now: float | None = None) -> bool:
        if self.heartbeat_time <= 0:
            return True  # never reported yet; grace period handled by caller
        now = time.time() if now is None else now
        return (now - self.heartbeat_time) < dead_window_s

    def should_relaunch(self, exit_reason: NodeExitReason) -> bool:
        """Relaunch policy (reference: dist_job_manager.py:561 _should_relaunch).

        Fatal (software) errors do not relaunch; everything else —
        kill/preemption/OOM/hardware — does, bounded by max_relaunch_count.
        """
        if exit_reason == NodeExitReason.FATAL_ERROR:
            return False
        return self.relaunch_count < self.max_relaunch_count
