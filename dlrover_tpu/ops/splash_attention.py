"""Splash attention: sparse-mask Pallas attention (causal / local window).

Reference analog: none — SURVEY.md §5.7 marks long-context attention a
capability gap the TPU build must fill natively; splash attention is the
TPU-idiomatic sparse-mask kernel (jax.experimental.pallas.ops.tpu.
splash_attention). Beyond the dense-causal flash kernel it skips whole
blocks that the mask zeroes, which makes sliding-window ("local")
attention pay only for the window: at seq S with window W the work drops
from O(S^2/2) to O(S*W).

Exposed through the same AttentionFn interface the transformer uses
(``[B, S, H, D]``, ``causal`` kwarg), selected via
``TransformerConfig.attention = "splash"`` with an optional
``attention_window``; falls back to (windowed) dense einsum off-TPU.
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp


def _dense_window(q, k, v, *, causal: bool, window: int) -> jax.Array:
    """Reference path: dense attention with an optional local window.

    The window==0 case delegates to the canonical dense_attention so
    there is exactly one full-causal softmax implementation to drift.
    """
    from dlrover_tpu.models.transformer import dense_attention

    if window <= 0:
        return dense_attention(q, k, v, causal=causal)
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32)
    logits = logits * scale
    s_q, s_k = q.shape[1], k.shape[1]
    q_pos = jnp.arange(s_q)[:, None]
    k_pos = jnp.arange(s_k)[None, :]
    mask = q_pos - k_pos < window
    if causal:
        mask &= q_pos >= k_pos
    else:
        mask &= k_pos - q_pos < window
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def splash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                     causal: bool = True, window: int = 0) -> jax.Array:
    """Sparse-mask attention; [B, S, H, D] like dense_attention.

    ``window > 0`` restricts each query to the last ``window`` keys
    (sliding-window / local attention); the kernel skips fully-masked
    blocks, so long sequences pay O(S * window).

    Grouped-query attention is native: k/v may carry fewer heads than q
    (H divisible by G) — the MQA kernel reads the shared KV directly
    instead of the repeat-to-H path, cutting KV memory traffic by H/G.
    """
    n_rep = q.shape[2] // k.shape[2]
    if jax.devices()[0].platform != "tpu":
        if n_rep > 1:
            k = jnp.repeat(k, n_rep, axis=2)
            v = jnp.repeat(v, n_rep, axis=2)
        return _dense_window(q, k, v, causal=causal, window=window)

    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sk,
        splash_attention_mask as sm,
    )

    B, S, H, D = q.shape
    if window > 0:
        # LocalMask allows keys in [q - left, q + right]
        base = sm.LocalMask(
            (S, S), (window - 1, 0 if causal else window - 1), 0,
        )
    elif causal:
        base = sm.CausalMask((S, S))
    else:
        base = sm.FullMask((S, S))
    # 512 blocks + fused bwd measured fastest on v5e across seq 1k-8k
    # (vs the 128 defaults: 51.6ms -> 13.8ms causal fwd+bwd at 8k, and
    # 1.2-1.5x faster than the tuned dense-causal flash kernel); gcd
    # keeps any 128-multiple sequence divisible
    b = math.gcd(S, 512)
    blocks = sk.BlockSizes(
        block_q=b, block_kv=b, block_kv_compute=b,
        block_q_dkv=b, block_kv_dkv=b, block_kv_dkv_compute=b,
        use_fused_bwd_kernel=True,
    )
    scale = 1.0 / math.sqrt(D)

    if n_rep > 1:
        # GQA: one MQA kernel per kv group, vmapped over (batch, group)
        G = k.shape[2]
        mask = sm.MultiHeadMask([base for _ in range(n_rep)])
        kernel = sk.make_splash_mqa_single_device(mask=mask,
                                                  block_sizes=blocks)
        qg = (q * scale).transpose(0, 2, 1, 3).reshape(B, G, n_rep, S, D)
        kg = k.transpose(0, 2, 1, 3)  # [B, G, S, D]
        vg = v.transpose(0, 2, 1, 3)
        out = jax.vmap(jax.vmap(kernel))(qg, kg, vg)  # [B, G, n_rep, S, D]
        return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)

    mask = sm.MultiHeadMask([base for _ in range(H)])
    kernel = sk.make_splash_mha_single_device(mask=mask,
                                              block_sizes=blocks)
    # [B, S, H, D] -> [B, H, S, D]; splash takes per-batch [H, S, D]
    qt = (q * scale).transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    out = jax.vmap(kernel)(qt, kt, vt)
    return out.transpose(0, 2, 1, 3)


def make_splash_attention(window: int = 0, native_gqa: bool = False):
    """AttentionFn factory bound to a window size (strategy layer hook).

    ``native_gqa`` makes the model hand over UNREPEATED grouped KV
    (``supports_gqa``): n_rep x less KV activation memory, but measured
    ~20% slower than the repeat path at llama3 attention geometry on
    v5e (the per-group MQA calls batch worse than one wide MHA call) —
    enable when activation memory is the binding constraint.
    """
    fn = partial(splash_attention, window=window)
    fn.supports_gqa = bool(native_gqa)
    return fn
