"""Flash attention for TPU: Pallas tiled online-softmax kernels.

Reference analog: the reference glues flash-attn CUDA kernels into its
models (atorch/atorch/modules/transformer/layers.py FA wrappers; tfplus
ships its own fmha C++ op, tfplus/flash_attn/kernels/
flash_attention_fwd_kernel.cc:28). The TPU-native equivalents are Pallas
kernels: this module provides

- ``flash_attention(q, k, v, causal=...)``: drop-in for
  models.transformer.dense_attention ([B, S, H, D] layout). On TPU it
  dispatches to jax's production Pallas flash kernel (fwd + bwd,
  jax.experimental.pallas.ops.tpu.flash_attention); elsewhere it falls
  back to the dense einsum path.
- ``flash_fwd_pallas``: this repo's own forward kernel — a compact tiled
  online-softmax implementation (one (batch*head, q-block) grid cell
  streams K/V blocks through VMEM, carrying running max / sum / output) —
  runnable in interpret mode on CPU for tests and usable directly for
  inference-style no-grad calls.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


# --------------------------------------------------------------- own kernel


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                scale: float):
    """One (batch*head, q-block) cell: stream K/V blocks, online softmax.

    Refs are blocked to [block_q, D] (q, o) and [S, D] (k, v); the K/V
    sequence is tiled in ``block_k`` chunks inside the kernel so VMEM
    holds one chunk at a time.
    """
    from jax.experimental import pallas as pl

    block_q, d = q_ref.shape
    s = k_ref.shape[0]
    q_idx = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale

    def body(start, carry):
        o, m, l = carry
        k = k_ref[pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32
        )
        v = v_ref[pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32
        )
        logits = q @ k.T  # [block_q, block_k]
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = start * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[:, None])
        l = l * corr + p.sum(axis=-1)
        o = o * corr[:, None] + p @ v
        return o, m_new, l

    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)

    num_k = s // block_k
    if causal:
        # blocks strictly past the q block's diagonal contribute nothing
        last = (q_idx + 1) * block_q
        num_k_live = jax.lax.div(last + block_k - 1, block_k)
        o, m, l = jax.lax.fori_loop(0, num_k_live, body, (o0, m0, l0))
    else:
        o, m, l = jax.lax.fori_loop(0, num_k, body, (o0, m0, l0))
    o_ref[:] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)


def flash_fwd_pallas(q: jax.Array, k: jax.Array, v: jax.Array, *,
                     causal: bool = True, block_q: int = 128,
                     block_k: int = 128,
                     interpret: bool | None = None) -> jax.Array:
    """This repo's Pallas forward kernel. [B, S, H, D] -> [B, S, H, D].

    ``interpret`` defaults to True off-TPU so the same kernel is testable
    on the CPU mesh.
    """
    from jax.experimental import pallas as pl

    B, S, H, D = q.shape
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if S % block_q or S % block_k:
        raise ValueError(f"seq {S} not divisible by blocks "
                         f"({block_q}, {block_k})")
    scale = 1.0 / math.sqrt(D)
    # [B, S, H, D] -> [B*H, S, D]
    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    qt, kt, vt = to_bhsd(q), to_bhsd(k), to_bhsd(v)

    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, causal=causal, scale=scale
    )
    out = pl.pallas_call(
        kernel,
        grid=(B * H, S // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, S, D), q.dtype),
        interpret=interpret,
    )(qt, kt, vt)
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


# ----------------------------------------------------- production dispatch


def _block_for(seq: int) -> int:
    """Largest power-of-two block <= 1024 that divides ``seq``."""
    return math.gcd(seq, 1024)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True) -> jax.Array:
    """Training-path flash attention, dense_attention-compatible.

    On TPU: jax's production Pallas kernel (tiled fwd AND bwd — the bwd
    is what keeps long-seq training memory flat). Elsewhere: the dense
    einsum reference (CPU Pallas interpret mode has no bwd kernel).
    """
    if jax.devices()[0].platform != "tpu":
        from dlrover_tpu.models.transformer import dense_attention

        return dense_attention(q, k, v, causal=causal)
    from jax.experimental.pallas.ops.tpu import flash_attention as fa

    # [B, S, H, D] -> [B, H, S, D]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    # 1024-sized q/k blocks measured 4.1x faster than the kernel's
    # defaults for fwd+bwd at seq 4096 / d 64 on v5e (14.8ms vs 60.8ms,
    # batch 4 x 12 heads); blocks must divide the sequence, so take
    # gcd(seq, 1024) — a power-of-two divisor, 1024 whenever seq allows
    bq = _block_for(q.shape[1])
    bk = _block_for(k.shape[1])
    blocks = fa.BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk,
        block_q_dkv=bq, block_k_dkv=bk,
        block_q_dq=bq, block_k_dq=bk, block_k_major_dq=bk,
    )
    out = fa.flash_attention(
        qt, kt, vt, causal=causal,
        sm_scale=1.0 / math.sqrt(q.shape[-1]),
        block_sizes=blocks,
    )
    return out.transpose(0, 2, 1, 3)
