"""Flash attention for TPU: Pallas tiled online-softmax kernels.

Reference analog: the reference glues flash-attn CUDA kernels into its
models (atorch/atorch/modules/transformer/layers.py FA wrappers; tfplus
ships its own fmha C++ op, tfplus/flash_attn/kernels/
flash_attention_fwd_kernel.cc:28). The TPU-native equivalents are Pallas
kernels: this module provides

- ``flash_attention(q, k, v, causal=...)``: drop-in for
  models.transformer.dense_attention ([B, S, H, D] layout). On TPU it
  dispatches to jax's production Pallas flash kernel (fwd + bwd,
  jax.experimental.pallas.ops.tpu.flash_attention); elsewhere it falls
  back to the dense einsum path.
- ``flash_fwd_pallas``: this repo's own forward kernel — a compact tiled
  online-softmax implementation (one (batch*head, q-block) grid cell
  streams K/V blocks through VMEM, carrying running max / sum / output) —
  runnable in interpret mode on CPU for tests and usable directly for
  inference-style no-grad calls.
- ``flash_attention_own``: the differentiable form of the own kernel —
  custom VJP whose backward is two more Pallas kernels (FlashAttention-2
  split: a dQ kernel streaming K/V per q-block and a dK/dV kernel
  streaming Q per k-block, both recomputing probabilities from the saved
  per-row logsumexp instead of materializing [S, S]).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e30


# --------------------------------------------------------------- own kernel


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                causal: bool, scale: float):
    """One (batch*head, q-block) cell: stream K/V blocks, online softmax.

    Refs are blocked to [block_q, D] (q, o) and [S, D] (k, v); the K/V
    sequence is tiled in ``block_k`` chunks inside the kernel so VMEM
    holds one chunk at a time.
    """
    from jax.experimental import pallas as pl

    block_q, d = q_ref.shape
    s = k_ref.shape[0]
    q_idx = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32) * scale

    def body(start, carry):
        o, m, l = carry
        k = k_ref[pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32
        )
        v = v_ref[pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32
        )
        logits = q @ k.T  # [block_q, block_k]
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = start * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        m_new = jnp.maximum(m, logits.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(logits - m_new[:, None])
        l = l * corr + p.sum(axis=-1)
        o = o * corr[:, None] + p @ v
        return o, m_new, l

    o0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)

    num_k = s // block_k
    if causal:
        # blocks strictly past the q block's diagonal contribute nothing
        last = (q_idx + 1) * block_q
        num_k_live = jax.lax.div(last + block_k - 1, block_k)
        o, m, l = jax.lax.fori_loop(0, num_k_live, body, (o0, m0, l0))
    else:
        o, m, l = jax.lax.fori_loop(0, num_k, body, (o0, m0, l0))
    o_ref[:] = (o / jnp.maximum(l, 1e-30)[:, None]).astype(o_ref.dtype)
    # per-row logsumexp of the scaled logits: the backward recomputes
    # probabilities from it (p = exp(scale*qk - lse)) instead of saving P
    lse_ref[:] = m + jnp.log(jnp.maximum(l, 1e-30))


def _resolve_blocks(S: int, block_q: int, block_k: int) -> tuple[int, int]:
    block_q = min(block_q, S)
    block_k = min(block_k, S)
    if S % block_q or S % block_k:
        raise ValueError(f"seq {S} not divisible by blocks "
                         f"({block_q}, {block_k})")
    return block_q, block_k


def _to_bhsd(x: jax.Array) -> jax.Array:
    B, S, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)


def _fwd_call(qt, kt, vt, *, causal: bool, block_q: int, block_k: int,
              interpret: bool) -> tuple[jax.Array, jax.Array]:
    """[B*H, S, D] inputs -> (o [B*H, S, D], lse [B*H, S])."""
    from jax.experimental import pallas as pl

    BH, S, D = qt.shape
    scale = 1.0 / math.sqrt(D)
    kernel = functools.partial(
        _fwd_kernel, block_k=block_k, causal=causal, scale=scale
    )
    return pl.pallas_call(
        kernel,
        grid=(BH, S // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, S, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((None, S, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((None, block_q), lambda b, i: (b, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), qt.dtype),
            jax.ShapeDtypeStruct((BH, S), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)


def flash_fwd_pallas(q: jax.Array, k: jax.Array, v: jax.Array, *,
                     causal: bool = True, block_q: int = 128,
                     block_k: int = 128,
                     interpret: bool | None = None) -> jax.Array:
    """This repo's Pallas forward kernel. [B, S, H, D] -> [B, S, H, D].

    ``interpret`` defaults to True off-TPU so the same kernel is testable
    on the CPU mesh.
    """
    B, S, H, D = q.shape
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    block_q, block_k = _resolve_blocks(S, block_q, block_k)
    out, _ = _fwd_call(
        _to_bhsd(q), _to_bhsd(k), _to_bhsd(v),
        causal=causal, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )
    return out.reshape(B, H, S, D).transpose(0, 2, 1, 3)


# ------------------------------------------------------------ own backward


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               block_k: int, causal: bool, scale: float):
    """dQ for one (batch*head, q-block): stream K/V, recompute P row-wise.

    ds = P * (dO @ V^T - delta); dQ = scale * ds @ K — FlashAttention-2's
    backward with the probabilities rebuilt from the saved logsumexp.
    """
    from jax.experimental import pallas as pl

    block_q, d = q_ref.shape
    s = k_ref.shape[0]
    q_idx = pl.program_id(1)
    q = q_ref[:].astype(jnp.float32)
    do = do_ref[:].astype(jnp.float32)
    lse = lse_ref[:]
    delta = delta_ref[:]

    def body(start, dq):
        k = k_ref[pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32
        )
        v = v_ref[pl.dslice(start * block_k, block_k), :].astype(
            jnp.float32
        )
        logits = (q @ k.T) * scale
        if causal:
            q_pos = q_idx * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = start * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        p = jnp.exp(logits - lse[:, None])
        dp = do @ v.T
        ds = p * (dp - delta[:, None])
        return dq + ds @ k

    num_k = s // block_k
    if causal:
        last = (q_idx + 1) * block_q
        num_k = jax.lax.div(last + block_k - 1, block_k)
    dq = jax.lax.fori_loop(
        0, num_k, body, jnp.zeros((block_q, d), jnp.float32)
    )
    dq_ref[:] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, block_q: int, causal: bool,
                scale: float):
    """dK/dV for one (batch*head, k-block): stream Q/dO blocks.

    dV = P^T @ dO; dK = scale * ds^T @ Q. Causal skips Q blocks entirely
    above the diagonal (their rows can't attend into this k-block).
    """
    from jax.experimental import pallas as pl

    block_k, d = k_ref.shape
    s = q_ref.shape[0]
    k_idx = pl.program_id(1)
    k = k_ref[:].astype(jnp.float32)
    v = v_ref[:].astype(jnp.float32)

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[pl.dslice(qi * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[pl.dslice(qi * block_q, block_q), :].astype(
            jnp.float32
        )
        lse = lse_ref[pl.dslice(qi * block_q, block_q)]
        delta = delta_ref[pl.dslice(qi * block_q, block_q)]
        logits = (q @ k.T) * scale  # [block_q, block_k]
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            k_pos = k_idx * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        p = jnp.exp(logits - lse[:, None])
        dv = dv + p.T @ do
        dp = do @ v.T
        ds = p * (dp - delta[:, None])
        dk = dk + ds.T @ q
        return dk, dv

    num_q = s // block_q
    zero = (jnp.zeros((block_k, d), jnp.float32),
            jnp.zeros((block_k, d), jnp.float32))
    if causal:
        # first q block whose rows reach this k-block's first column
        first = jax.lax.div(k_idx * block_k, block_q)
        dk, dv = jax.lax.fori_loop(first, num_q, body, zero)
    else:
        dk, dv = jax.lax.fori_loop(0, num_q, body, zero)
    dk_ref[:] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[:] = dv.astype(dv_ref.dtype)


def _bwd_call(qt, kt, vt, ot, do_t, lse, *, causal: bool, block_q: int,
              block_k: int, interpret: bool):
    from jax.experimental import pallas as pl

    BH, S, D = qt.shape
    scale = 1.0 / math.sqrt(D)
    # delta_i = rowsum(dO_i * O_i): the softmax-jacobian diagonal term,
    # cheap enough to leave to XLA fusion outside the kernels
    delta = (do_t.astype(jnp.float32) * ot.astype(jnp.float32)).sum(-1)

    full = lambda b, i: (b, 0, 0)  # noqa: E731
    rows = lambda b, i: (b, i, 0)  # noqa: E731
    vec = lambda b, i: (b, i)      # noqa: E731
    vec_full = lambda b, i: (b, 0)  # noqa: E731

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, block_k=block_k, causal=causal,
                          scale=scale),
        grid=(BH, S // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, D), rows),
            pl.BlockSpec((None, S, D), full),
            pl.BlockSpec((None, S, D), full),
            pl.BlockSpec((None, block_q, D), rows),
            pl.BlockSpec((None, block_q), vec),
            pl.BlockSpec((None, block_q), vec),
        ],
        out_specs=pl.BlockSpec((None, block_q, D), rows),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), qt.dtype),
        interpret=interpret,
    )(qt, kt, vt, do_t, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, block_q=block_q, causal=causal,
                          scale=scale),
        grid=(BH, S // block_k),
        in_specs=[
            pl.BlockSpec((None, S, D), full),
            pl.BlockSpec((None, block_k, D), rows),
            pl.BlockSpec((None, block_k, D), rows),
            pl.BlockSpec((None, S, D), full),
            pl.BlockSpec((None, S), vec_full),
            pl.BlockSpec((None, S), vec_full),
        ],
        out_specs=[
            pl.BlockSpec((None, block_k, D), rows),
            pl.BlockSpec((None, block_k, D), rows),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), kt.dtype),
            jax.ShapeDtypeStruct((BH, S, D), vt.dtype),
        ],
        interpret=interpret,
    )(qt, kt, vt, do_t, lse, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_own(q: jax.Array, k: jax.Array, v: jax.Array,
                        causal: bool = True, block_q: int = 128,
                        block_k: int = 128,
                        interpret: bool | None = None) -> jax.Array:
    """Differentiable own-kernel flash attention, [B, S, H, D] layout.

    Forward and backward are all this repo's Pallas kernels (no library
    fallback): fwd saves (O, lse); bwd runs the dQ and dK/dV kernels.
    Interpret mode makes the full fwd+bwd pair testable on CPU.
    """
    out, _ = _flash_own_fwd(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_own_fwd(q, k, v, causal, block_q, block_k, interpret):
    B, S, H, D = q.shape
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    bq, bk = _resolve_blocks(S, block_q, block_k)
    qt, kt, vt = _to_bhsd(q), _to_bhsd(k), _to_bhsd(v)
    ot, lse = _fwd_call(qt, kt, vt, causal=causal, block_q=bq,
                        block_k=bk, interpret=interpret)
    out = ot.reshape(B, H, S, D).transpose(0, 2, 1, 3)
    return out, (qt, kt, vt, ot, lse, (B, S, H, D))


def _flash_own_bwd(causal, block_q, block_k, interpret, res, g):
    qt, kt, vt, ot, lse, (B, S, H, D) = res
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    bq, bk = _resolve_blocks(S, block_q, block_k)
    do_t = _to_bhsd(g)
    dq, dk, dv = _bwd_call(
        qt, kt, vt, ot, do_t, lse, causal=causal, block_q=bq,
        block_k=bk, interpret=interpret,
    )

    def back(x):
        return x.reshape(B, H, S, D).transpose(0, 2, 1, 3)

    return back(dq), back(dk), back(dv)


flash_attention_own.defvjp(_flash_own_fwd, _flash_own_bwd)


# ----------------------------------------------------- production dispatch


def _block_for(seq: int) -> int:
    """Largest power-of-two block <= 1024 that divides ``seq``."""
    return math.gcd(seq, 1024)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True) -> jax.Array:
    """Training-path flash attention, dense_attention-compatible.

    On TPU: jax's production Pallas kernel (tiled fwd AND bwd — the bwd
    is what keeps long-seq training memory flat). Elsewhere: the dense
    einsum reference (CPU Pallas interpret mode has no bwd kernel).
    """
    if jax.devices()[0].platform != "tpu":
        from dlrover_tpu.models.transformer import dense_attention

        return dense_attention(q, k, v, causal=causal)
    from jax.experimental.pallas.ops.tpu import flash_attention as fa

    # [B, S, H, D] -> [B, H, S, D]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    # 1024-sized q/k blocks measured 4.1x faster than the kernel's
    # defaults for fwd+bwd at seq 4096 / d 64 on v5e (14.8ms vs 60.8ms,
    # batch 4 x 12 heads); blocks must divide the sequence, so take
    # gcd(seq, 1024) — a power-of-two divisor, 1024 whenever seq allows
    bq = _block_for(q.shape[1])
    bk = _block_for(k.shape[1])
    blocks = fa.BlockSizes(
        block_q=bq, block_k_major=bk, block_k=bk, block_b=1,
        block_q_major_dkv=bq, block_k_major_dkv=bk,
        block_q_dkv=bq, block_k_dkv=bk,
        block_q_dq=bq, block_k_dq=bk, block_k_major_dq=bk,
    )
    out = fa.flash_attention(
        qt, kt, vt, causal=causal,
        sm_scale=1.0 / math.sqrt(q.shape[-1]),
        block_sizes=blocks,
    )
    return out.transpose(0, 2, 1, 3)
