"""Mixture-of-Experts: top-k gating + expert-parallel dispatch.

Reference analog: atorch/atorch/modules/moe/ (moe_layer.py all_to_all
dispatch, topk_gating.py, switch_gating.py, ddp.py expert-aware grad
groups). TPU-native design: experts carry an "expert" logical axis that
the strategy maps onto the expert mesh axis; dispatch/combine are einsums
against a capacity-limited one-hot dispatch tensor, and XLA lowers the
resharding between token-sharded and expert-sharded layouts to all_to_all
collectives — no imperative dispatch code, and expert-parallel gradients
need no special DDP handling (they're just sharded arrays).

Gating follows the Switch/GShard recipe: softmax router, top-k experts
per token, per-expert capacity ``ceil(T/E * capacity_factor)`` with
overflow tokens dropped (their residual path passes through), and the
load-balancing auxiliary loss ``E * sum_e f_e * p_e``.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MoeConfig:
    n_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25


def init_moe_params(key: jax.Array, d_model: int, d_ff: int,
                    cfg: MoeConfig) -> dict:
    import math

    k_r, k_in, k_out = jax.random.split(key, 3)
    return {
        "w_router": jax.random.normal(
            k_r, (d_model, cfg.n_experts), jnp.float32
        ) / math.sqrt(d_model),
        "w_in": jax.random.normal(
            k_in, (cfg.n_experts, d_model, d_ff), jnp.float32
        ) / math.sqrt(d_model),
        "w_out": jax.random.normal(
            k_out, (cfg.n_experts, d_ff, d_model), jnp.float32
        ) / math.sqrt(d_ff),
    }


def moe_logical_axes(cfg: MoeConfig | None = None) -> dict:
    """Logical axes: experts shard over the "expert" mesh axis."""
    return {
        "w_router": ("embed", None),
        "w_in": ("expert", "embed", "mlp"),
        "w_out": ("expert", "mlp", "embed"),
    }


def _dispatch_tensors(gates: jax.Array, cfg: MoeConfig, capacity: int
                      ) -> tuple[jax.Array, jax.Array]:
    """(combine [T,E,C], dispatch mask [T,E,C]) for top-k routed tokens.

    GShard-style position assignment: tokens claim expert slots in order;
    tokens past an expert's capacity are dropped for that expert.
    """
    T, E = gates.shape
    combine = jnp.zeros((T, E, capacity), gates.dtype)
    remaining = gates
    # slots already used per expert by earlier k-iterations
    used = jnp.zeros((E,), jnp.int32)
    for _ in range(cfg.top_k):
        idx = jnp.argmax(remaining, axis=-1)                 # [T]
        onehot = jax.nn.one_hot(idx, E, dtype=gates.dtype)   # [T, E]
        gate_k = (remaining * onehot).sum(-1)                # [T]
        # position of each token within its chosen expert's buffer —
        # cumsum MUST run in int32: a bf16 cumsum cannot represent
        # integers past 256, so long sequences would collide tokens into
        # the same capacity slot (blended expert inputs)
        onehot_i = jax.nn.one_hot(idx, E, dtype=jnp.int32)
        # a zero-gate token (e.g. masked out) claims no slot at all
        routed = (gate_k > 0)
        onehot_i = onehot_i * routed[:, None].astype(jnp.int32)
        pos = (jnp.cumsum(onehot_i, axis=0) - onehot_i
               ) + used[None, :]                             # [T, E] i32
        pos_tok = (pos * onehot_i).sum(-1)                   # [T] i32
        keep = routed & (pos_tok < capacity)
        slot = jax.nn.one_hot(
            jnp.where(keep, pos_tok, capacity), capacity + 1,
            dtype=gates.dtype,
        )[:, :capacity]                                      # [T, C]
        combine = combine + (
            gate_k[:, None, None] * onehot[:, :, None] * slot[:, None, :]
        )
        used = used + (onehot_i * keep[:, None].astype(jnp.int32)).sum(0)
        remaining = remaining * (1.0 - onehot)
    dispatch = (combine > 0).astype(gates.dtype)
    return combine, dispatch


def moe_ffn(params: dict, x: jax.Array, cfg: MoeConfig,
            constrain=None, token_mask: jax.Array | None = None
            ) -> tuple[jax.Array, jax.Array]:
    """MoE feed-forward. x: [B, S, M] -> ([B, S, M], aux_loss scalar).

    ``constrain`` (strategy layer) pins the expert-sharded intermediates
    so XLA keeps expert compute on the expert mesh axis. ``token_mask``
    [B, S] excludes padding from routing, capacity, and the aux loss —
    pad tokens would otherwise evict real tokens from expert buffers.
    """
    import math

    B, S, M = x.shape
    T = B * S
    E = cfg.n_experts
    pin = constrain or (lambda v, a: v)
    xt = x.reshape(T, M)

    logits = (xt.astype(jnp.float32) @ params["w_router"]).astype(
        jnp.float32
    )
    gates = jax.nn.softmax(logits, axis=-1)                   # [T, E]
    if token_mask is not None:
        mask_t = token_mask.reshape(T).astype(jnp.float32)
        gates = gates * mask_t[:, None]
        n_real = jnp.maximum(mask_t.sum(), 1.0)
    else:
        mask_t = None
        n_real = float(T)

    # load-balancing aux loss over REAL tokens: fraction routed to e
    # (top-1) times mean router prob for e, scaled by E (Switch eq. 4)
    top1 = jax.nn.one_hot(jnp.argmax(gates, -1), E, dtype=jnp.float32)
    if mask_t is not None:
        top1 = top1 * mask_t[:, None]
    aux = E * jnp.sum(
        (top1.sum(0) / n_real) * (gates.sum(0) / n_real)
    )

    capacity = max(
        cfg.top_k, math.ceil(T / E * cfg.capacity_factor)
    )
    combine, dispatch = _dispatch_tensors(
        gates.astype(x.dtype), cfg, capacity
    )

    # [T,E,C] x [T,M] -> [E,C,M]: becomes an all_to_all when tokens are
    # batch-sharded and experts expert-sharded
    x_e = jnp.einsum("tec,tm->ecm", dispatch, xt)
    x_e = pin(x_e, ("expert", None, "embed"))
    h = jax.nn.relu(jnp.einsum("ecm,emf->ecf", x_e, params["w_in"].astype(
        x.dtype
    )))
    h = pin(h, ("expert", None, "mlp"))
    y_e = jnp.einsum("ecf,efm->ecm", h, params["w_out"].astype(x.dtype))
    y = jnp.einsum("tec,ecm->tm", combine, y_e)
    return y.reshape(B, S, M), aux.astype(jnp.float32)
