"""Ulysses sequence parallelism: all-to-all head redistribution.

The second of the two modern context-parallel schemes (SURVEY.md §2.5
names both as capability gaps to fill natively — the reference's own SP is
an all-reduce softmax). DeepSpeed-Ulysses (Jacobs et al., 2023):

- activations arrive sequence-sharded, [B, S/n, H, D] per device;
- one ``all_to_all`` trades the sequence split for a head split: every
  device ends with the FULL sequence for H/n heads;
- attention runs locally, completely standard (any per-device kernel —
  dense, flash — since each head's attention is independent);
- a second ``all_to_all`` restores sequence sharding.

vs ring attention (ops/ring_attention.py): Ulysses moves 2x the activation
bytes in two bursts but runs UNMODIFIED local attention (no online-softmax
ring pipeline), and its comm volume is independent of the sequence length
per hop count — the better fit when heads are plentiful and the per-device
kernel is highly tuned. Ring wins when n > H or memory for the full-S
scores per head is the binding constraint. Both ride the same "sequence"
mesh axis, so strategies can pick per model shape.

Constraint: the sequence-axis size must divide n_heads (and kv_heads for
GQA) — heads are the resource being redistributed.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

from jax import lax
from jax.sharding import Mesh


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = True,
                      attn_impl: Callable | None = None):
    """Per-shard body (call under shard_map): [B, S/n, H, D] in/out.

    GQA-native: k/v arrive with their OWN (smaller) head count and are
    all-to-all'd unexpanded — repeating to n_heads happens locally after
    the gather, so the comm bursts move only kv-head bytes (the point of
    GQA). This is why the model layer must NOT pre-repeat
    (``supports_gqa`` on the wrapper).
    """
    if attn_impl is None:
        from dlrover_tpu.models.transformer import dense_attention

        attn_impl = dense_attention

    def seq_to_heads(x):
        # split heads (axis 2) across the group, gather the sequence
        # (axis 1): [B, S/n, H, D] -> [B, S, H/n, D]
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    n_rep = qg.shape[2] // kg.shape[2]
    if n_rep > 1:
        import jax.numpy as jnp

        kg = jnp.repeat(kg, n_rep, axis=2)
        vg = jnp.repeat(vg, n_rep, axis=2)
    o = attn_impl(qg, kg, vg, causal=causal)
    # inverse: split the sequence back, gather the heads
    return lax.all_to_all(
        o, axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def make_ulysses_attention(
    mesh: Mesh, axis_name: str = "sequence",
    batch_axes: tuple[str, ...] = ("data", "fsdp"),
    heads_axis: str = "tensor",
    attn_impl: Callable | None = None,
) -> Callable:
    """Drop-in ``attention_fn`` (same signature/degradation contract as
    make_ring_attention): global [B, S, H, D] arrays, sequence-sharded by
    the strategy's activation constraints."""
    from dlrover_tpu.ops.collectives import (
        seq_parallel_spec,
        shard_map_nocheck,
    )

    spec = seq_parallel_spec(mesh, axis_name, batch_axes, heads_axis)
    if spec is None:
        from dlrover_tpu.models.transformer import dense_attention

        return dense_attention
    n = mesh.shape[axis_name]
    h_spec = spec[2]

    def attn(q, k, v, *, causal: bool = True):
        heads_local = q.shape[2] // (mesh.shape.get(heads_axis, 1)
                                     if h_spec else 1)
        kv_local = k.shape[2] // (mesh.shape.get(heads_axis, 1)
                                  if h_spec else 1)
        if heads_local % n or kv_local % n:
            raise ValueError(
                f"ulysses needs the sequence axis ({n}) to divide the "
                f"per-shard head counts ({heads_local} q / {kv_local} "
                f"kv); use ring attention for this shape"
            )
        body = partial(
            ulysses_attention, axis_name=axis_name, causal=causal,
            attn_impl=attn_impl,
        )
        return shard_map_nocheck(
            body, mesh=mesh,
            in_specs=(spec, spec, spec), out_specs=spec,
        )(q, k, v)

    # GQA-native: the layer body hands over UNEXPANDED kv heads and the
    # all-to-alls move only kv bytes (repeat happens post-gather)
    attn.supports_gqa = True
    return attn
