"""int8 quantized matmuls for training — the TPU MXU's 2x-rate path.

Reference analog: atorch's ``Fp8Optimization`` (TransformerEngine fp8 on
H100s, ``atorch/auto/opt_lib/amp_optimization.py:197``) — same idea, the
hardware's narrow-precision matmul path, TPU-first: v5e MXUs run int8 at
~2x bf16 throughput (measured 252 vs 156 TOP/s on back-to-back d=3072
chains), and XLA lowers ``lax.dot_general`` on int8 operands with an
int32 accumulator straight onto it. No CUDA kernels, no module
injection: a drop-in ``int8_matmul`` with a custom VJP.

Scheme (standard AQT-class symmetric quantization):
- forward ``y = x @ w``: x is quantized per *row* (each [..., K] vector
  gets its own scale — token outliers stay local), w per *column*. Both
  scale choices depend only on non-contracted indices, so the int32
  product un-scales exactly: ``y = (xq @ wq) * sx * sw``.
- backward contracts over different axes, where the forward scales
  would sit on the contracted index, so operands are *re*-quantized
  along the axis each grad contraction needs: ``dx = (dyq @ wqT)`` with
  dy per-row and w per-row(K); ``dw = (xqT @ dyq)`` with x per-column(K)
  and dy per-column(N). Gradients take the straight-through estimator
  (quantization treated as identity), the universal practice.

The bf16 master weights live in the optimizer state as usual; this is a
compute-path quantization, not a storage format. Quality guardrail: keep
the embedding/LM-head matmuls in bf16 (vocab logits are
quantization-sensitive); ``TransformerConfig.int8_matmuls`` wires only
the layer-stack projections (QKV/out/FFN) through here.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_EPS = 1e-8


def _quantize(x: jax.Array, axis: int) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization with one scale per slice along `axis`.

    Returns (int8 values, f32 scales broadcastable against x). The scale
    lives on every index EXCEPT `axis` — quantizing "along" the axis that
    a later dot contracts over.
    """
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax, _EPS) / 127.0
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -127, 127)
    return q.astype(jnp.int8), scale


def _i8_dot(a_q: jax.Array, b_q: jax.Array) -> jax.Array:
    """[M, K]i8 @ [K, N]i8 -> [M, N]f32 via the int32 MXU path."""
    out = lax.dot_general(
        a_q, b_q, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return out.astype(jnp.float32)


@jax.custom_vjp
def int8_matmul(x: jax.Array, w: jax.Array) -> jax.Array:
    """``x[..., K] @ w[K, N]`` with both operands int8-quantized.

    Forward and both backward contractions ride the MXU's int8 path;
    gradients are straight-through w.r.t. the quantization.
    """
    y, _ = _fwd(x, w)
    return y


def _fwd(x, w):
    lead = x.shape[:-1]
    k = x.shape[-1]
    x2 = x.reshape(-1, k)
    xq, sx = _quantize(x2, axis=1)       # per row: scale [M, 1]
    wq, sw = _quantize(w, axis=0)        # per column: scale [1, N]
    y = _i8_dot(xq, wq) * sx * sw
    y = y.astype(x.dtype).reshape(*lead, w.shape[1])
    return y, (x2, w)


def _bwd(res, dy):
    x2, w = res
    dt = x2.dtype
    lead_n = dy.shape[-1]
    dy2 = dy.reshape(-1, lead_n).astype(jnp.float32)

    # dx = dy @ w.T  (contract N): dy per-row, w per-row(K)
    dyq_r, sdy_r = _quantize(dy2, axis=1)            # [M,1]
    wq_k, sw_k = _quantize(w, axis=1)                # [K,1] scale per row k
    dx = _i8_dot(dyq_r, wq_k.T) * sdy_r * sw_k.T     # [M,K]

    # dw = x.T @ dy  (contract M): x per-column(K), dy per-column(N)
    xq_c, sx_c = _quantize(x2, axis=0)               # [1,K]
    dyq_c, sdy_c = _quantize(dy2, axis=0)            # [1,N]
    dw = _i8_dot(xq_c.T, dyq_c) * sx_c.T * sdy_c     # [K,N]

    return (dx.astype(dt).reshape(*dy.shape[:-1], w.shape[0]),
            dw.astype(w.dtype))


int8_matmul.defvjp(_fwd, _bwd)


def matmul_error(x: jax.Array, w: jax.Array) -> float:
    """Relative Frobenius error of the quantized product (diagnostics)."""
    exact = jnp.asarray(x, jnp.float32) @ jnp.asarray(w, jnp.float32)
    approx = int8_matmul(x, w).astype(jnp.float32)
    return float(jnp.linalg.norm(approx - exact) /
                 jnp.maximum(jnp.linalg.norm(exact), _EPS))
