"""Compressed collectives: int8-quantized gradient reduction.

Reference analog: ATorch's CUDA quant-reduce kernels for communication
compression (atorch/atorch/ops/csrc/quantization/quant_reduce.cu) — the
gradient allreduce ships int8 payloads instead of f32/bf16. On TPU the
collectives are XLA's; compression is expressed in-graph.

Two transports:

- ``quantized_ring_mean`` (the default for a single axis): a ring
  reduce-scatter with per-hop requantization followed by an int8
  all-gather. Per-device wire bytes ~= 2x payload in int8 ~= 1/4 of the
  f32 ring allreduce it replaces, independent of axis size N — the shape
  that actually wins on a DCN-spanning data axis.
- ``quantized_gather_mean``: all-gather of everyone's int8 payload,
  O(N) bytes per device. Lower quantization error (single quantization,
  exact per-participant scales) but only cheaper than f32 allreduce for
  small N; used for multi-axis reductions where a single ring does not
  apply.

Both must run inside ``shard_map`` (they take mesh axis names).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax


def shard_map_nocheck(f: Callable, *, mesh, in_specs, out_specs) -> Callable:
    """``shard_map`` with replication/varying-axis checking disabled,
    across jax versions: resolves the top-level vs experimental export
    and the ``check_vma`` vs ``check_rep`` kwarg rename in one place.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax exposes it under experimental
        from jax.experimental.shard_map import shard_map
    try:
        return shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    except TypeError:
        return shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )


def _quantize(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    scale = jnp.max(jnp.abs(x)) / 127.0 + 1e-30
    q = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return q, scale


def quantized_gather_mean(x: jax.Array, axes: Sequence[str]) -> jax.Array:
    """Mean across ``axes`` via int8 all-gather (O(N) per-device bytes)."""
    if not axes:
        return x
    axes = tuple(axes)
    q, scale = _quantize(x)
    qg = lax.all_gather(q, axes)                 # [N, ...]
    sg = lax.all_gather(scale, axes)             # [N]
    deq = qg.astype(jnp.float32) * sg.reshape((-1,) + (1,) * x.ndim)
    return deq.mean(0).astype(x.dtype)


def quantized_ring_mean(x: jax.Array, axis: str, n: int) -> jax.Array:
    """Mean across mesh ``axis`` (size ``n``) with int8 ring transport.

    Ring reduce-scatter: n-1 hops, each forwarding a requantized partial
    sum of one 1/n chunk; then an int8 all-gather of the reduced chunks.
    Per-device bytes ~= 2 * |x| in int8, independent of n. Requantizing
    at every hop accumulates error O(n * max|partial|/254) — still far
    below gradient noise for n in the tens.
    """
    if n == 1:
        return x
    idx = lax.axis_index(axis)
    flat = x.reshape(-1).astype(jnp.float32)
    size = flat.shape[0]
    chunk = -(-size // n)  # ceil
    flat = jnp.pad(flat, (0, chunk * n - size))
    parts = flat.reshape(n, chunk)

    fwd = [(i, (i + 1) % n) for i in range(n)]

    # step k: device idx holds the running sum of chunk (idx - k) mod n,
    # forwards it, and absorbs the incoming sum of chunk (idx - k - 1)
    acc = lax.dynamic_index_in_dim(parts, idx % n, 0, keepdims=False)
    for k in range(n - 1):
        q, scale = _quantize(acc)
        q = lax.ppermute(q, axis, fwd)
        scale = lax.ppermute(scale, axis, fwd)
        incoming = q.astype(jnp.float32) * scale
        local = lax.dynamic_index_in_dim(
            parts, (idx - k - 1) % n, 0, keepdims=False
        )
        acc = incoming + local
    # device idx now owns the full sum of chunk (idx + 1) mod n
    q, scale = _quantize(acc)
    qg = lax.all_gather(q, axis)                 # [n, chunk] by device
    sg = lax.all_gather(scale, axis)             # [n]
    deq = qg.astype(jnp.float32) * sg[:, None]
    # device i's slot holds chunk (i + 1) mod n -> roll into chunk order
    ordered = jnp.roll(deq, 1, axis=0)
    out = ordered.reshape(-1)[:size] / n
    return out.reshape(x.shape).astype(x.dtype)


def quantized_tree_mean(
    tree: Any, axes: Sequence[str], axis_sizes: dict[str, int] | None = None
) -> Any:
    """Quantized mean over every leaf of a gradient pytree.

    Single axis -> ring transport (O(1) per-device bytes); multiple axes
    -> gather transport. ``axis_sizes`` (mesh.shape) is required for the
    ring path.
    """
    axes = tuple(axes)
    if len(axes) == 1 and axis_sizes is not None:
        n = int(axis_sizes[axes[0]])
        return jax.tree.map(
            lambda g: quantized_ring_mean(g, axes[0], n), tree
        )
    return jax.tree.map(lambda g: quantized_gather_mean(g, axes), tree)


def seq_parallel_spec(mesh, axis_name: str,
                      batch_axes: tuple[str, ...] = ("data", "fsdp"),
                      heads_axis: str = "tensor"):
    """The [B, S, H, D] PartitionSpec shared by the sequence-parallel
    attention wrappers (ring + Ulysses), or None when the mesh has no
    usable sequence axis (callers degrade to dense attention)."""
    from jax.sharding import PartitionSpec

    if axis_name not in mesh.axis_names or mesh.shape[axis_name] <= 1:
        return None
    batch = tuple(a for a in batch_axes if a in mesh.axis_names
                  and mesh.shape[a] > 1)
    b_spec = batch if len(batch) > 1 else (batch[0] if batch else None)
    h_spec = (
        heads_axis
        if heads_axis in mesh.axis_names and mesh.shape[heads_axis] > 1
        else None
    )
    return PartitionSpec(b_spec, axis_name, h_spec, None)
