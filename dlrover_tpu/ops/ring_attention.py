"""Ring attention over a named "sequence" mesh axis.

Long-context/context-parallel attention: the reference's closest analog is
ATorch's DistributedSelfAttention (atorch/atorch/modules/
distributed_transformer/distributed_attention.py:21,79 — an all-reduce
softmax over sequence shards), and SURVEY.md §5.7 marks true ring/blockwise
attention as a capability gap the TPU build must fill natively.

Design (Ring Attention, Liu et al. 2023, blockwise-parallel form):
- Q, K, V live sequence-sharded: [B, S, H, D] with S split over the
  ``sequence`` mesh axis; each device keeps its Q block resident.
- K/V blocks rotate around the ring via ``lax.ppermute`` — N-1 hops on ICI
  neighbors, each overlapped by XLA with the local block computation.
- Softmax is accumulated online (running max + log-sum-exp rescaling), so
  the full [S, S] score matrix never materializes: memory is O(S_local²)
  per step instead of O(S²).
- Causal masking is block-structured: a KV block strictly after the local
  Q block contributes nothing and its compute is skipped with ``lax.cond``
  (the rotation still runs to keep the ring in lockstep).

The feed-forward half of long-context ("blockwise FFN") needs no special
op: activations stay sequence-sharded via the strategy's sharding rules and
the FFN is position-wise.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec

NEG_INF = -1e30


def _block_scores(q, k, scale, q_offset, k_offset, causal):
    """fp32 masked scores for one (Q block, KV block) pair.

    q: [B, Sq, H, D], k: [B, Sk, H, D] -> [B, H, Sq, Sk]
    """
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = k_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(mask, logits, NEG_INF)
    return logits


def _accumulate(carry, logits, v):
    """Online-softmax accumulation of one KV block.

    carry: (o [B,H,Sq,D] f32, l [B,H,Sq] f32, m [B,H,Sq] f32)
    """
    o, l, m = carry
    m_new = jnp.maximum(m, logits.max(axis=-1))
    # rescale previous accumulators to the new max
    corr = jnp.exp(m - m_new)
    p = jnp.exp(logits - m_new[..., None])
    l = l * corr + p.sum(axis=-1)
    # the flop-dominant PV matmul runs in the compute dtype (bf16 MXU
    # rate); only the accumulators stay f32 — same split as
    # dense_attention's fp32-softmax/bf16-matmul
    pv = jnp.einsum("bhqk,bkhd->bhqd", p.astype(v.dtype), v)
    o = o * corr[..., None] + pv.astype(jnp.float32)
    return o, l, m_new


def ring_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, *,
    axis_name: str = "sequence",
    causal: bool = True,
) -> jax.Array:
    """Per-shard ring attention body (call under shard_map/jit).

    q, k, v: the LOCAL sequence shard [B, S_local, H, D]. Returns the local
    output shard [B, S_local, H, D].
    """
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, S, H, D = q.shape
    scale = 1.0 / math.sqrt(D)
    q_offset = my * S

    o0 = jnp.zeros((B, H, S, D), jnp.float32)
    l0 = jnp.zeros((B, H, S), jnp.float32)
    m0 = jnp.full((B, H, S), NEG_INF, jnp.float32)

    perm = [(j, (j + 1) % n) for j in range(n)]

    def step(i, carry):
        o, l, m, k_cur, v_cur = carry
        src = (my - i) % n  # which global chunk this KV block is
        k_offset = src * S

        def attend(c):
            logits = _block_scores(q, k_cur, scale, q_offset, k_offset,
                                   causal)
            return _accumulate(c, logits, v_cur)

        if causal:
            # blocks strictly in the future contribute nothing: skip the
            # matmuls, keep the ring rotation
            o, l, m = lax.cond(
                src <= my, attend, lambda c: c, (o, l, m)
            )
        else:
            o, l, m = attend((o, l, m))
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return o, l, m, k_next, v_next

    o, l, m, _, _ = lax.fori_loop(0, n, step, (o0, l0, m0, k, v))
    out = o / jnp.maximum(l, 1e-30)[..., None]
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def make_ring_attention(
    mesh: Mesh, axis_name: str = "sequence",
    batch_axes: tuple[str, ...] = ("data", "fsdp"),
    heads_axis: str = "tensor",
) -> Callable:
    """Drop-in ``attention_fn`` (same signature as dense_attention).

    Takes GLOBAL [B, S, H, D] arrays (sequence-sharded by the strategy's
    activation constraints) and runs the ring body under ``shard_map``.
    Heads stay sharded over the tensor axis when the mesh has one —
    attention is independent per head, and replicating them here would
    all-gather q/k/v and duplicate the ring FLOPs across the tensor axis.
    """
    from dlrover_tpu.ops.collectives import (
        seq_parallel_spec,
        shard_map_nocheck,
    )

    spec = seq_parallel_spec(mesh, axis_name, batch_axes, heads_axis)
    if spec is None:
        # no sequence axis on this mesh: degrade to dense attention (the
        # elasticity property — same model code on any mesh)
        from dlrover_tpu.models.transformer import dense_attention

        return dense_attention

    # replication/varying-axis checking is disabled: the lax.cond causal
    # skip's branches intentionally differ in which inputs they touch
    def attn(q, k, v, *, causal: bool = True):
        body = partial(ring_attention, axis_name=axis_name, causal=causal)
        return shard_map_nocheck(
            body, mesh=mesh,
            in_specs=(spec, spec, spec), out_specs=spec,
        )(q, k, v)

    return attn
