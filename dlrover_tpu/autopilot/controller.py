"""Autopilot controller: the Brain-style closed loop over a launched
plan.

Runs master-side, riding the trainer snapshot pushes exactly like the
continuous straggler detector (``telemetry/anomaly.py``): the delta of
the ``dlrover_tpu_train_step_seconds`` histogram's (sum, count) between
two pushes is that node's mean step time over the interval — no new
RPC, no probe round. The controller compares the fleet's recent median
against the launched plan's prediction
(:class:`~dlrover_tpu.autopilot.planner.Plan.pred_step_s`); live MFU
rides the same pushes as corroborating evidence.

Contradiction rule (hysteretic, same spirit as the PR-5 interval
tuner): ``measured / predicted > tolerance`` on ``action_streak``
consecutive evaluations fires a retune; a ratio back under
``clear_ratio`` resets the streak, so a transient dip (one slow data
shard, a neighbor's compile) never triggers anything. A retune picks
the best APPLICABLE alternative from the planner's ranked list and
applies it the cheapest way that works:

==================  =======================================  =========
plan delta          mechanism                                path
==================  =======================================  =========
same mesh+schedule  swap the step program (compile cache),   ``hot``
                    state buffers untouched
mesh axes differ    PR-6 reshard: rebuild program + move     ``reshard``
                    state shards (``mesh.reshard_state``
                    semantics), launder, resume
schedule differs    SPMD<->MPMD runtime rebuild              ``reschedule``
==================  =======================================  =========

None of the paths restarts a process. Every decision journals an
``autopilot_retune`` instant carrying the full evidence that triggered
it; retunes are bounded per job (``DLROVER_TPU_AUTOPILOT_MAX_RETUNES``)
— a plan that keeps contradicting after the budget is an operator
page, not an oscillation (DESIGN.md §24 runbook).
"""

from __future__ import annotations

import dataclasses
import statistics
import threading
from collections import deque
from typing import Callable, Optional

from dlrover_tpu.autopilot.planner import Plan, _pred_step_gauge
from dlrover_tpu.common import envspec
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.anomaly import _step_stats
from dlrover_tpu.telemetry.journal import (
    current_trace_id,
    format_ctx,
    get_journal,
)
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

MFU_METRIC = "dlrover_tpu_mfu"

_step_ratio_gauge = registry().gauge(
    "dlrover_tpu_autopilot_step_ratio",
    "recent measured step time over the launched plan's prediction "
    "(>1 = slower than planned; a sustained excursion past the "
    "tolerance is the retune trigger)",
)
_retunes_total = registry().counter(
    "dlrover_tpu_autopilot_retunes_total",
    "applied autopilot retunes by application path "
    "(hot/reshard/reschedule)",
    label_names=("path",),
)
_contradiction_streak = registry().gauge(
    "dlrover_tpu_autopilot_contradiction_streak",
    "consecutive evaluations the live step time has contradicted the "
    "plan's prediction (resets under the clear ratio)",
)


def choose_path(current: Plan, target: Plan) -> str:
    """The retune decision table: cheapest mechanism that can morph
    ``current`` into ``target`` without a restart."""
    if target.schedule != current.schedule:
        return "reschedule"
    if dict(target.mesh_axes) != dict(current.mesh_axes):
        return "reshard"
    return "hot"


def _mfu_value(samples: list) -> Optional[float]:
    """Latest ``dlrover_tpu_mfu`` gauge value in a pushed snapshot, or
    None (CPU backends leave the gauge unset)."""
    for metric in samples:
        if not isinstance(metric, dict) \
                or metric.get("name") != MFU_METRIC:
            continue
        values = [float(s.get("value", 0.0))
                  for s in metric.get("samples", ())]
        values = [v for v in values if v > 0]
        if values:
            return max(values)
    return None


@dataclasses.dataclass
class RetuneDecision:
    """One journaled retune: evidence in, chosen alternative out."""

    from_plan: Plan
    to_plan: Plan
    path: str
    evidence: dict
    # span context (§27) of the journaled autopilot_retune verdict —
    # the ParalConfig push and the trainer's apply journal as children
    sctx: str = ""


class _NodeSteps:
    """Per-node cumulative (sum, count) tracker — the anomaly.py delta
    trick, kept separately so the controller works without a straggler
    detector in the loop."""

    __slots__ = ("cum_sum", "cum_count")

    def __init__(self):
        self.cum_sum = 0.0
        self.cum_count = 0

    def delta(self, total: float, count: int) -> Optional[float]:
        dsum = total - self.cum_sum
        dcount = count - self.cum_count
        if dcount < 0 or dsum < 0:  # trainer respawned: counters reset
            dsum, dcount = total, count
        self.cum_sum, self.cum_count = total, count
        return dsum / dcount if dcount > 0 else None


class AutopilotController:
    """Hysteretic plan-vs-measured contradiction detector + retuner.

    ``on_retune(decision)`` is the application hook: the master
    servicer wires it to a ParalConfig push (the trainer hot-applies
    through ``autopilot/apply.py``); in-process harnesses call the
    applier directly. ``applicable(current, target)`` lets the caller
    veto alternatives its apply path cannot morph to (e.g. a batch
    geometry the running loader cannot feed) — the controller then
    falls through to the next ranked alternative.
    """

    def __init__(
        self,
        *,
        tolerance: float = 1.5,
        clear_ratio: float = 1.2,
        action_streak: int = 3,
        window: int = 8,
        min_points: int = 3,
        max_retunes: Optional[int] = None,
        on_retune: Optional[Callable[[RetuneDecision], None]] = None,
        applicable: Optional[Callable[[Plan, Plan], bool]] = None,
    ):
        if clear_ratio >= tolerance:
            raise ValueError(
                "clear_ratio must sit below tolerance (hysteresis)"
            )
        self.tolerance = tolerance
        self.clear_ratio = clear_ratio
        self.action_streak = max(1, action_streak)
        self.min_points = max(1, min_points)
        if max_retunes is None:
            max_retunes = envspec.get_int(
                EnvKey.AUTOPILOT_MAX_RETUNES, 2
            )
        self.max_retunes = max(0, int(max_retunes))
        self._on_retune = on_retune
        self._applicable = applicable
        self._lock = threading.Lock()
        self._window = window
        self._points: deque[float] = deque(maxlen=window)
        self._nodes: dict[int, _NodeSteps] = {}
        self._plan: Optional[Plan] = None
        self._alternatives: list[Plan] = []
        self._streak = 0
        self._retunes_used = 0
        self._calibrated = False
        self._last_mfu: Optional[float] = None

    # ------------------------------------------------------------- arming

    def arm(self, plan: Plan, alternatives: list[Plan]) -> None:
        """Install the launched plan and its ranked retune menu; resets
        the measurement window (a fresh plan gets a fresh verdict).

        A ``source="model"`` prediction is CALIBRATED from the first
        healthy window before it can be contradicted: the roofline's
        constants rank candidates against each other, but its absolute
        scale is backend-dependent (parallel/cost_model.py says so
        outright) — the contradiction signal for an analytic plan is a
        DEGRADATION relative to its own early steps (sick host, data
        stall), not disagreement with the roofline's absolute guess.
        ``source="history"`` predictions are real measurements and are
        held to directly."""
        with self._lock:
            self._plan = plan
            self._alternatives = list(alternatives)
            self._points.clear()
            self._streak = 0
            self._calibrated = plan.source == "history"
        logger.info(
            "autopilot armed: plan %s pred %.4fs/step, %d alternatives, "
            "%d/%d retunes used", plan.name, plan.pred_step_s,
            len(alternatives), self._retunes_used, self.max_retunes,
        )

    def export_state(self) -> dict:
        """Armed plan + retune budget for the master state snapshot
        (DESIGN.md §26). The contradiction streak/window deliberately
        stay out: post-restart metrics deltas re-baseline anyway, and a
        retune must be re-earned by fresh evidence — but the BUDGET
        already charged must survive, or a crash-restart would re-grant
        spent retunes (the double-retune hazard)."""
        with self._lock:
            return {
                "plan": self._plan.to_json() if self._plan else "",
                "alternatives": [p.to_json()
                                 for p in self._alternatives],
                "retunes_used": self._retunes_used,
            }

    def restore_state(self, state: dict) -> None:
        with self._lock:
            self._retunes_used = max(
                self._retunes_used, int(state.get("retunes_used", 0))
            )
        plan_json = state.get("plan", "")
        if not plan_json:
            return
        try:
            plan = Plan.from_json(plan_json)
            alternatives = [Plan.from_json(a)
                            for a in state.get("alternatives", ())]
        except (ValueError, TypeError, KeyError):
            logger.warning("unparseable autopilot snapshot state; "
                           "controller stays unarmed", exc_info=True)
            return
        self.arm(plan, alternatives)

    @property
    def armed(self) -> bool:
        with self._lock:
            return self._plan is not None

    @property
    def plan(self) -> Optional[Plan]:
        with self._lock:
            return self._plan

    @property
    def retunes_used(self) -> int:
        with self._lock:
            return self._retunes_used

    # ---------------------------------------------------------- ingestion

    def observe_snapshot(self, node_id: int, samples: list
                         ) -> Optional[RetuneDecision]:
        """Feed one pushed registry snapshot (the servicer calls this
        beside the straggler detector); cheap no-op when unarmed or the
        push carries no step histogram."""
        if not self.armed:
            return None
        stats = _step_stats(samples)
        if stats is None:
            return None
        mfu = _mfu_value(samples)
        with self._lock:
            tracker = self._nodes.setdefault(node_id, _NodeSteps())
            step_s = tracker.delta(*stats)
            if mfu is not None:
                self._last_mfu = mfu
        if step_s is None:
            return None
        return self.observe_step_time(step_s)

    def observe_step_time(self, step_s: float
                          ) -> Optional[RetuneDecision]:
        """Direct feed (in-process harnesses, the trainer-side loop);
        returns the decision when this observation fired a retune."""
        if step_s <= 0:
            return None
        with self._lock:
            if self._plan is None:
                return None
            self._points.append(step_s)
            decision = self._evaluate_locked()
        if decision is not None:
            self._publish(decision)
        return decision

    # ---------------------------------------------------------- evaluation

    def _evaluate_locked(self) -> Optional[RetuneDecision]:
        plan = self._plan
        if len(self._points) < self.min_points:
            return None
        measured = statistics.median(self._points)
        if not self._calibrated or plan.pred_step_s <= 0:
            plan.pred_step_s = measured
            self._calibrated = True
            _pred_step_gauge.set(round(measured, 6))
            logger.info(
                "autopilot calibrated plan %s baseline to %.4fs/step "
                "(analytic prediction replaced by the first healthy "
                "window)", plan.name, measured,
            )
            return None
        ratio = measured / plan.pred_step_s
        _step_ratio_gauge.set(round(ratio, 4))
        if ratio > self.tolerance:
            self._streak += 1
        elif ratio < self.clear_ratio:
            self._streak = 0
        _contradiction_streak.set(self._streak)
        if self._streak < self.action_streak:
            return None
        if self._retunes_used >= self.max_retunes:
            # budget spent: keep journal-visible evidence flowing (the
            # ratio gauge) but never thrash — the §24 runbook case
            return None
        target = self._pick_alternative_locked(plan)
        if target is None:
            return None
        self._retunes_used += 1
        evidence = {
            "measured_step_s": round(measured, 6),
            "pred_step_s": round(plan.pred_step_s, 6),
            "ratio": round(ratio, 4),
            "streak": self._streak,
            "tolerance": self.tolerance,
            "mfu": round(self._last_mfu, 4)
            if self._last_mfu is not None else None,
            "retunes_used": self._retunes_used,
            "max_retunes": self.max_retunes,
        }
        path = choose_path(plan, target)
        # re-arm on the target: its own prediction becomes the new
        # baseline and the window restarts clean
        self._alternatives = [
            p for p in self._alternatives
            if p.fingerprint != target.fingerprint
        ] + [plan]
        self._plan = target
        self._points.clear()
        self._streak = 0
        self._calibrated = target.source == "history"
        return RetuneDecision(
            from_plan=plan, to_plan=target, path=path, evidence=evidence
        )

    def _pick_alternative_locked(self, plan: Plan) -> Optional[Plan]:
        for cand in sorted(self._alternatives,
                           key=lambda p: (p.pred_step_s, p.rank)):
            if cand.fingerprint == plan.fingerprint:
                continue
            if self._applicable is not None \
                    and not self._applicable(plan, cand):
                continue
            return cand
        return None

    def _publish(self, decision: RetuneDecision) -> None:
        _retunes_total.labels(decision.path).inc()
        verdict_span = get_journal().emit(
            "autopilot_retune",
            from_plan=decision.from_plan.name,
            from_fingerprint=decision.from_plan.fingerprint,
            to_plan=decision.to_plan.name,
            to_fingerprint=decision.to_plan.fingerprint,
            to_source=decision.to_plan.source,
            path=decision.path,
            **decision.evidence,
        )
        decision.sctx = format_ctx(current_trace_id(), verdict_span)
        logger.warning(
            "autopilot retune: %s -> %s via %s (measured %.4fs vs "
            "pred %.4fs, streak %d, %d/%d retunes)",
            decision.from_plan.name, decision.to_plan.name,
            decision.path, decision.evidence["measured_step_s"],
            decision.evidence["pred_step_s"],
            decision.evidence["streak"],
            decision.evidence["retunes_used"], self.max_retunes,
        )
        if self._on_retune is not None:
            try:
                self._on_retune(decision)
            except Exception:  # noqa: BLE001 - the hook must not kill ingest
                logger.exception("autopilot on_retune hook failed")
