"""Trainer-side plan application: retune without a restart.

The controller's decision (``autopilot_retune``) names a target plan;
this module morphs the RUNNING trainer onto it in-process:

1. build the target strategy's step program through the existing
   ``load_or_compile`` path (so a plan the fallback precompiler or a
   previous incarnation already compiled loads in ~0.1s instead of
   paying XLA again — the same warm path a launch takes);
2. move the live state onto the target layout: each leaf is host-
   gathered off its current sharding and ``device_put`` onto the target
   program's exact state sharding (the PR-6 reshard semantics; for a
   ``hot`` retune — same mesh, same schedule — this is a near-no-op
   re-put);
3. launder the moved tree (``compile_cache.launder`` — the §17 CPU
   buffer-adoption hazard: a host-built tree must never reach a
   deserialized donating executable un-re-staged).

``can_apply`` is the trainer-side applicability predicate (it builds
the real target mesh on this world); ``plan_applicable`` is its
device-free master-side mirror the controller consults before arming a
retune. Both encode the same rule: this applier morphs SPMD↔SPMD plans
whose batch geometry matches the running loader (the data pipeline
keeps streaming untouched through a retune); SPMD↔MPMD rescheduling
additionally requires the runtime rebuild the example wires
(``MpmdTrain`` construction), so it is only offered where that path is
present.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Optional

from dlrover_tpu.autopilot.planner import Plan
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_apply_seconds = registry().histogram(
    "dlrover_tpu_autopilot_apply_seconds",
    "wall time of one in-process retune application (program build/"
    "load + state move + launder), by path",
    label_names=("path",),
)


def can_apply(current: Plan, target: Plan,
              step_batch: int | None = None) -> bool:
    """True when :func:`apply_plan` can morph ``current`` into
    ``target`` on the live job: SPMD on both sides and, when the
    caller states its per-step global batch, a target mesh that can
    shard it. The assembled batch shape ``[accum, step_batch, ...]``
    is independent of the data-parallel width (step_batch =
    global/accum), so a dp-width change IS retunable — only a mesh
    whose batch axes don't divide the step batch (or that fails to
    build on this world) is a restart-class change."""
    if current.schedule != "spmd" or target.schedule != "spmd":
        return False
    if step_batch is not None:
        try:
            import jax

            from dlrover_tpu.parallel.mesh import data_parallel_size

            mesh = target.strategy().build_mesh(jax.devices())
            if step_batch % data_parallel_size(mesh):
                return False
        except (ValueError, AssertionError):
            return False
    return True


def plan_applicable(current: Plan, target: Plan,
                    step_batch: int | None = None) -> bool:
    """Device-free mirror of :func:`can_apply` for the MASTER-side
    controller: same schedule gate and dp-width divisibility, resolved
    arithmetically from the plan's stamped ``mesh_axes``/``n_devices``
    instead of building a mesh over the caller's own devices (the
    master's device set is not the trainer's). Wired as the
    controller's ``applicable`` predicate so a retune the trainer's
    apply path would veto is never armed, journaled, or charged
    against the retune budget."""
    if current.schedule != "spmd" or target.schedule != "spmd":
        return False
    if step_batch:
        from dlrover_tpu.parallel.mesh import MeshSpec

        n = target.n_devices or current.n_devices
        if not n:
            return True  # no stamped world: only the schedule gate
        try:
            sizes = MeshSpec(axes=dict(target.mesh_axes)).resolved(n)
        except (ValueError, TypeError):
            return False
        dp_width = 1
        for axis in ("data", "fsdp"):
            dp_width *= sizes.get(axis, 1)
        if step_batch % dp_width:
            return False
    return True


@dataclasses.dataclass
class AppliedPlan:
    compiled: Any
    state: Any
    path: str
    seconds: float
    cache_hit: bool = False


def apply_plan(
    target: Plan,
    *,
    state: Any,
    loss_fn_for,
    init_params_fn,
    logical_params,
    optimizer,
    model_cfg=None,
    path: str = "hot",
    cache=None,
    num_nodes: int = 1,
    example_batch: Any = None,
    extra_fingerprint: Optional[dict] = None,
) -> AppliedPlan:
    """Build the target plan's program and carry the live state onto
    it. Returns the new (compiled, state) pair — the caller swaps them
    into the running trainer (``ElasticTrainer.swap_compiled``); no
    process restarts, no rendezvous."""
    import jax
    import numpy as np

    from dlrover_tpu.parallel import compile_cache as cc
    from dlrover_tpu.trainer.train_step import compile_train

    start = time.monotonic()
    strategy = target.strategy()
    mesh = strategy.build_mesh()
    compiled = compile_train(
        strategy=strategy,
        mesh=mesh,
        loss_fn=loss_fn_for(strategy, mesh),
        init_params_fn=init_params_fn,
        logical_params=logical_params,
        optimizer=optimizer,
    )
    cache_hit = False
    if example_batch is not None and cc.aot_cache_enabled():
        # the launch path's load_or_compile, verbatim: a retune target
        # the fallback daemon (or a sibling) already built loads warm
        state_abs = jax.eval_shape(compiled.init, jax.random.PRNGKey(0))
        state_abs = jax.tree.map(
            lambda leaf, sh: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=sh
            ),
            state_abs, compiled.state_shardings,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
        )
        batch_abs = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(
                np.shape(a), np.asarray(a).dtype,
                sharding=compiled.batch_sharding,
            ),
            example_batch,
        )
        key, key_inputs = cc.compile_fingerprint(
            num_nodes=num_nodes,
            total_devices=len(jax.devices()),
            mesh_axes=dict(mesh.shape),
            model=model_cfg if model_cfg is not None else target.model,
            strategy=strategy,
            args_signature=cc.abstract_signature((state_abs, batch_abs)),
            extra=extra_fingerprint,
        )
        aot = cc.load_or_compile(
            key, key_inputs,
            compile_fn=lambda: compiled.step.lower(
                state_abs, batch_abs).compile(),
            cache=cache,
        )
        compiled.step = aot.fn
        compiled.cache_hit = aot.cache_hit
        compiled.flops_per_step = aot.flops
        cache_hit = bool(aot.cache_hit)

    # state move: host-gather each leaf and re-put under the TARGET
    # program's exact sharding (exact, not remapped — the new program
    # dictates the layout); hot retunes re-put onto identical shardings
    def _move(leaf, sharding):
        return jax.device_put(
            np.asarray(jax.device_get(leaf)), sharding
        )

    new_state = jax.tree.map(_move, state, compiled.state_shardings)
    # host-built tree + (possibly deserialized, donating) executable:
    # re-stage before the first step call (the §17 hazard)
    new_state = cc.launder(new_state)
    dur = time.monotonic() - start
    _apply_seconds.labels(path).observe(dur)
    logger.info(
        "autopilot applied plan %s via %s in %.2fs (aot %s)",
        target.name, path, dur, "hit" if cache_hit else "miss",
    )
    return AppliedPlan(
        compiled=compiled, state=new_state, path=path, seconds=dur,
        cache_hit=cache_hit,
    )
