"""Autopilot planner: feasible (strategy × mesh × schedule) points,
ranked, as typed plans.

The ``auto_accelerate`` front half (PAPER.md §1), built from parts the
repo already owns: every candidate point is AOT-lowered on the host
(``parallel/dry_run.py`` — per-device peak memory and FLOPs without
touching a chip), filtered by the device-memory envelope
(``parallel/auto.py device_hbm_bytes``, overridable via
``DLROVER_TPU_DEVICE_HBM_BYTES`` for CPU/tunneled backends), and ranked
by the schedule-aware roofline (``parallel/cost_model.py``). The MPMD
schedule axis (2412.14374) enters as an extra point per eligible stage
count, costed with the per-stage heterogeneous estimates behind
``--schedule auto``.

Measured history outranks the model: when
:class:`~dlrover_tpu.autopilot.history.PlanHistory` holds a measurement
for a point at this exact workload shape, that point is re-scored from
the measurement (``source="history"`` — the Brain-style cross-job
learning), so a fleet's second job with the same model/mesh fingerprint
starts from evidence, not estimates.

The winner (and the full ranked list — the controller's retune menu)
is journaled as ``autopilot_plan`` and returned as typed
:class:`Plan` records the trainer launches directly through the
existing ``load_or_compile`` path.
"""

from __future__ import annotations

import dataclasses
import json
import math
import statistics
from typing import Any, Optional, Sequence

from dlrover_tpu.autopilot.history import (
    PlanHistory,
    canonical_strategy_json,
    plan_fingerprint,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_plans_total = registry().counter(
    "dlrover_tpu_autopilot_plans_total",
    "autopilot plans emitted, by ranking evidence of the winner "
    "(model = analytic cost model, history = measured history)",
    label_names=("source",),
)
_feasible_points = registry().gauge(
    "dlrover_tpu_autopilot_feasible_points",
    "candidate (strategy x mesh x schedule) points that AOT-compiled "
    "and fit the device-memory envelope in the latest planner run",
)
_pred_step_gauge = registry().gauge(
    "dlrover_tpu_autopilot_pred_step_seconds",
    "the launched plan's predicted step time (cost model or measured "
    "history) — the controller's contradiction baseline",
)

# bump when the enumeration or ranking changes in a way that must
# invalidate persisted plan caches
_PLANNER_VERSION = 1


@dataclasses.dataclass
class Plan:
    """One launchable point: strategy + mesh + schedule with its
    prediction — everything the trainer needs to launch through
    ``load_or_compile`` and the controller needs to judge the launch."""

    name: str = "dp"
    strategy_json: str = ""
    schedule: str = "spmd"            # "spmd" | "mpmd"
    mesh_axes: dict = dataclasses.field(default_factory=dict)
    pred_step_s: float = 0.0
    # the raw cost-model estimate, kept beside pred_step_s (which may
    # be a measurement or a calibrated estimate) so a cache reload can
    # re-run the history calibration from scratch
    analytic_step_s: float = 0.0
    pred_peak_bytes: int = 0
    pred_flops: float = 0.0
    source: str = "model"             # "model" | "history"
    fingerprint: str = ""
    # workload identity (history.shape_key fields)
    model: str = ""
    n_devices: int = 0
    batch: int = 0
    seq: int = 0
    hbm_gb: float = 0.0
    rank: int = 0

    def strategy(self):
        from dlrover_tpu.parallel.strategy import Strategy

        return Strategy.from_json(self.strategy_json)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "Plan":
        return cls(**json.loads(text))


@dataclasses.dataclass
class RankedPlans:
    """Planner output: ``plans[0]`` is the launch, the tail is the
    controller's retune menu; ``reports`` keeps every dry-run (also the
    infeasible ones — the journal's evidence that OOM points were seen
    and rejected, never launched)."""

    plans: list = dataclasses.field(default_factory=list)
    reports: list = dataclasses.field(default_factory=list)
    from_cache: bool = False

    @property
    def winner(self) -> Plan:
        return self.plans[0]

    def alternatives(self) -> list:
        return self.plans[1:]

    def to_json(self) -> str:
        return json.dumps({
            "version": _PLANNER_VERSION,
            "plans": [dataclasses.asdict(p) for p in self.plans],
        }, indent=2)

    @classmethod
    def from_json(cls, text: str) -> "RankedPlans":
        data = json.loads(text)
        if data.get("version") != _PLANNER_VERSION:
            raise ValueError("planner version mismatch")
        return cls(plans=[Plan(**p) for p in data["plans"]],
                   from_cache=True)


def default_points(num_devices: int, *, mpmd_stages: int = 0
                   ) -> list[tuple[Any, str]]:
    """The enumeration: strategy presets in preference order (cheapest
    collectives first, ``parallel/auto.py``) each as an SPMD point,
    plus an MPMD pipeline point per eligible stage count — the
    schedule axis the MPMD scheduling work (2412.14374) argues for."""
    from dlrover_tpu.parallel import strategy as st
    from dlrover_tpu.parallel.auto import default_candidates

    points: list[tuple[Any, str]] = [
        (s, "spmd") for s in default_candidates(num_devices)
    ]
    if mpmd_stages > 1 and num_devices % mpmd_stages == 0 \
            and num_devices // mpmd_stages >= 1:
        points.append((st.mpmd(pipeline_size=mpmd_stages), "mpmd"))
    return points


def _mpmd_estimate(strategy, base_report, *, model_cfg, batch: int,
                   seq: int, num_devices: int, hw=None):
    """(est_step_s, peak_bytes) for an MPMD point, derived from the
    base SPMD dry-run: the per-stage programs run the SAME math, so the
    roofline work/traffic terms carry over and only the schedule terms
    (per-stage heterogeneous 1F1B fill/drain + boundary p2p) are new.
    Peak memory divides by the stage count — each stage's devices hold
    only that stage's params/optimizer state (the §21 ZeRO split) plus
    in-flight microbatch activations (bounded by the 1F1B window, ≤ the
    monolith's activation set)."""
    from dlrover_tpu.parallel.cost_model import (
        PipelineSchedule,
        estimate_step_time,
    )

    extra = strategy.extra or {}
    stages = int(extra.get("pipeline_stages", 2) or 2)
    micro = int(extra.get("pipeline_microbatches", 0) or 0) or stages
    stage_times: tuple = ()
    if model_cfg is not None:
        try:
            from dlrover_tpu.parallel.mpmd import estimate_stage_times

            stage_times = tuple(estimate_stage_times(
                model_cfg, num_stages=stages, step_batch=batch,
                seq=seq, microbatches=micro, hw=hw,
            ))
        except Exception:  # noqa: BLE001 - fall back to uniform stages
            stage_times = ()
    act_bytes = 0.0
    if model_cfg is not None:
        try:
            import numpy as np

            dt = np.dtype(getattr(model_cfg, "dtype", "float32")).itemsize
            act_bytes = (batch / micro) * seq * model_cfg.d_model * dt
        except Exception:  # noqa: BLE001
            act_bytes = 0.0
    est = estimate_step_time(
        flops=base_report.flops,
        bytes_accessed=base_report.bytes_accessed,
        hw=hw,
        schedule=PipelineSchedule(
            kind="mpmd_1f1b", num_stages=stages, num_microbatches=micro,
            activation_bytes=act_bytes, stage_time_s=stage_times,
        ),
    )
    peak = int(math.ceil(base_report.hbm_bytes / stages)) \
        if base_report.hbm_bytes else 0
    return est.est_step_s, peak


def enumerate_plans(
    *,
    model: str,
    loss_fn_for,
    init_params_fn,
    logical_params,
    optimizer,
    example_batch,
    batch: int,
    seq: int,
    devices: Sequence | None = None,
    points: Sequence[tuple[Any, str]] | None = None,
    hbm_capacity_bytes: Optional[int] = None,
    history: PlanHistory | None = None,
    model_cfg=None,
    mpmd_stages: int = 0,
    hw=None,
) -> RankedPlans:
    """Enumerate, AOT-filter, rank; emit the typed plan list.

    Deterministic by construction: the point list is a fixed preference
    order, scores come from the (deterministic) AOT analyses and cost
    model or from history, and ties break on preference index — two
    runs over the same inputs produce the identical ranked list.
    """
    import jax
    import numpy as np

    from dlrover_tpu.parallel.auto import device_hbm_bytes
    from dlrover_tpu.parallel.dry_run import DryRunReport, dry_run
    from dlrover_tpu.trainer.train_step import compile_train

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    if points is None:
        points = default_points(n, mpmd_stages=mpmd_stages)
    if hbm_capacity_bytes is None:
        hbm_capacity_bytes = device_hbm_bytes(devices[0])
    hbm_gb = round(hbm_capacity_bytes / 2**30, 3) \
        if hbm_capacity_bytes else 0.0

    def build_step(strategy):
        mesh = strategy.build_mesh(devices)
        compiled = compile_train(
            strategy=strategy,
            mesh=mesh,
            loss_fn=loss_fn_for(strategy, mesh),
            init_params_fn=init_params_fn,
            logical_params=logical_params,
            optimizer=optimizer,
        )
        state_abstract = jax.eval_shape(
            compiled.init, jax.random.PRNGKey(0)
        )
        state_abstract = jax.tree.map(
            lambda leaf, s: jax.ShapeDtypeStruct(
                leaf.shape, leaf.dtype, sharding=s
            ),
            state_abstract, compiled.state_shardings,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct),
        )
        batch_abstract = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(
                np.shape(a), np.asarray(a).dtype,
                sharding=compiled.batch_sharding,
            ),
            example_batch,
        )
        return compiled.step, (state_abstract, batch_abstract)

    measured = history.lookup(model, n, batch, seq, hbm_gb) \
        if history is not None else {}

    reports: list[DryRunReport] = []
    scored: list[tuple[float, int, Plan]] = []
    base_spmd_report: DryRunReport | None = None
    for idx, (strategy, schedule) in enumerate(points):
        if schedule == "mpmd":
            # per-stage programs are never one jit program: cost the
            # point off the base SPMD dry-run instead of compiling P×3
            # stage programs here (the launch path compiles them once,
            # through the per-stage compile cache)
            if base_spmd_report is None:
                logger.info("autopilot: no feasible SPMD base for the "
                            "mpmd point; skipping")
                continue
            est_s, peak = _mpmd_estimate(
                strategy, base_spmd_report, model_cfg=model_cfg,
                batch=batch, seq=seq, num_devices=n, hw=hw,
            )
            r = DryRunReport(
                strategy_name=strategy.name, ok=True,
                flops=base_spmd_report.flops, hbm_bytes=peak,
                bytes_accessed=base_spmd_report.bytes_accessed,
                est_step_s=est_s,
            )
        else:
            r = dry_run(build_step, strategy, hw=hw)
        reports.append(r)
        fits = r.fits(hbm_capacity_bytes) if hbm_capacity_bytes else r.ok
        if not fits:
            logger.info(
                "autopilot: %s/%s infeasible (%s, peak %.2f GB > "
                "envelope %.2f GB)", r.strategy_name, schedule,
                r.error or "OOM", r.hbm_bytes / 2**30,
                hbm_capacity_bytes / 2**30 if hbm_capacity_bytes else 0,
            )
            continue
        if schedule == "spmd" and base_spmd_report is None:
            base_spmd_report = r
        sj = canonical_strategy_json(strategy)
        plan = Plan(
            name=f"{strategy.name}/{schedule}",
            strategy_json=sj,
            schedule=schedule,
            mesh_axes=dict(strategy.mesh_axes),
            pred_step_s=r.est_step_s,
            analytic_step_s=r.est_step_s,
            pred_peak_bytes=int(r.hbm_bytes),
            pred_flops=r.flops,
            source="model",
            fingerprint=plan_fingerprint(sj, schedule),
            model=model, n_devices=n, batch=batch, seq=seq,
            hbm_gb=hbm_gb,
        )
        seen = measured.get(sj)
        if seen and seen.get("step_time_s", 0) > 0:
            plan.pred_step_s = seen["step_time_s"]
            plan.source = "history"
        scored.append((r.est_step_s, idx, plan))
    _calibrate_model_preds(scored)
    if not scored:
        raise RuntimeError(
            "autopilot: no candidate point compiled and fit the "
            "device-memory envelope: "
            + "; ".join(f"{r.strategy_name}: {r.error or 'OOM'}"
                        for r in reports)
        )
    scored.sort(key=lambda t: (
        t[2].pred_step_s if t[2].pred_step_s > 0 else math.inf, t[1],
    ))
    plans = []
    for rank, (_, _, plan) in enumerate(scored):
        plan.rank = rank
        plans.append(plan)
    ranked = RankedPlans(plans=plans, reports=reports)
    _journal_plan(ranked)
    return ranked


def _calibrate_model_preds(scored: list) -> None:
    """Put model- and history-sourced predictions on ONE scale.

    The roofline's constants rank candidates against each other but
    its absolute scale is backend-dependent (parallel/cost_model.py
    says so outright) — mixing raw analytic estimates with real
    measurements would let an optimistic estimate outrank a measured
    winner forever. Every plan that has BOTH (analytic est, measured
    step) yields a scale factor; the median factor rescales the plans
    history never saw, so the ranking compares measured-vs-calibrated
    instead of measured-vs-wishful. ``scored`` rows are
    ``(analytic_est_s, preference_idx, plan)`` mutated in place."""
    factors = [
        plan.pred_step_s / est
        for est, _, plan in scored
        if plan.source == "history" and est > 0 and plan.pred_step_s > 0
    ]
    if not factors:
        return
    factor = statistics.median(factors)
    for est, _, plan in scored:
        if plan.source == "model" and est > 0:
            plan.pred_step_s = est * factor


def _journal_plan(ranked: RankedPlans) -> None:
    win = ranked.winner
    _plans_total.labels(win.source).inc()
    _feasible_points.set(len(ranked.plans))
    _pred_step_gauge.set(round(win.pred_step_s, 6))
    get_journal().emit(
        "autopilot_plan",
        plan=win.name, fingerprint=win.fingerprint,
        schedule=win.schedule, source=win.source,
        pred_step_s=round(win.pred_step_s, 6),
        pred_peak_gb=round(win.pred_peak_bytes / 2**30, 3),
        model=win.model, n_devices=win.n_devices, batch=win.batch,
        seq=win.seq, feasible=len(ranked.plans),
        ranked=[p.name for p in ranked.plans],
        cached=ranked.from_cache,
    )
    logger.info(
        "autopilot plan: %s (source=%s, pred %.4fs/step, %d feasible "
        "points)", win.name, win.source, win.pred_step_s,
        len(ranked.plans),
    )


def _workload_fingerprint(init_params_fn, example_batch, n_devices: int,
                          batch: int, seq: int, model: str,
                          mpmd_stages: int) -> str:
    """Cache key for a persisted plan list: everything that determines
    the planner's answer (mirrors ``parallel/auto.py``'s strategy-cache
    fingerprint — a hit for a DIFFERENT workload would launch a plan
    that never passed this workload's fit check)."""
    import hashlib

    import jax
    import numpy as np

    shapes = jax.tree_util.tree_map(
        lambda l: (tuple(l.shape), str(l.dtype)),
        jax.eval_shape(init_params_fn, jax.random.PRNGKey(0)),
    )
    flat, _ = jax.tree_util.tree_flatten_with_path(shapes)
    param_sig = sorted((jax.tree_util.keystr(p), v) for p, v in flat)
    batch_sig = sorted(
        (k, tuple(np.shape(v)), str(np.asarray(v).dtype))
        for k, v in example_batch.items()
    )
    blob = repr((param_sig, batch_sig, n_devices, batch, seq, model,
                 mpmd_stages, _PLANNER_VERSION))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def load_or_plan(cache_path: str, **kwargs) -> RankedPlans:
    """``enumerate_plans`` with a persisted result, the
    ``load_strategy`` analog: an elastic restart reuses the ranked list
    instead of burning the recovery window on N candidate AOT compiles.
    Keyed by the workload fingerprint; any change (shapes, world size,
    planner version) re-runs the search. History still wins: a cached
    list whose winner came from the analytic model is re-ranked against
    the (cheap) history lookup so fresh measurements are never shadowed
    by a stale cache."""
    import os

    import jax

    devices = kwargs.get("devices")
    n = len(devices) if devices is not None else len(jax.devices())
    fp = _workload_fingerprint(
        kwargs["init_params_fn"], kwargs["example_batch"], n,
        kwargs["batch"], kwargs["seq"], kwargs["model"],
        kwargs.get("mpmd_stages", 0),
    )
    history: PlanHistory | None = kwargs.get("history")
    try:
        with open(cache_path) as f:
            data = json.load(f)
        if data.get("fingerprint") == fp:
            ranked = RankedPlans.from_json(json.dumps(data["ranked"]))
            if history is not None:
                _rescore_from_history(ranked, history)
            _journal_plan(ranked)
            logger.info("autopilot: reusing cached plan list from %s",
                        cache_path)
            return ranked
    except (OSError, ValueError, KeyError, TypeError):
        pass
    ranked = enumerate_plans(**kwargs)
    try:
        os.makedirs(os.path.dirname(cache_path) or ".", exist_ok=True)
        tmp = f"{cache_path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({
                "fingerprint": fp,
                "ranked": json.loads(ranked.to_json()),
            }, f, indent=2)
        os.replace(tmp, cache_path)
    except OSError as e:  # cache is best-effort
        logger.warning("could not persist plan cache: %s", e)
    return ranked


def _rescore_from_history(ranked: RankedPlans,
                          history: PlanHistory) -> None:
    """Re-run the history substitution + calibration over a cached plan
    list, from the stored analytic estimates — measurements recorded
    since the cache was written must never be shadowed by it."""
    win = ranked.winner
    measured = history.lookup(win.model, win.n_devices, win.batch,
                              win.seq, win.hbm_gb)
    rows = []
    for plan in ranked.plans:
        seen = measured.get(canonical_strategy_json(plan.strategy_json))
        if seen and seen.get("step_time_s", 0) > 0:
            plan.pred_step_s = seen["step_time_s"]
            plan.source = "history"
        elif plan.analytic_step_s > 0:
            plan.pred_step_s = plan.analytic_step_s
            plan.source = "model"
        rows.append((plan.analytic_step_s, plan.rank, plan))
    _calibrate_model_preds(rows)
    ranked.plans.sort(
        key=lambda p: (p.pred_step_s if p.pred_step_s > 0 else math.inf,
                       p.rank)
    )
    for rank, plan in enumerate(ranked.plans):
        plan.rank = rank
