"""Strategy autopilot: closed-loop ``auto_accelerate`` for JAX.

PAPER.md's ATorch centerpiece is ``auto_accelerate`` — automatic
strategy search over DP/ZeRO/FSDP/TP/PP — plus the Brain service that
retunes running jobs from observed metrics. This package is the loop
that connects the repo's existing ingredients (DESIGN.md §24):

- :mod:`~dlrover_tpu.autopilot.planner` enumerates feasible
  (strategy preset × mesh shape × schedule) points for the current
  world size via AOT lowering (``parallel/dry_run.py`` — no chips
  touched), ranks them with the schedule-aware cost model, and emits a
  typed :class:`~dlrover_tpu.autopilot.planner.Plan` the trainer
  launches through the existing ``load_or_compile`` path.
- :mod:`~dlrover_tpu.autopilot.controller` runs master-side, riding
  the trainer snapshot pushes like ``telemetry/anomaly.py``: it
  compares live step time / MFU against the plan's prediction and, on
  sustained contradiction, picks the best ranked alternative and
  applies it the cheapest way that works (hot program swap, the PR-6
  reshard path, or an SPMD↔MPMD reschedule), journaling an
  ``autopilot_retune`` decision trail — bounded retunes per job.
- :mod:`~dlrover_tpu.autopilot.history` persists (plan fingerprint →
  measured step_s/MFU) into the strategy-engine measured history so
  the next job with the same workload fingerprint seeds its ranking
  from measurements instead of the analytic model — the Brain-style
  cross-job learning of PAPER.md §1.
- :mod:`~dlrover_tpu.autopilot.apply` is the trainer-side applier: it
  rebuilds the step program for the new plan (through the compile
  cache), reshards the live state onto the new layout, and launders it
  — the job never restarts.
"""

from dlrover_tpu.autopilot.controller import (  # noqa: F401
    AutopilotController,
    RetuneDecision,
    choose_path,
)
from dlrover_tpu.autopilot.history import (  # noqa: F401
    PlanHistory,
    canonical_strategy_json,
    plan_fingerprint,
    shape_key,
)
from dlrover_tpu.autopilot.planner import (  # noqa: F401
    Plan,
    RankedPlans,
    enumerate_plans,
    load_or_plan,
)
