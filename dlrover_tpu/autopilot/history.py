"""The autopilot's measured-history vocabulary and store.

One fingerprint vocabulary (ISSUE-13 satellite): the successive-halving
winner (``parallel/search.py``), the strategy-engine measured history
(``parallel/engine_service.py``) and the autopilot planner all key
measurements the same way —

- :func:`shape_key` is the workload identity ``(model, n_devices,
  batch, seq, hbm_gb)`` — exactly the tuple the engine service uses for
  ``_measured``/``_observations`` and its sqlite primary key; a
  measurement only transfers at the exact shape it ran at (any other
  batch/seq never passed the fit check).
- :func:`canonical_strategy_json` is the per-plan identity within a
  shape key: the strategy's JSON with sorted keys and no whitespace, so
  ``Strategy.to_json`` (indent=2, field order) and a planner-minted
  plan compare equal. The schedule needs no separate axis — it is
  encoded in the strategy itself (``extra.mpmd`` / the ``pipeline``
  preset), which is what lets the engine service stay schedule-blind.
- :func:`plan_fingerprint` is the short stable digest of that identity
  used in journals and the retune decision trail.

:class:`PlanHistory` reads/writes the engine-service store through
either a live :class:`~dlrover_tpu.parallel.engine_service.\
StrategyEngineClient` or an in-process (unstarted) service with a
sqlite path — same message types, same store, so a search winner
recorded by job A seeds job B's planner ranking.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


def shape_key(model: str, n_devices: int, batch: int, seq: int,
              hbm_gb: float = 0.0) -> tuple:
    """The workload identity tuple — byte-for-byte the key
    ``StrategyEngineService`` indexes its measured history by."""
    return (str(model), int(n_devices), int(batch), int(seq),
            float(hbm_gb))


def canonical_strategy_json(strategy: Any) -> str:
    """Whitespace/ordering-normalized strategy JSON.

    Accepts a ``Strategy``, a JSON string, or an already-parsed dict;
    two serializations of the same strategy always canonicalize to the
    same string, so dict lookups keyed on it behave like strategy
    equality."""
    if hasattr(strategy, "to_json"):
        obj = json.loads(strategy.to_json())
    elif isinstance(strategy, str):
        obj = json.loads(strategy)
    else:
        obj = strategy
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def plan_fingerprint(strategy: Any, schedule: str = "spmd") -> str:
    """Short digest identifying one plan point (strategy + schedule)
    for journals and retune evidence. The schedule rides along even
    though the strategy JSON implies it — the trail must stay readable
    without parsing strategy extras."""
    blob = canonical_strategy_json(strategy) + "|" + str(schedule)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


class PlanHistory:
    """Measured (plan → step_s/MFU) history over the engine-service
    store.

    Backends (first non-None wins): ``client`` — a typed
    ``StrategyEngineClient`` talking to a running service; ``service``
    — an in-process ``StrategyEngineService`` (started or not: reads
    and writes go through ``handle()`` directly); ``db_path`` — sugar
    that builds an in-process service around the sqlite file, giving a
    masterless job cross-run persistence with the exact schema a later
    shared engine would warm-start from.
    """

    def __init__(self, client=None, service=None, db_path: str = ""):
        self._client = client
        self._service = service
        if self._client is None and self._service is None and db_path:
            from dlrover_tpu.parallel.engine_service import (
                StrategyEngineService,
            )

            self._service = StrategyEngineService(db_path=db_path)
            self._owns_service = True
        else:
            self._owns_service = False

    @property
    def available(self) -> bool:
        return self._client is not None or self._service is not None

    # ------------------------------------------------------------- reads

    def lookup(self, model: str, n_devices: int, batch: int, seq: int,
               hbm_gb: float = 0.0) -> dict[str, dict]:
        """{canonical_strategy_json: {"step_time_s": s, "mfu": m}} for
        the shape key; {} when the store is empty/unreachable (the
        planner then ranks purely analytically)."""
        if not self.available:
            return {}
        try:
            if self._client is not None:
                obs = self._client.get_observations(
                    model, n_devices, batch=batch, seq=seq, hbm_gb=hbm_gb
                )
            else:
                from dlrover_tpu.common import messages as m

                obs = list(self._service.handle(
                    m.StrategyObservationsRequest(
                        model=model, n_devices=n_devices, batch=batch,
                        seq=seq, hbm_gb=hbm_gb,
                    )
                ).observations)
        except (ConnectionError, RuntimeError, OSError) as e:
            logger.warning("plan history lookup failed: %s", e)
            return {}
        out: dict[str, dict] = {}
        for o in obs:
            try:
                key = canonical_strategy_json(o["strategy_json"])
            except (KeyError, ValueError, TypeError):
                continue
            out[key] = {
                "step_time_s": float(o.get("step_time_s", 0.0)),
                "mfu": float(o.get("mfu", 0.0) or 0.0),
            }
        return out

    # ------------------------------------------------------------ writes

    def record(self, strategy: Any, step_time_s: float, *, model: str,
               n_devices: int, batch: int, seq: int,
               hbm_gb: float = 0.0, mfu: Optional[float] = None) -> bool:
        """Report one measured (plan → step_s/MFU) observation; best
        effort — history is an accelerant, never a correctness
        dependency."""
        if not self.available or step_time_s <= 0:
            return False
        sj = canonical_strategy_json(strategy)
        try:
            if self._client is not None:
                self._client.report_measurement(
                    model=model, n_devices=n_devices, strategy=sj,
                    step_time_s=float(step_time_s), batch=batch,
                    seq=seq, hbm_gb=hbm_gb, mfu=float(mfu or 0.0),
                )
            else:
                from dlrover_tpu.common import messages as m

                self._service.handle(m.StrategyMeasurement(
                    model=model, n_devices=n_devices, batch=batch,
                    seq=seq, hbm_gb=hbm_gb, strategy_json=sj,
                    step_time_s=float(step_time_s),
                    mfu=float(mfu or 0.0),
                ))
            return True
        except (ConnectionError, RuntimeError, OSError, ValueError) as e:
            logger.warning("plan history record failed: %s", e)
            return False

    def close(self) -> None:
        if self._owns_service and self._service is not None:
            self._service.stop()
            self._service = None
