"""Goodput accounting — the reference's headline metric.

Reference analog: DLRover's core claim is raising large-job goodput from
69% to >95% via elastic fault tolerance + flash checkpoints
(dlrover README.md:54-55). Goodput here follows that definition:

    goodput = productive training time / total wall-clock time

where time spent in rendezvous, process respawn, recompilation,
checkpoint restore, re-computing rolled-back steps, and straggling all
count as lost.

Two measurement paths share this module:

- ``GoodputRecorder`` + ``compute_goodput``: a per-node JSONL event log
  written by the trainer (one ``start`` per incarnation, one ``step``
  per optimizer step) and an offline aggregator. This is what
  ``bench.py`` and the e2e tests use — it survives process death because
  every event is an O_APPEND line.
- ``SpeedMonitor.goodput()`` (master/speed_monitor.py): a live estimate
  from the steps workers already report, for JobStats observability.

Accounting model: each *retained* step (one that contributed to final
progress, i.e. was never rolled back and re-run) earns its own duration,
capped at the p95 of steady-state step durations. Re-executed steps earn
nothing for their discarded run; restart gaps and outlier steps (which
hide restarts/compiles) fall out as (total - productive). The p95 cap
keeps one-time costs that hide inside a step (first-step compile after a
restart) out of the productive column while still counting ordinary
step-to-step jitter as training time — the reference's definition
charges only downtime/rollback/restart against goodput, not variance.
"""

from __future__ import annotations

import dataclasses
import json
import os
import statistics
import time
from typing import Iterable


@dataclasses.dataclass
class GoodputReport:
    goodput: float          # productive / total, from first step onward
    goodput_cold: float     # productive / total incl. first-compile window
    total_s: float          # warm window (first step -> last event)
    total_cold_s: float     # first start event -> last event
    productive_s: float
    n_steps: int            # unique steps that reached final progress
    n_incarnations: int
    median_step_s: float
    cap_step_s: float       # p95 steady duration: per-step credit cap
    redone_steps: int       # step executions discarded by rollback
    lost_s: float           # warm-window lost time

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        for k, v in d.items():
            if isinstance(v, float):
                d[k] = round(v, 4)
        return d


class GoodputRecorder:
    """Append-only JSONL event log; one recorder per trainer incarnation.

    Events: ``{"ev": "start", "t": ..., "restart": N}`` once at
    construction, ``{"ev": "step", "step": G, "t": ...}`` after each
    optimizer step, ``{"ev": "done", "t": ...}`` at clean exit. Appends
    are line-atomic (single short write with O_APPEND), so a SIGKILL
    mid-run loses at most the final line.
    """

    def __init__(self, path: str, restart_count: int = 0):
        self._path = path
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        self._f = open(path, "a", buffering=1)
        self._write({"ev": "start", "t": time.time(),
                     "restart": restart_count})

    def _write(self, event: dict) -> None:
        self._f.write(json.dumps(event) + "\n")

    def step(self, step: int) -> None:
        self._write({"ev": "step", "step": int(step), "t": time.time()})

    def done(self) -> None:
        self._write({"ev": "done", "t": time.time()})

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


def _parse_events(path: str) -> list[dict]:
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue  # torn final line after a SIGKILL
            if isinstance(ev, dict) and "t" in ev:
                events.append(ev)
    return events


def compute_goodput(
    path: str | Iterable[str],
    end_time: float | None = None,
    start_time: float | None = None,
) -> GoodputReport:
    """Aggregate one node's goodput log (or pick the most complete of
    several nodes' logs).

    ``start_time``/``end_time`` widen the cold window to an external
    observer's clock (e.g. bench.py's just-before-launch timestamp), so
    process spawn and interpreter startup count as lost too.
    """
    if not isinstance(path, (str, os.PathLike)):
        reports = [compute_goodput(p, end_time, start_time) for p in path]
        if not reports:
            raise ValueError("no goodput logs given")
        return max(reports, key=lambda r: r.n_steps)

    events = _parse_events(str(path))
    if not events:
        raise ValueError(f"no events in goodput log {path}")

    # Walk incarnations in file order; O_APPEND keeps that equal to time
    # order even across process restarts.
    retained: dict[int, float] = {}   # step -> raw duration of kept run
    steady: list[float] = []
    redone = 0
    n_incarnations = 0
    first_start_t = None
    first_step_t = None
    last_t = events[0]["t"]
    prev_t = None
    first_of_incarnation = True
    for ev in events:
        last_t = ev["t"]
        if ev["ev"] == "start":
            n_incarnations += 1
            if first_start_t is None:
                first_start_t = ev["t"]
            prev_t = ev["t"]
            first_of_incarnation = True
        elif ev["ev"] == "step":
            if prev_t is None:  # torn log missing its start line
                prev_t = ev["t"]
            dur = max(0.0, ev["t"] - prev_t)
            step = int(ev["step"])
            if step in retained:
                redone += 1
            retained[step] = dur
            if not first_of_incarnation:
                steady.append(dur)
            if first_step_t is None:
                first_step_t = ev["t"]
            prev_t = ev["t"]
            first_of_incarnation = False

    if not retained:
        raise ValueError(f"no step events in goodput log {path}")

    basis = steady if steady else list(retained.values())
    median = statistics.median(basis)
    # p95 credit cap per retained step: a genuinely-faster step earns
    # its own (smaller) duration so productive never exceeds real
    # compute time; ordinary jitter under the cap counts as training,
    # while compile-bearing post-restart first steps and pathological
    # outliers spill into the lost column.
    cap = sorted(basis)[min(len(basis) - 1, int(0.95 * len(basis)))]
    productive = sum(min(d, cap) for d in retained.values())

    t_end = last_t if end_time is None else max(last_t, end_time)
    t_cold = first_step_t if first_start_t is None else first_start_t
    if start_time is not None:
        t_cold = min(t_cold, start_time)
    # Warm window starts one step-credit before the first step
    # completion so the first step itself is inside the window.
    t_warm = first_step_t - cap
    total_cold = max(1e-9, t_end - t_cold)
    total_warm = max(1e-9, min(total_cold, t_end - t_warm))
    productive = min(productive, total_warm)

    return GoodputReport(
        goodput=productive / total_warm,
        goodput_cold=productive / total_cold,
        total_s=total_warm,
        total_cold_s=total_cold,
        productive_s=productive,
        n_steps=len(retained),
        n_incarnations=n_incarnations,
        median_step_s=median,
        cap_step_s=cap,
        redone_steps=redone,
        lost_s=total_warm - productive,
    )
