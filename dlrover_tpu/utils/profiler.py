"""Profiling: compiled FLOPs, MFU accounting, step timing, trace export.

Reference analog: ATorch's AProfiler (atorch/atorch/utils/prof.py:38 —
monkey-patches torch functionals to count FLOPs/MACs per module) and the
GPU timeline tracer (utils/tracer.py). XLA makes the counting half free:
``jit(f).lower(...).compile().cost_analysis()`` reports the compiled
program's exact FLOPs, so MFU comes from arithmetic instead of per-op
formula tables; the timeline half is ``jax.profiler`` (xplane traces for
Perfetto/TensorBoard).
"""

from __future__ import annotations

import contextlib
import dataclasses
import statistics
import time
from typing import Any, Callable

import numpy as np

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

# bf16 peak FLOP/s per chip by device kind (public spec sheets).
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def device_peak_flops(device=None) -> float | None:
    """bf16 peak FLOP/s of one chip, or None when unknown (CPU)."""
    import jax

    device = device or jax.devices()[0]
    return PEAK_FLOPS.get(getattr(device, "device_kind", ""))


def executable_flops(compiled) -> float:
    """FLOPs of an ALREADY-compiled executable (no lower/compile).

    Works for both fresh ``jit(f).lower(...).compile()`` results and
    deserialized AOT executables; this backend's ``cost_analysis``
    returns a list of dicts, which is unwrapped. Returns 0.0 when the
    backend doesn't report a cost analysis.
    """
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return float((cost or {}).get("flops", 0.0))
    except Exception:  # noqa: BLE001 - profiling must never break training
        logger.exception("cost analysis failed")
        return 0.0


def compiled_flops(fn: Callable, *args, **kwargs) -> float:
    """Exact FLOPs of the compiled program for these (abstract) args.

    ``fn`` must be a ``jax.jit``-wrapped callable; compilation hits the
    same cache as execution, so calling this after a warmup step is cheap.
    Returns 0.0 when the backend doesn't report a cost analysis.
    """
    try:
        return executable_flops(fn.lower(*args, **kwargs).compile())
    except Exception:  # noqa: BLE001 - profiling must never break training
        logger.exception("cost analysis failed")
        return 0.0


@dataclasses.dataclass
class StepStats:
    steps: int = 0
    mean_s: float = 0.0
    p50_s: float = 0.0
    p90_s: float = 0.0
    min_s: float = 0.0
    flops_per_step: float = 0.0
    tflops_per_s: float = 0.0
    mfu: float | None = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class StepProfiler:
    """Accumulates per-step wall times; computes throughput + MFU.

    The caller is responsible for synchronizing before ``stop`` marks
    (device_get of a step output); dispatch-only timing would lie.
    """

    def __init__(self, flops_per_step: float = 0.0,
                 peak_flops: float | None = None,
                 num_devices: int = 1):
        self._flops = flops_per_step
        self._peak = peak_flops
        self._num_devices = max(1, num_devices)
        self._times: list[float] = []
        self._t0: float | None = None

    def start(self) -> None:
        self._t0 = time.monotonic()

    def stop(self) -> None:
        if self._t0 is not None:
            self._times.append(time.monotonic() - self._t0)
            self._t0 = None

    @contextlib.contextmanager
    def step(self):
        self.start()
        try:
            yield
        finally:
            self.stop()

    def stats(self) -> StepStats:
        if not self._times:
            return StepStats()
        ts = sorted(self._times)
        mean = statistics.fmean(ts)
        flops_per_s = self._flops / mean if mean > 0 else 0.0
        mfu = None
        if self._peak:
            mfu = flops_per_s / (self._peak * self._num_devices)
        return StepStats(
            steps=len(ts),
            mean_s=round(mean, 5),
            p50_s=round(ts[len(ts) // 2], 5),
            p90_s=round(ts[int(len(ts) * 0.9)], 5),
            min_s=round(ts[0], 5),
            flops_per_step=self._flops,
            tflops_per_s=round(flops_per_s / 1e12, 2),
            mfu=round(mfu, 4) if mfu is not None else None,
        )


@contextlib.contextmanager
def trace(log_dir: str):
    """xplane timeline trace (view in TensorBoard/Perfetto/xprof).

    Reference analog: the torch.profiler timeline export in AProfiler.
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("profile trace written to %s", log_dir)


def profile_train_step(step_fn: Callable, state: Any, batch: Any,
                       steps: int = 20, sync: Callable[[Any], None]
                       | None = None) -> tuple[Any, StepStats]:
    """Convenience: time ``steps`` chained executions of a compiled train
    step, with compiled-FLOPs-based MFU. ``sync(metrics)`` forces
    completion (default: device_get of the first output leaf)."""
    import jax

    flops = compiled_flops(step_fn, state, batch)

    def default_sync(out):
        jax.device_get(jax.tree_util.tree_leaves(out)[0])

    sync = sync or default_sync
    # warmup
    state, out = step_fn(state, batch)
    sync(out)
    t0 = time.monotonic()
    for _ in range(steps):
        state, out = step_fn(state, batch)
    sync(out)
    per = (time.monotonic() - t0) / steps
    flops_per_s = flops / per if per > 0 else 0.0
    peak = device_peak_flops()
    # one timed interval over N chained steps: only the mean is real —
    # percentile fields stay 0 (use StepProfiler for order statistics)
    stats = StepStats(
        steps=steps,
        mean_s=round(per, 5),
        flops_per_step=flops,
        tflops_per_s=round(flops_per_s / 1e12, 2),
        mfu=round(flops_per_s / (peak * jax.device_count()), 4)
        if peak else None,
    )
    return state, stats


# ------------------------------------------------------------ breakdown
#
# Reference analog: atorch's AProfiler per-op FLOP formula table
# (atorch/utils/prof.py:482-720 — monkey-patched torch functionals
# counting MACs per module). The JAX shape is cleaner: trace once to a
# jaxpr and charge each equation from its static shapes — control flow
# included (scan bodies multiply by trip count), no patching, no
# execution.

_ELEMENTWISE = frozenset({
    "add", "sub", "mul", "div", "max", "min", "pow", "exp", "log",
    "tanh", "logistic", "rsqrt", "sqrt", "erf", "neg", "sign", "abs",
    "floor", "ceil", "round", "clamp", "select_n", "and", "or", "not",
    "xor", "integer_pow", "cos", "sin",
})
_REDUCE = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "argmax", "argmin", "cumsum",
    "cumlogsumexp", "cummax", "cummin", "cumprod",
})


def _size(v) -> float:
    try:
        return float(np.prod(v.aval.shape)) if v.aval.shape else 1.0
    except Exception:  # noqa: BLE001
        return 0.0


def _dot_flops(eqn) -> float:
    (lhs_contract, _), _ = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    out = eqn.outvars[0].aval.shape
    k = 1.0
    for d in lhs_contract:
        k *= lhs[d]
    return 2.0 * float(np.prod(out) if out else 1.0) * k


def _conv_flops(eqn) -> float:
    rhs = eqn.invars[1].aval.shape  # kernel
    out = eqn.outvars[0].aval.shape
    dn = eqn.params["dimension_numbers"]
    # kernel contributes spatial * in-feature MACs per output element;
    # the kernel's in-feature dim is ALREADY C_in/groups by JAX's
    # conv contract, so grouped/depthwise needs no extra division
    k = 1.0
    for i, d in enumerate(rhs):
        if i != dn.rhs_spec[0]:  # skip the out-feature dim
            k *= d
    return 2.0 * float(np.prod(out)) * k


def _jaxpr_flops(jaxpr, acc: dict, mult: float = 1.0) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name == "dot_general":
            acc["dot_general"] = acc.get("dot_general", 0.0) + \
                mult * _dot_flops(eqn)
        elif name == "conv_general_dilated":
            acc["conv"] = acc.get("conv", 0.0) + mult * _conv_flops(eqn)
        elif name == "scan":
            inner = eqn.params["jaxpr"].jaxpr
            _jaxpr_flops(inner, acc, mult * eqn.params["length"])
        elif name == "while":
            # trip count is dynamic: charge one iteration and flag it
            acc["_dynamic_while"] = 1.0
            _jaxpr_flops(eqn.params["body_jaxpr"].jaxpr, acc, mult)
        elif name == "cond":
            # branches are alternatives; charge the heaviest
            best: dict = {}
            for br in eqn.params["branches"]:
                trial: dict = {}
                _jaxpr_flops(br.jaxpr, trial, mult)
                if sum(v for k, v in trial.items()
                       if not k.startswith("_")) > \
                   sum(v for k, v in best.items()
                       if not k.startswith("_")):
                    best = trial
            for k, v in best.items():
                acc[k] = acc.get(k, 0.0) + v
        elif "jaxpr" in eqn.params:  # pjit/remat/closed_call/custom_*
            inner = eqn.params["jaxpr"]
            _jaxpr_flops(getattr(inner, "jaxpr", inner), acc, mult)
        elif "call_jaxpr" in eqn.params:
            inner = eqn.params["call_jaxpr"]
            _jaxpr_flops(getattr(inner, "jaxpr", inner), acc, mult)
        elif name in _ELEMENTWISE:
            acc["elementwise"] = acc.get("elementwise", 0.0) + \
                mult * _size(eqn.outvars[0])
        elif name in _REDUCE:
            acc["reduce"] = acc.get("reduce", 0.0) + \
                mult * _size(eqn.invars[0])


def flops_breakdown(fn: Callable, *args, **kwargs) -> dict[str, float]:
    """Analytic FLOPs of ``fn`` by op class, from one abstract trace.

    Returns ``{"dot_general": ..., "conv": ..., "elementwise": ...,
    "reduce": ..., "total": ...}`` (matmul/conv FLOPs are the MXU
    work; elementwise/reduce counts are VPU op counts, kept separate
    because they price differently). Charges scan bodies by trip
    count; a dynamic ``while`` is charged one iteration and flagged
    with ``{"_dynamic_while": 1.0}``.
    """
    import jax

    jaxpr = jax.make_jaxpr(lambda *a: fn(*a, **kwargs))(*args)
    acc: dict[str, float] = {}
    _jaxpr_flops(jaxpr.jaxpr, acc)
    acc["total"] = sum(
        v for k, v in acc.items() if not k.startswith("_")
    )
    return acc
