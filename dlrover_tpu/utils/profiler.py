"""Profiling: compiled FLOPs, MFU accounting, step timing, trace export.

Reference analog: ATorch's AProfiler (atorch/atorch/utils/prof.py:38 —
monkey-patches torch functionals to count FLOPs/MACs per module) and the
GPU timeline tracer (utils/tracer.py). XLA makes the counting half free:
``jit(f).lower(...).compile().cost_analysis()`` reports the compiled
program's exact FLOPs, so MFU comes from arithmetic instead of per-op
formula tables; the timeline half is ``jax.profiler`` (xplane traces for
Perfetto/TensorBoard).
"""

from __future__ import annotations

import contextlib
import dataclasses
import statistics
import time
from typing import Any, Callable

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

# bf16 peak FLOP/s per chip by device kind (public spec sheets).
PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def device_peak_flops(device=None) -> float | None:
    """bf16 peak FLOP/s of one chip, or None when unknown (CPU)."""
    import jax

    device = device or jax.devices()[0]
    return PEAK_FLOPS.get(getattr(device, "device_kind", ""))


def compiled_flops(fn: Callable, *args, **kwargs) -> float:
    """Exact FLOPs of the compiled program for these (abstract) args.

    ``fn`` must be a ``jax.jit``-wrapped callable; compilation hits the
    same cache as execution, so calling this after a warmup step is cheap.
    Returns 0.0 when the backend doesn't report a cost analysis.
    """
    try:
        compiled = fn.lower(*args, **kwargs).compile()
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        return float((cost or {}).get("flops", 0.0))
    except Exception:  # noqa: BLE001 - profiling must never break training
        logger.exception("cost analysis failed")
        return 0.0


@dataclasses.dataclass
class StepStats:
    steps: int = 0
    mean_s: float = 0.0
    p50_s: float = 0.0
    p90_s: float = 0.0
    min_s: float = 0.0
    flops_per_step: float = 0.0
    tflops_per_s: float = 0.0
    mfu: float | None = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class StepProfiler:
    """Accumulates per-step wall times; computes throughput + MFU.

    The caller is responsible for synchronizing before ``stop`` marks
    (device_get of a step output); dispatch-only timing would lie.
    """

    def __init__(self, flops_per_step: float = 0.0,
                 peak_flops: float | None = None,
                 num_devices: int = 1):
        self._flops = flops_per_step
        self._peak = peak_flops
        self._num_devices = max(1, num_devices)
        self._times: list[float] = []
        self._t0: float | None = None

    def start(self) -> None:
        self._t0 = time.monotonic()

    def stop(self) -> None:
        if self._t0 is not None:
            self._times.append(time.monotonic() - self._t0)
            self._t0 = None

    @contextlib.contextmanager
    def step(self):
        self.start()
        try:
            yield
        finally:
            self.stop()

    def stats(self) -> StepStats:
        if not self._times:
            return StepStats()
        ts = sorted(self._times)
        mean = statistics.fmean(ts)
        flops_per_s = self._flops / mean if mean > 0 else 0.0
        mfu = None
        if self._peak:
            mfu = flops_per_s / (self._peak * self._num_devices)
        return StepStats(
            steps=len(ts),
            mean_s=round(mean, 5),
            p50_s=round(ts[len(ts) // 2], 5),
            p90_s=round(ts[int(len(ts) * 0.9)], 5),
            min_s=round(ts[0], 5),
            flops_per_step=self._flops,
            tflops_per_s=round(flops_per_s / 1e12, 2),
            mfu=round(mfu, 4) if mfu is not None else None,
        )


@contextlib.contextmanager
def trace(log_dir: str):
    """xplane timeline trace (view in TensorBoard/Perfetto/xprof).

    Reference analog: the torch.profiler timeline export in AProfiler.
    """
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
        logger.info("profile trace written to %s", log_dir)


def profile_train_step(step_fn: Callable, state: Any, batch: Any,
                       steps: int = 20, sync: Callable[[Any], None]
                       | None = None) -> tuple[Any, StepStats]:
    """Convenience: time ``steps`` chained executions of a compiled train
    step, with compiled-FLOPs-based MFU. ``sync(metrics)`` forces
    completion (default: device_get of the first output leaf)."""
    import jax

    flops = compiled_flops(step_fn, state, batch)

    def default_sync(out):
        jax.device_get(jax.tree_util.tree_leaves(out)[0])

    sync = sync or default_sync
    # warmup
    state, out = step_fn(state, batch)
    sync(out)
    t0 = time.monotonic()
    for _ in range(steps):
        state, out = step_fn(state, batch)
    sync(out)
    per = (time.monotonic() - t0) / steps
    flops_per_s = flops / per if per > 0 else 0.0
    peak = device_peak_flops()
    # one timed interval over N chained steps: only the mean is real —
    # percentile fields stay 0 (use StepProfiler for order statistics)
    stats = StepStats(
        steps=steps,
        mean_s=round(per, 5),
        flops_per_step=flops,
        tflops_per_s=round(flops_per_s / 1e12, 2),
        mfu=round(flops_per_s / (peak * jax.device_count()), 4)
        if peak else None,
    )
    return state, stats
