"""Cross-strategy numeric consistency checker.

Reference analog: atorch/atorch/utils/numberic_checker.py — the reference
compares module outputs between two model builds to localize numeric
drift. The TPU-shaped version of that question is sharding-induced:
every Strategy compiles the SAME math into a different SPMD program, so
"does fsdp_tp still compute what dp computes?" is the drift check that
matters here. This runs the full value-and-grad under each strategy on
identical data and reports per-leaf gradient deviation — the test-time
safety net behind the claim that strategies are semantics-preserving
layout choices.

Run at f32: bf16 reduction reordering produces real (harmless) drift
that would drown the signal.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import numpy as np

import jax

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.strategy import Strategy

logger = get_logger(__name__)


@dataclasses.dataclass
class DriftReport:
    loss: dict[str, float]              # strategy name -> loss
    max_grad_rel_dev: float             # worst leaf, worst pair
    worst_leaf: str
    per_leaf: dict[str, float]          # leaf -> max relative deviation
    ok: bool

    def summary(self) -> str:
        state = "OK" if self.ok else "DRIFT"
        return (
            f"[{state}] max grad deviation {self.max_grad_rel_dev:.2e} "
            f"at {self.worst_leaf}; losses "
            + " ".join(f"{k}={v:.6g}" for k, v in self.loss.items())
        )


def check_strategies(
    *,
    loss_fn_for: Callable[[Strategy, Any], Callable],
    init_params_fn: Callable[..., Any],
    logical_params: Any,
    batch: Any,
    strategies: dict[str, Strategy],
    rtol: float = 1e-4,
    seed: int = 0,
) -> DriftReport:
    """Loss + gradients under every strategy on identical params/data.

    ``loss_fn_for(strategy, mesh) -> loss_fn(params, batch)`` — the same
    factory the training path uses (models.transformer.make_loss_fn),
    so the check exercises the real per-strategy attention kernels and
    activation constraints, not a simplified stand-in.
    """
    from jax.sharding import NamedSharding

    if len(strategies) < 2:
        raise ValueError("need at least two strategies to compare")

    grads: dict[str, dict[str, np.ndarray]] = {}
    losses: dict[str, float] = {}
    base_params = init_params_fn(jax.random.PRNGKey(seed))
    for name, strategy in strategies.items():
        mesh = strategy.build_mesh()
        specs = strategy.specs(logical_params, mesh)
        params = jax.tree.map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh, s)),
            base_params, specs,
            is_leaf=lambda x: hasattr(x, "shape") and not isinstance(
                x, tuple),
        )
        loss_fn = loss_fn_for(strategy, mesh)
        val, grad = jax.jit(jax.value_and_grad(loss_fn))(params, batch)
        losses[name] = float(jax.device_get(val))
        flat, _ = jax.tree_util.tree_flatten_with_path(grad)
        grads[name] = {
            "/".join(str(getattr(p, "key", getattr(p, "idx", p)))
                     for p in path): np.asarray(jax.device_get(leaf))
            for path, leaf in flat
        }

    names = list(strategies)
    ref = grads[names[0]]
    per_leaf: dict[str, float] = {}
    for other in names[1:]:
        for leaf_name, g_ref in ref.items():
            g = grads[other].get(leaf_name)
            if g is None:
                per_leaf[leaf_name] = float("inf")
                continue
            scale = max(float(np.max(np.abs(g_ref))), 1e-12)
            dev = float(np.max(np.abs(g - g_ref))) / scale
            per_leaf[leaf_name] = max(per_leaf.get(leaf_name, 0.0), dev)
    worst_leaf = max(per_leaf, key=per_leaf.get)
    worst = per_leaf[worst_leaf]
    # loss drift counts too: a gradient-free offset (buggy constant
    # metric term under one preset) must not pass as OK
    loss_vals = list(losses.values())
    loss_dev = (max(loss_vals) - min(loss_vals)) / max(
        abs(max(loss_vals, key=abs)), 1e-12
    )
    report = DriftReport(
        loss=losses, max_grad_rel_dev=worst, worst_leaf=worst_leaf,
        per_leaf=per_leaf, ok=worst <= rtol and loss_dev <= rtol,
    )
    (logger.info if report.ok else logger.warning)(report.summary())
    return report
