"""Exit-code classification and failover decisions.

Reference analog: the agent/master failure split in
dlrover/python/elastic_agent/torch/training.py:356-360 (exit-code semantics)
and dlrover/python/master/node/dist_job_manager.py:561 (_should_relaunch:
hardware -> relaunch the node, OOM -> bigger pod, software -> restart the
process). TPU specifics: a trainer cannot fix a bad chip by restarting on
the same host, so hardware faults escalate to node relaunch (the
operator/scaler replaces the host); HBM is fixed per chip, so OOM restarts
in place after reporting — the resource optimizer's job is to shrink the
per-step footprint (grad accumulation) or grow the slice.

Exit-code contract (trainer side helpers in trainer/bootstrap.py):
    0    success
    210  out of memory (HBM/host)           -> restart + report OOM
    211  hardware/chip fault                -> relaunch node
    <0   killed by signal -abs(code)        -> restart (KILLED/PREEMPTED)
    else software error                     -> restart, bounded
"""

from __future__ import annotations

import enum
import signal

from dlrover_tpu.common.constants import NodeExitReason

EXIT_CODE_OOM = 210
EXIT_CODE_HARDWARE = 211
# 128+signal exit codes some runtimes report instead of negative returncodes
_SIGNAL_BASE = 128


class FailureAction(str, enum.Enum):
    RESTART_PROCESS = "restart_process"
    RELAUNCH_NODE = "relaunch_node"
    GIVE_UP = "give_up"


def classify_exit(exit_code: int) -> NodeExitReason:
    if exit_code == 0:
        return NodeExitReason.SUCCEEDED
    if exit_code == EXIT_CODE_OOM:
        return NodeExitReason.OOM
    if exit_code == EXIT_CODE_HARDWARE:
        return NodeExitReason.HARDWARE_ERROR
    sig = None
    if exit_code < 0:
        sig = -exit_code
    elif exit_code > _SIGNAL_BASE:
        sig = exit_code - _SIGNAL_BASE
    if sig is not None and not 0 < sig < signal.NSIG:
        # not a real signal number (e.g. exit code 255 -> "signal 127"):
        # a software error that happens to exit above 128, not a kill
        sig = None
    if sig == signal.SIGKILL:
        # the OOM killer and hard preemption both SIGKILL; without more
        # signal treat it as an external kill (restartable)
        return NodeExitReason.KILLED
    if sig == signal.SIGTERM:
        return NodeExitReason.PREEMPTED
    if sig is not None:
        return NodeExitReason.KILLED
    return NodeExitReason.UNKNOWN


def decide(reason: NodeExitReason, restart_count: int,
           max_restarts: int) -> FailureAction:
    """What the agent does about a dead training process."""
    if reason == NodeExitReason.HARDWARE_ERROR:
        return FailureAction.RELAUNCH_NODE
    if reason == NodeExitReason.FATAL_ERROR:
        return FailureAction.GIVE_UP
    if restart_count >= max_restarts:
        return FailureAction.GIVE_UP
    return FailureAction.RESTART_PROCESS
