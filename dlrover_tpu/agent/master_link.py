"""Shared agent <-> master degraded-mode link (DESIGN.md §26).

This generalizes the pattern the serving gateway pioneered
(``gateway/control.py``): a component that talks to the master keeps
doing its real job through a master outage, and the outage itself is
ONE observable transition — a ``degraded_mode`` journal instant on
enter/exit, the ``dlrover_tpu_agent_degraded{component}`` gauge for
alerting, and the ``dlrover_tpu_agent_master_unreachable_total``
counter for rate — instead of a per-tick log line ("heartbeat failed:
master unreachable" × every 15 s × every node was the pre-§26 state).

Every failed tick also attempts a re-dial: a restarted master binds a
fresh port and republishes it in the atomic port file
(``DLROVER_TPU_MASTER_PORT_FILE``), so the link is what moves an
agent's client onto the new incarnation; the epoch fence on the first
successful RPC then runs the client's reconcile.

Users: the elastic agent's heartbeat loop (``component="agent"``), the
gateway control link (``gateway/control.py``, with its legacy unlabeled
gauge), and the embedding fabric coordinator's persist-ledger path
(``component="embedding"``).
"""

from __future__ import annotations

import threading
import time

from dlrover_tpu.common import envspec
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_degraded_gauge = registry().gauge(
    "dlrover_tpu_agent_degraded",
    "1 while this component runs without a reachable master, by "
    "component (training keeps stepping; control actions queue)",
    label_names=("component",),
)
_unreachable_total = registry().counter(
    "dlrover_tpu_agent_master_unreachable_total",
    "master-unreachable ticks observed by degraded links, by component",
    label_names=("component",),
)


class MasterLink:
    """Degraded-mode state machine around a master client.

    ``client`` needs nothing beyond being the object whose calls the
    owner guards; when it exposes ``maybe_redial()`` (MasterClient),
    failed ticks re-resolve the master address from the port file.
    ``gauge`` overrides the labeled default (the gateway keeps its
    documented unlabeled ``dlrover_tpu_gateway_degraded``).
    """

    def __init__(self, client, *, component: str = "agent",
                 gauge=None, warn_every_s: float | None = None):
        self._client = client
        self.component = component
        self._gauge = gauge if gauge is not None \
            else _degraded_gauge.labels(component)
        if warn_every_s is None:
            warn_every_s = envspec.get_float(EnvKey.DEGRADED_WARN_S,
                                             30.0) or 30.0
        self._warn_every_s = warn_every_s
        self._lock = threading.Lock()
        self._degraded = False
        self._degraded_since = 0.0
        self._stale_logged = False
        self._last_warn = 0.0
        self._gauge.set(0)

    @property
    def degraded(self) -> bool:
        with self._lock:
            return self._degraded

    # ------------------------------------------------------------- ticks

    def ok(self) -> None:
        """A master call succeeded: leave degraded mode (one journal
        instant; control actions simply resume)."""
        with self._lock:
            if not self._degraded:
                return
            self._degraded = False
            self._stale_logged = False
        self._gauge.set(0)
        get_journal().emit("degraded_mode", state="exit",
                           component=self.component)
        logger.info("master reachable again; %s left degraded mode",
                    self.component)

    def failed(self, err: Exception) -> None:
        """A master call failed: count it, enter degraded mode on the
        first failure (one journal instant), rate-limit the repeats,
        and try to re-resolve the master address from the port file."""
        _unreachable_total.labels(self.component).inc()
        now = time.monotonic()
        with self._lock:
            entered = not self._degraded
            self._degraded = True
            if entered:
                self._degraded_since = now
            warn = entered or now - self._last_warn >= self._warn_every_s
            if warn:
                self._last_warn = now
        if entered:
            self._gauge.set(1)
            get_journal().emit("degraded_mode", state="enter",
                               component=self.component,
                               error=str(err)[:200])
        if warn:
            logger.warning(
                "master unreachable (%s); %s running degraded "
                "(repeats suppressed for %.0fs)", err, self.component,
                self._warn_every_s,
            )
        redial = getattr(self._client, "maybe_redial", None)
        if redial is not None:
            try:
                redial()
            except Exception:  # noqa: BLE001 - re-dial is best-effort
                logger.exception("master re-dial failed")

    def stale(self) -> bool:
        """Mirrored-config staleness bound (DESIGN.md §30): True once
        the link has been degraded for longer than
        ``DLROVER_TPU_LINK_STALE_S``. Degraded mode keeps the component
        doing its real job on last-known config; past this bound that
        config is old enough that acting on it (a queued restart, a
        mirrored scale target) can contradict what the partitioned
        master has since decided — consumers should drop it and wait
        for the link to recover. The first stale tick of an episode is
        one ``degraded_mode`` state="stale" journal instant."""
        stale_s = envspec.get_float(EnvKey.LINK_STALE_S, 60.0) or 60.0
        with self._lock:
            if not self._degraded:
                return False
            if time.monotonic() - self._degraded_since < stale_s:
                return False
            first = not self._stale_logged
            self._stale_logged = True
        if first:
            get_journal().emit("degraded_mode", state="stale",
                               component=self.component)
            logger.warning(
                "%s degraded for over %.0fs: mirrored master config is "
                "now STALE; holding position until the link recovers",
                self.component, stale_s,
            )
        return True
