"""Preemption/maintenance-notice watcher: save BEFORE the kill.

Reference analog: Flash Checkpoint's breakpoint save fires when a
failure has already happened (reference ckpt_saver.py:631
save_shm_to_storage, triggered from training.py:590-610). TPU preemption
is better than that: the platform *announces* the kill (GCE maintenance
events / TPU preemption notices), and a preempted host VM loses its
shared memory — restart-in-place never applies (SURVEY §7
"restart-in-place vs preemption"). So the agent watches for the notice
and, the moment it lands, (1) force-replicates the current shm snapshot
to its buddy host over DCN (checkpoint/buddy.py), (2) runs the
breakpoint persist, and (3) tells the master to arm the short
dead-window so the replacement host launches seconds after the VM dies.
The relaunched agent then restores from the buddy with zero storage
reads (elastic_agent._restore_from_buddy).

Notice sources, in precedence order:
- ``DLROVER_TPU_PREEMPTION_FILE``: a path; the notice fires when the
  file exists. ``{node_id}`` in the value is substituted. This is both
  the test-injection hook and the deployment hook for environments
  where a node daemon materializes maintenance events as files.
- ``DLROVER_TPU_PREEMPTION_URL``: polled with a GET; any 200 response
  whose body is not ``NONE`` fires (the GCE
  ``instance/maintenance-event`` metadata convention). Requires the
  metadata server; unset by default.
"""

from __future__ import annotations

import os
import threading
from typing import Callable

from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

ENV_NOTICE_FILE = EnvKey.PREEMPTION_FILE
ENV_NOTICE_URL = EnvKey.PREEMPTION_URL


class PreemptionWatcher:
    """Polls the configured notice source; fires ``on_notice`` ONCE."""

    def __init__(self, on_notice: Callable[[], None], *,
                 node_id: int = 0, poll_interval_s: float = 1.0,
                 notice_file: str | None = None,
                 notice_url: str | None = None):
        notice_file = (notice_file
                       if notice_file is not None
                       else os.environ.get(ENV_NOTICE_FILE, ""))
        self._file = (notice_file.replace("{node_id}", str(node_id))
                      if notice_file else "")
        self._url = (notice_url
                     if notice_url is not None
                     else os.environ.get(ENV_NOTICE_URL, ""))
        self._on_notice = on_notice
        self._interval = poll_interval_s
        self._fired = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="preemption-watcher", daemon=True
        )

    @property
    def enabled(self) -> bool:
        return bool(self._file or self._url)

    @property
    def fired(self) -> bool:
        return self._fired.is_set()

    def start(self) -> "PreemptionWatcher":
        if self.enabled:
            self._thread.start()
            logger.info(
                "preemption watcher armed (%s)",
                self._file or self._url,
            )
        return self

    def stop(self) -> None:
        self._stop.set()

    def _noticed(self) -> bool:
        if self._file and os.path.exists(self._file):
            return True
        if self._url:
            import urllib.request

            try:
                req = urllib.request.Request(
                    self._url, headers={"Metadata-Flavor": "Google"}
                )
                with urllib.request.urlopen(req, timeout=2.0) as resp:
                    body = resp.read(256).decode(errors="replace").strip()
                return bool(body) and body.upper() != "NONE"
            except OSError:
                return False
        return False

    def _loop(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                if self._noticed():
                    self._fired.set()
                    logger.warning("preemption notice detected")
                    try:
                        self._on_notice()
                    except Exception:  # noqa: BLE001 - the prepare steps
                        logger.exception("preemption handler failed")
                    return  # one-shot: the node is going away
            except Exception:  # noqa: BLE001 - keep polling
                logger.exception("preemption poll failed")
