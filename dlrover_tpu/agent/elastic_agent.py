"""Per-host elastic agent: rendezvous, spawn, monitor, restart.

Reference analog: dlrover/python/elastic_agent/torch/training.py
(ElasticTrainingAgent:349, _invoke_run:547, _membership_changed:676,
launch_agent:695). TPU-native differences:

- one training *process per host* owning all local TPU chips (torch runs one
  per GPU); the agent spawns exactly one child and the JAX runtime fans out
  over local devices.
- a completed rendezvous yields the JAX coordinator address; the child calls
  ``jax.distributed.initialize`` from env instead of joining a TCPStore.
- restart-in-place: on child failure or membership change the agent asks the
  flash-checkpoint saver to persist the latest shm snapshot, then respawns
  the child, which restores from shm in seconds (SURVEY.md §5.4).
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from enum import Enum

from dlrover_tpu import chaos
from dlrover_tpu.common import envspec
from dlrover_tpu.common.accelerator import sniff_accelerator
from dlrover_tpu.common.constants import (
    Defaults,
    EnvKey,
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.rpc import find_free_port
from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.telemetry.journal import (
    current_ctx,
    get_journal,
    set_trace_id,
)
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_restarts_total = registry().counter(
    "dlrover_tpu_agent_restarts_total",
    "trainer respawns by kind (failure vs planned)",
    label_names=("kind",),
)
_incarnation_gauge = registry().gauge(
    "dlrover_tpu_agent_incarnation",
    "current trainer incarnation number on this node",
)
_rdzv_wait_seconds = registry().histogram(
    "dlrover_tpu_agent_rdzv_wait_seconds",
    "agent-observed rendezvous wait (join -> completed world)",
)
_reshard_choices = registry().counter(
    "dlrover_tpu_agent_reshard_choice_total",
    "recovery rendezvous outcomes by path: covered=true means the "
    "compile cache already holds an executable for the new topology "
    "and the incarnation takes the reshard-with-fallback path",
    label_names=("covered",),
)


class RunResult(str, Enum):
    SUCCEEDED = "succeeded"
    FAILED = "failed"
    # this host should be replaced, not restarted-in-place: the launcher
    # exits with a distinct code the operator/scaler keys on
    NODE_RELAUNCH = "node_relaunch"


@dataclasses.dataclass
class AgentConfig:
    job_name: str = "local"
    master_addr: str = ""
    node_id: int = 0
    entrypoint: list[str] = dataclasses.field(default_factory=list)
    max_restarts: int = Defaults.MAX_RESTARTS
    monitor_interval_s: float = Defaults.MONITOR_INTERVAL_S
    heartbeat_interval_s: float = Defaults.HEARTBEAT_INTERVAL_S
    rdzv_timeout_s: float = Defaults.RDZV_WAIT_TIMEOUT_S
    network_check: bool = False
    exclude_straggler: bool = False
    local_devices: int = 0  # 0 -> autodetect
    host_ip: str = "127.0.0.1"
    topology_key: str = ""
    save_on_failure: bool = True
    comm_port_base: int = 0  # 0 -> pick free ports
    # node-local hang detection (agent/hang_detector.py): restart the
    # trainer when its reported step stops advancing for this long.
    # 0 disables. The grace covers (re)compilation after every spawn.
    hang_timeout_s: float = 0.0
    hang_startup_grace_s: float = 600.0


def _detect_local_devices() -> int:
    override = os.environ.get(EnvKey.DEVICE_COUNT_OVERRIDE)
    if override:
        return int(override)
    # TPU chips must be counted from their kernel device nodes: importing
    # jax here would initialize libtpu and steal the (exclusive-access)
    # chips from the trainer child this agent is about to spawn
    kind, count = sniff_accelerator()
    if kind == "tpu":
        return count
    try:
        import jax

        return jax.local_device_count()
    except Exception:  # noqa: BLE001 - no jax / no devices in agent is fine
        return 1


class ElasticAgent:
    """Runs one elastic training lifecycle on this host."""

    def __init__(self, config: AgentConfig, client: MasterClient | None = None):
        self._config = config
        # rack attach (DESIGN.md §28): when the launcher placed this
        # node behind a rack sub-master it sets DLROVER_TPU_RACK_ID and
        # points master_addr at the sub-master. The client then
        # re-dials target-keyed: the rack's own port file first, the
        # root's as the degraded direct-to-root fallback — and prefers
        # the rack file again on every re-dial, so a respawned
        # sub-master reclaims its agents automatically.
        rack_port_file = envspec.get(EnvKey.RACK_PORT_FILE) \
            if envspec.get(EnvKey.RACK_ID) else None
        self._client = client or MasterClient(
            config.master_addr, config.node_id,
            port_file=rack_port_file,
            fallback_port_file=envspec.get(EnvKey.MASTER_PORT_FILE)
            if rack_port_file else None,
        )
        self._proc: subprocess.Popen | None = None
        # failure restarts (consume the failover budget) vs the incarnation
        # counter (any respawn — failures, membership changes, config)
        self._restart_count = 0
        self._incarnation = 0
        self._stopped = threading.Event()
        self._local_devices = config.local_devices or _detect_local_devices()
        self._ckpt_saver = None  # wired by agent/ckpt_saver.py start()
        self._resource_monitor = None
        self._config_tuner = None
        self._buddy_server = None
        self._buddy_replicator = None
        self._preemption_watcher = None
        self._metrics_server = None
        self._world: dict[int, int] = {}
        self._master_link = None  # agent/master_link.py, set at run()
        self._standby = None  # agent/standby.py StandbyManager
        self._node_rank = -1
        self._pending_action = ""
        # span context (§27) of the config push that requested a restart:
        # the planned node_restart attaches under the master's verdict
        self._pending_restart_sctx = ""
        self._action_lock = threading.Lock()
        self._hang = None
        if config.hang_timeout_s > 0:
            from dlrover_tpu.agent.hang_detector import HangDetector

            self._hang = HangDetector(
                config.node_id,
                timeout_s=config.hang_timeout_s,
                startup_grace_s=config.hang_startup_grace_s,
            )

    # ------------------------------------------------------------ rendezvous

    def _rendezvous(self) -> tuple[int, int, str]:
        """Join the training rendezvous; return (rank, num_nodes, coordinator).

        The advertised address carries a freshly picked port the JAX
        coordination service will bind if this node becomes rank 0.
        """
        port = self._config.comm_port_base or find_free_port(
            self._config.host_ip
        )
        addr = f"{self._config.host_ip}:{port}"
        wait_start = time.time()
        join_deadline = wait_start + self._config.rdzv_timeout_s
        while True:
            try:
                self._client.join_rendezvous(
                    addr=addr,
                    local_devices=self._local_devices,
                    topology_key=self._config.topology_key,
                )
                break
            except (ConnectionError, TimeoutError, OSError) as e:
                # a master mid-restart must delay the rendezvous, not
                # kill the agent (§26): re-resolve from the port file
                # and retry inside the rendezvous budget
                if time.time() >= join_deadline:
                    raise
                logger.warning("rendezvous join failed (%s); "
                               "re-dialing the master", e)
                self._client.maybe_redial()
                time.sleep(0.5)
        world = self._client.wait_comm_world(
            timeout=self._config.rdzv_timeout_s
        )
        self._world = world.world
        self._node_rank = world.world[self._config.node_id]
        # adopt the master-minted job trace id before journaling: this
        # agent's spans (and the trainer child, via inherited env) link
        # into the job-wide trace
        set_trace_id(world.trace_id)
        waited = time.time() - wait_start
        _rdzv_wait_seconds.observe(waited)
        get_journal().emit(
            "rendezvous_wait", dur=waited, round=world.round,
            rank=self._node_rank, nodes=len(world.world),
            remote_parent=world.sctx,
        )
        logger.info(
            "rendezvous round %d: rank %d of %d nodes, coordinator %s",
            world.round, self._node_rank, len(world.world), world.coordinator,
        )
        self._reshard_decision(world)
        return self._node_rank, len(world.world), world.coordinator

    def _reshard_decision(self, world) -> None:
        """Choose the recovery path for the world this round produced:
        when the master's compile cache already holds an executable for
        the new topology (published by the pre-failure incarnation or
        the fallback-AOT daemon), the upcoming incarnation is a
        *reshard* event — it will load the program instead of cold
        compiling — and the journal records the choice so the recovery
        trail reads ``reshard`` rather than a cold compile. No coverage
        means today's restart path, unchanged (DESIGN.md §17). The
        event also records the newest VERIFIED storage step: a
        multi-host reshard whose missing shards have no live copy falls
        back to storage (``reshard_state``'s piece registry, DESIGN.md
        §20) — the journal shows up front whether that net exists."""
        from dlrover_tpu.master.kv_store import node_topology_prefix

        try:
            # scan by world size, not device count: the program key pins
            # the exact device topology, but the agent's chip count and
            # the trainer's jax device count can differ (virtual test
            # meshes), and the question here is only "does the N-node
            # world have a pre-compiled program"
            resp = self._client.compile_cache_query(
                node_topology_prefix(len(world.world))
            )
        except (ConnectionError, RuntimeError, OSError) as e:
            logger.warning("compile-cache coverage query failed: %s", e)
            return
        covered = bool(resp.covered)
        stage_execs = self._stage_coverage(len(world.world),
                                           world.total_devices)
        _reshard_choices.labels(str(covered).lower()).inc()
        if covered:
            get_journal().emit(
                "reshard", nodes=len(world.world),
                devices=world.total_devices,
                executables=resp.executables,
                stage_executables=stage_execs,
                shrink=bool(world.reshard),
                storage_step=self._verified_storage_step(),
            )
            logger.info(
                "recovery is a reshard event: %d pre-compiled "
                "executable(s) for %d nodes / %d devices%s%s",
                resp.executables, len(world.world), world.total_devices,
                f" ({stage_execs} per-stage pipeline programs — the "
                "incarnation reloads per stage)" if stage_execs else "",
                " (membership shrink)" if world.reshard else "",
            )

    def _stage_coverage(self, nodes: int, total_devices: int) -> int:
        """Per-stage MPMD program coverage for this world (DESIGN.md
        §21): stage keys carry a ``pp`` marker right after the topology
        tag (``compile_cache.stage_key``), so one prefix scan counts
        them. An MPMD job's recovery is per-stage — this is the
        evidence that only the affected stage will compile cold. Note
        stage submeshes are a SLICE of the world, so the scan uses the
        per-stage device count when the world divides evenly; 0 simply
        means "not an MPMD job" and is not journaled as coverage."""
        from dlrover_tpu.master.kv_store import topology_tag

        count = 0
        seen = set()
        for per_stage_devices in {total_devices, *(
            total_devices // p for p in (2, 4, 8)
            if total_devices % p == 0 and total_devices // p >= 1
        )}:
            prefix = topology_tag(per_stage_devices, nodes) + "/pp"
            if prefix in seen:
                continue
            seen.add(prefix)
            try:
                resp = self._client.compile_cache_query(prefix)
                count += int(resp.executables)
            except (ConnectionError, RuntimeError, OSError):
                return 0
        return count

    def _verified_storage_step(self) -> int:
        """Newest fully-verified checkpoint step in storage (-1 = none
        / unknown): the reshard's missing-shard fallback source."""
        if self._ckpt_saver is None:
            return -1
        try:
            header = self._ckpt_saver.shm_handler.header() or {}
            ckpt_dir = header.get("ckpt_dir") or ""
            if not ckpt_dir:
                return -1
            from dlrover_tpu.common.storage import PosixDiskStorage
            from dlrover_tpu.checkpoint.integrity import (
                resolve_restore_step,
            )

            got = resolve_restore_step(PosixDiskStorage(), ckpt_dir)
            return -1 if got is None else got[0]
        except Exception:  # noqa: BLE001 - evidence only, never blocks
            return -1

    # ----------------------------------------------------------- child mgmt

    def _child_env_update(self, rank: int, num_nodes: int,
                          coordinator: str) -> dict[str, str]:
        """The env-var contract one trainer incarnation runs under —
        shared by cold spawns and standby promotions."""
        update = {
            EnvKey.JOB_NAME: self._config.job_name,
            EnvKey.MASTER_ADDR: self._client._client.addr,
            EnvKey.NODE_ID: str(self._config.node_id),
            EnvKey.NODE_RANK: str(rank),
            EnvKey.NODE_NUM: str(num_nodes),
            EnvKey.COORDINATOR: coordinator,
            EnvKey.RESTART_COUNT: str(self._incarnation),
        }
        trace = os.environ.get(EnvKey.TRACE_ID)
        if trace:
            # a parked standby was spawned before the first rendezvous
            # delivered the job trace id: promotion must carry it
            update[EnvKey.TRACE_ID] = trace
        # span context (§27): a child spawned inside a recovery incident
        # attaches its restore/recompile spans under it. Unconditional so
        # a stale inherited value never leaks into a healthy incarnation.
        update[EnvKey.SPAN_CTX] = current_ctx()
        if self._config_tuner is not None:
            update[EnvKey.PARAL_CONFIG_PATH] = self._config_tuner.path
        return update

    def _spawn(self, rank: int, num_nodes: int, coordinator: str
               ) -> subprocess.Popen:
        env = dict(os.environ)
        env.update(self._child_env_update(rank, num_nodes, coordinator))
        logger.info(
            "spawning training process (incarnation %d, failures %d): %s",
            self._incarnation, self._restart_count,
            " ".join(self._config.entrypoint),
        )
        if self._hang is not None:
            # every incarnation recompiles: fresh grace period
            self._hang.reset()
        _incarnation_gauge.set(self._incarnation)
        return subprocess.Popen(
            self._config.entrypoint, env=env, start_new_session=True
        )

    def _respawn(self, rank: int, num_nodes: int, coordinator: str
                 ) -> subprocess.Popen:
        """Warm path first: promote the parked standby (it has already
        paid spawn + imports and may have a restore prefetch running),
        then re-arm a fresh one in the background. Cold `_spawn` when
        standbys are off, dead, or never armed."""
        if self._standby is not None:
            proc = self._standby.promote(
                self._child_env_update(rank, num_nodes, coordinator)
            )
            if proc is not None:
                if self._hang is not None:
                    self._hang.reset()
                _incarnation_gauge.set(self._incarnation)
                self._standby.arm_async()
                return proc
        return self._spawn(rank, num_nodes, coordinator)

    def _arm_standby(self) -> None:
        from dlrover_tpu.agent.standby import StandbyManager, standby_enabled

        if not standby_enabled() or not self._config.entrypoint:
            return
        if self._standby is None:
            self._standby = StandbyManager(
                self._config.entrypoint, self._config.node_id
            )
        self._standby.arm_async()

    def _prepare_standby_restore(self) -> None:
        """Failure time, post-persist: point the parked standby at the
        checkpoint dir so its storage restore prefetch overlaps the
        rendezvous round this agent is about to run."""
        if self._standby is None or self._ckpt_saver is None:
            return
        try:
            header = self._ckpt_saver.shm_handler.header()
        except Exception:  # noqa: BLE001 - prefetch is best-effort
            return
        if header:
            ckpt_dir = header.get("ckpt_dir") or ""
            if ckpt_dir:
                self._standby.prepare(ckpt_dir)

    def _kill_child(self) -> None:
        if self._proc is None or self._proc.poll() is not None:
            return
        try:
            os.killpg(self._proc.pid, signal.SIGTERM)
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                os.killpg(self._proc.pid, signal.SIGKILL)
                self._proc.wait(timeout=10)
        except ProcessLookupError:
            pass

    # ------------------------------------------------------------ main loop

    def run(self) -> RunResult:
        from dlrover_tpu.telemetry.bundle import install_sigusr2
        from dlrover_tpu.telemetry.exposition import start_from_env

        self._metrics_server = start_from_env()
        # operator runbook: `kill -USR2 <agent pid>` captures a full
        # flight-recorder bundle (incl. the live trainer's stacks) on
        # demand without disturbing the job
        install_sigusr2(
            on_bundle=self._report_bundle,
            child_pid_fn=lambda: (
                self._proc.pid
                if self._proc is not None and self._proc.poll() is None
                else None
            ),
        )
        self._start_heartbeat()
        self._start_ckpt_saver()
        self._start_resource_monitor()
        self._start_config_tuner()
        self._start_buddy_replication()
        self._start_preemption_watcher()
        try:
            if self._config.network_check:
                self._run_network_check()
            return self._invoke_run()
        finally:
            self._stopped.set()
            if self._preemption_watcher is not None:
                self._preemption_watcher.stop()
            if self._resource_monitor is not None:
                self._resource_monitor.stop()
            if self._config_tuner is not None:
                self._config_tuner.stop()
            if self._buddy_replicator is not None:
                self._buddy_replicator.stop()
            if self._buddy_server is not None:
                self._buddy_server.stop()
            if self._metrics_server is not None:
                self._metrics_server.stop()
            if self._standby is not None:
                self._standby.discard()
            self._kill_child()

    def _invoke_run(self) -> RunResult:
        rank, num_nodes, coordinator = self._rendezvous()
        self._restore_from_buddy()
        self._proc = self._spawn(rank, num_nodes, coordinator)
        # arm the warm standby only after the live trainer exists: the
        # first spawn must never queue behind the standby's import cost
        self._arm_standby()
        hang = self._hang
        while True:
            time.sleep(self._config.monitor_interval_s)
            code = self._proc.poll()
            if code == 0:
                logger.info("training process succeeded")
                self._client.report_node_event(
                    NodeEventType.MODIFIED, NodeStatus.SUCCEEDED.value,
                    NodeExitReason.SUCCEEDED,
                )
                self._client.report_job_exit(success=True)
                return RunResult.SUCCEEDED
            if code is not None:
                outcome = self._handle_failure(code)
                if outcome is not None:
                    return outcome
                continue
            if hang is not None and hang.check():
                # wedged trainer: the kill surfaces as a failure exit on
                # the next poll and flows through the normal restart and
                # failover budget (the reference's HangingDetector
                # relaunch). _handle_failure owns the master report — a
                # second report here would double-trigger master-side
                # recovery actions.
                logger.warning(
                    "hang detected: no training progress past step %d "
                    "for %.0fs; killing the wedged trainer",
                    hang.last_step(), self._config.hang_timeout_s,
                )
                # flight recorder FIRST: the wedged child's C-level
                # stack dump (SIGUSR2 -> faulthandler) is only readable
                # while it is still alive
                self._write_bundle(
                    "hang",
                    child_pid=(self._proc.pid
                               if self._proc is not None else None),
                    extra={"last_step": hang.last_step(),
                           "timeout_s": self._config.hang_timeout_s},
                )
                self._kill_child()
                continue
            if chaos.ENABLED:
                self._chaos_kill_check()
            # healthy: check for membership changes / master actions
            action = self._master_action()
            if action == "restart":
                self._restart_workers(reason="master restart action")
            elif action.startswith("profile"):
                self._arm_profile(action)
            elif self._membership_changed():
                self._restart_workers(reason="membership change")

    def _arm_profile(self, action: str) -> None:
        """Master-requested on-demand profiler capture ("profile:<K>"):
        hand the request to the live trainer via the bundle-root file
        (telemetry/efficiency.py) — the trainer owns the jax runtime,
        so the capture must run there, not here."""
        from dlrover_tpu.telemetry.efficiency import arm_profile_request

        try:
            steps = max(1, int(action.split(":", 1)[1]))
        except (IndexError, ValueError):
            steps = 5
        arm_profile_request(self._config.node_id, steps)
        logger.info("profiler capture armed for the trainer "
                    "(%d steps)", steps)

    def _chaos_kill_check(self) -> None:
        """Chaos plan ``agent_kill_trainer`` point: kill the live trainer
        with a chosen signal once its reported step matches the rule
        (e.g. ``{"match": {"step_gte": 8}, "args": {"sig": 9}}`` — the
        agent then observes exit code -sig and runs the normal failover
        ladder). The step comes from the hang detector's progress file,
        so the kill lands at a training position, not a wall-clock one.
        """
        from dlrover_tpu.agent.hang_detector import progress_path

        step = -1
        try:
            with open(progress_path(self._config.node_id)) as f:
                step = int(json.load(f)["step"])
        except (OSError, ValueError, KeyError):
            pass
        fault = chaos.fire("agent_kill_trainer", step=step,
                           incarnation=self._incarnation)
        if fault is None or self._proc is None \
                or self._proc.poll() is not None:
            return
        sig = int(fault.args.get("sig", signal.SIGKILL))
        logger.warning("chaos: killing trainer with signal %d at step %d",
                       sig, step)
        try:
            os.killpg(self._proc.pid, sig)
        except ProcessLookupError:
            pass

    def _handle_failure(self, exit_code: int) -> RunResult | None:
        """Classify the exit and act on it; None means restarted, keep
        monitoring. Reference: training.py:356-360 exit-code semantics +
        dist_job_manager.py:561 _should_relaunch."""
        from dlrover_tpu.agent.failure_policy import (
            FailureAction,
            classify_exit,
            decide,
        )

        reason = classify_exit(exit_code)
        action = decide(reason, self._restart_count,
                        self._config.max_restarts)
        logger.warning(
            "training process exited with code %d (%s) -> %s",
            exit_code, reason.value, action.value,
        )
        if exit_code != 0:
            # pre-respawn flight recorder: journal tail, metrics and env
            # as they were when the worker died (the child is gone — any
            # stale armed stack dump it left is scooped up, not poked)
            self._write_bundle(
                "crash",
                extra={"exit_code": exit_code, "reason": reason.value,
                       "action": action.value},
            )
        def _report_failure() -> None:
            self._client.report_failure(
                error_data=f"exit code {exit_code} ({reason.value})",
                restart_count=self._restart_count,
                level=(
                    TrainingExceptionLevel.NODE_ERROR
                    if reason in (NodeExitReason.HARDWARE_ERROR,
                                  NodeExitReason.OOM)
                    else TrainingExceptionLevel.PROCESS_ERROR
                ),
            )

        if action == FailureAction.RELAUNCH_NODE:
            _report_failure()
            # persist the snapshot first: the replacement host restores
            # from storage, not from this host's shm
            self._persist_checkpoint(reason="node relaunch")
            self._report_terminal(
                NodeStatus.FAILED.value, reason,
                f"exit code {exit_code}",
            )
            return RunResult.NODE_RELAUNCH
        if action == FailureAction.GIVE_UP:
            _report_failure()
            logger.error(
                "no failovers remain (%d used); job failed",
                self._restart_count,
            )
            self._report_terminal(
                NodeStatus.FAILED.value, NodeExitReason.FATAL_ERROR,
                f"exit code {exit_code}",
            )
            try:
                self._client.report_job_exit(
                    success=False, reason=f"exit code {exit_code}"
                )
            except (ConnectionError, TimeoutError, OSError) as e:
                logger.warning("job-exit report failed: %s", e)
            return RunResult.FAILED
        _restarts_total.labels("failure").inc()
        with get_journal().span(
            "node_restart", kind="failure", exit_code=exit_code,
            incarnation=self._incarnation + 1,
        ):
            # incident root (§27): opened at failure detection so the
            # failure report and every recovery phase below — persist,
            # rendezvous, restore, respawn, and the trainer child's own
            # restore/recompile (via SPAN_CTX) — journal as children
            _report_failure()
            self._persist_checkpoint(reason="process failure")
            # the persist is durable: the standby's restore prefetch can
            # now run concurrently with the rendezvous round below
            self._prepare_standby_restore()
            self._recover_shards()
            self._restart_count += 1
            self._incarnation += 1
            rank, num_nodes, coordinator = self._rendezvous()
            self._proc = self._respawn(rank, num_nodes, coordinator)
        return None

    def _report_terminal(self, status: str, exit_reason, message: str
                         ) -> None:
        """Terminal node-status reports must not crash the ladder when
        the master is mid-restart (§26): the outcome is also visible
        through the launcher exit code either way."""
        try:
            self._client.report_node_event(
                NodeEventType.MODIFIED, status, exit_reason, message
            )
        except (ConnectionError, TimeoutError, OSError) as e:
            logger.warning("terminal node event report failed: %s", e)

    def _restart_workers(self, reason: str) -> None:
        """Planned restart (membership change / config update): bumps the
        incarnation but does NOT consume the failover budget — only
        failures do (reference: _remaining_failovers decrements on failure
        only, training.py:594)."""
        logger.info("restarting workers: %s", reason)
        _restarts_total.labels("planned").inc()
        with self._action_lock:
            push_sctx = self._pending_restart_sctx
            self._pending_restart_sctx = ""
        with get_journal().span(
            "node_restart", kind="planned", reason=reason,
            incarnation=self._incarnation + 1, remote_parent=push_sctx,
        ):
            self._persist_checkpoint(reason=reason)
            self._kill_child()
            self._prepare_standby_restore()
            self._recover_shards()
            self._incarnation += 1
            rank, num_nodes, coordinator = self._rendezvous()
            self._proc = self._respawn(rank, num_nodes, coordinator)

    def _write_bundle(self, reason: str, child_pid: int | None = None,
                      extra: dict | None = None) -> str | None:
        """Capture a flight-recorder bundle and report its path to the
        master; best-effort and off via DLROVER_TPU_BUNDLES=0."""
        if os.environ.get(EnvKey.BUNDLES, "1") == "0":
            return None
        from dlrover_tpu.telemetry.bundle import write_bundle

        path = write_bundle(reason, node_id=self._config.node_id,
                            child_pid=child_pid, extra=extra)
        if path:
            self._report_bundle(path, reason)
        return path

    def _report_bundle(self, path: str, reason: str) -> None:
        try:
            self._client.report_debug_bundle(path, reason, proc="agent")
        except (ConnectionError, RuntimeError, OSError) as e:
            logger.warning("debug bundle report failed: %s", e)

    def _recover_shards(self) -> None:
        """Give the dead trainer's in-flight data shards back to the queue.

        Restart-in-place keeps this node alive, so the master's
        heartbeat-dead recovery never fires for it (reference analog:
        dist_job_manager relaunch path re-queuing worker shards).
        """
        try:
            self._client.recover_shards()
        except (ConnectionError, RuntimeError, OSError) as e:
            logger.warning("shard recovery request failed: %s", e)

    def _membership_changed(self) -> bool:
        try:
            return self._client.num_nodes_waiting() > 0
        except ConnectionError:
            return False

    def _master_action(self) -> str:
        with self._action_lock:
            action, self._pending_action = self._pending_action, ""
        return action

    # ------------------------------------------------------------- services

    def _start_heartbeat(self) -> None:
        from dlrover_tpu.agent.master_link import MasterLink

        # degraded-mode link (DESIGN.md §26): a master outage is ONE
        # journal instant + a counter (rate-limited warnings), the
        # trainer keeps stepping, and every failed tick re-resolves
        # the master address from the port file so a restarted master
        # is picked up within one heartbeat
        link = MasterLink(self._client, component="agent")
        self._master_link = link

        def loop():
            while not self._stopped.is_set():
                try:
                    action = self._client.report_heartbeat(
                        self._restart_count
                    )
                    if action:
                        with self._action_lock:
                            self._pending_action = action
                    # piggyback this node's metrics snapshot on the
                    # heartbeat cadence so the master's exposition
                    # endpoint serves job-wide series
                    self._client.report_metrics(registry().snapshot())
                    link.ok()
                except (ConnectionError, RuntimeError, OSError) as e:
                    link.failed(e)
                    if link.stale():
                        # a control action mirrored before the outage
                        # must not fire minutes later (§30): the master
                        # re-issues it on the next heartbeat if it
                        # still wants it
                        with self._action_lock:
                            self._pending_action = ""
                            self._pending_restart_sctx = ""
                self._stopped.wait(self._config.heartbeat_interval_s)

        threading.Thread(target=loop, name="agent-heartbeat",
                         daemon=True).start()

    def _start_ckpt_saver(self) -> None:
        from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver

        self._ckpt_saver = AsyncCheckpointSaver.start(
            node_id=self._config.node_id
        )

    def _start_resource_monitor(self) -> None:
        from dlrover_tpu.agent.resource_monitor import ResourceMonitor

        self._resource_monitor = ResourceMonitor(
            self._client,
            interval_s=self._config.heartbeat_interval_s,
            tpu_chips=self._local_devices,
        )
        self._resource_monitor.start()

    def _start_config_tuner(self) -> None:
        from dlrover_tpu.agent.config_tuner import ParalConfigTuner

        def on_update(config: dict) -> None:
            if config.get("restart_required") and self._proc is not None \
                    and self._proc.poll() is None:
                # recompile-class knobs apply at the next incarnation
                with self._action_lock:
                    self._pending_action = "restart"
                    self._pending_restart_sctx = config.get("sctx", "")

        self._config_tuner = ParalConfigTuner(
            self._client, on_update=on_update
        )
        self._config_tuner.start()

    def _start_buddy_replication(self) -> None:
        """Peer-redundant shm snapshots over DCN (checkpoint/buddy.py):
        this agent serves its peers' pushes and streams its own node's
        new snapshots to the master-assigned ring buddy. Disable with
        DLROVER_TPU_BUDDY=0."""
        if not envspec.get_bool(EnvKey.BUDDY):
            return
        from dlrover_tpu.checkpoint.buddy import (
            BuddyReplicator,
            BuddyServer,
        )

        try:
            self._buddy_server = BuddyServer(
                host=self._config.host_ip
            ).start()
            self._client.report_buddy_endpoint(self._buddy_server.addr)
        except (OSError, ConnectionError, RuntimeError) as e:
            logger.warning("buddy server unavailable: %s", e)
            self._buddy_server = None
            return
        interval = envspec.get_float(EnvKey.BUDDY_INTERVAL)
        self._buddy_replicator = BuddyReplicator(
            self._ckpt_saver.shm_handler, self._client,
            interval_s=interval,
        )
        self._buddy_replicator.start()

    def _start_preemption_watcher(self) -> None:
        """Arm the maintenance/preemption-notice watcher
        (agent/preemption.py); inert unless a notice source env is set."""
        from dlrover_tpu.agent.preemption import PreemptionWatcher

        watcher = PreemptionWatcher(
            self._on_preemption_notice, node_id=self._config.node_id,
            poll_interval_s=min(1.0, self._config.monitor_interval_s),
        )
        if watcher.enabled:
            self._preemption_watcher = watcher.start()

    def _on_preemption_notice(self) -> None:
        """The kill is coming: protect the snapshot while the host is
        still alive, then arm the master's fast relaunch. Order matters —
        the buddy push is what the <10s no-storage restore needs; the
        storage persist is the belt-and-braces fallback."""
        start = time.monotonic()
        # master first: it is a cheap RPC, and if the kill lands during
        # the (slow, multi-GB) replication/persist below, the master
        # must already be on the short dead-window or the relaunch waits
        # the full heartbeat window
        try:
            self._client.report_preemption_notice()
        except (ConnectionError, RuntimeError, OSError) as e:
            logger.warning("could not report preemption notice: %s", e)
        replicated = False
        if self._buddy_replicator is not None:
            try:
                # replicate_once is a no-op when the buddy already holds
                # the current step — "protected" either way
                self._buddy_replicator.replicate_once()
                replicated = True
            except Exception:  # noqa: BLE001 - keep preparing
                logger.exception("pre-kill buddy replication failed")
        self._persist_checkpoint(reason="preemption notice")
        logger.warning(
            "preemption prepare done in %.2fs (buddy replicated: %s)",
            time.monotonic() - start, replicated,
        )

    def _restore_from_buddy(self) -> None:
        """Pre-spawn: if this host's shm snapshot is gone (node relaunch
        on a fresh VM — TPU preemption), pull it back from the buddy so
        the trainer's restore-from-shm path works unchanged and storage
        stays the last resort (<10s budget, SURVEY §7 hard-parts).

        Independent of the local BuddyServer: fetching OUR snapshot only
        needs the buddy's server — a recycled VM whose own server failed
        to bind must still restore."""
        if not envspec.get_bool(EnvKey.BUDDY) \
                or self._ckpt_saver is None:
            return
        handler = self._ckpt_saver.shm_handler
        if handler.header() is not None:
            return  # local snapshot alive; nothing to do
        from dlrover_tpu.checkpoint.buddy import fetch_snapshot

        try:
            buddy = self._client.query_buddy()
        except (ConnectionError, RuntimeError, OSError) as e:
            logger.warning("buddy query failed: %s", e)
            return
        if not buddy.found:
            return
        start = time.monotonic()
        got = fetch_snapshot(buddy.addr, self._config.node_id)
        if got is None:
            logger.info("buddy node %d holds no snapshot for us",
                        buddy.buddy_node_id)
            return
        header, payload = got
        handler.write_raw(header, payload)
        logger.info(
            "restored snapshot step %s (%d bytes) from buddy node %d "
            "in %.2fs", header.get("step"), len(payload),
            buddy.buddy_node_id, time.monotonic() - start,
        )

    def _persist_checkpoint(self, reason: str) -> None:
        """Flush the latest in-memory snapshot to storage before a restart.

        Reference analog: the breakpoint save (ckpt_saver.py:631
        save_shm_to_storage) triggered from training.py:590-610.
        """
        if self._ckpt_saver is None:
            return
        try:
            if self._config.save_on_failure:
                self._ckpt_saver.save_shm_to_storage(reason=reason)
        except Exception:  # noqa: BLE001 - never let persist break restart
            logger.exception("breakpoint checkpoint persist failed")
        finally:
            # a trainer that died holding the shm writer lock must not
            # disable checkpointing for the rest of the job
            self._ckpt_saver.reset_writer_lock()

    # -------------------------------------------------------- network check

    def _run_network_check(self) -> None:
        """Pre-training collective probe with ≤2-round fault bisection.

        Reference analog: NodeCheckElasticAgent.run (training.py:805,956) +
        NetworkCheckRendezvousManager (reference rdzv_manager.py:349).
        Probe round 0 runs in master-assigned pairs; nodes whose pair failed
        are re-paired with known-good partners in round 1, so one bad node
        cannot condemn its healthy neighbor.
        """
        from dlrover_tpu.agent.node_check import run_node_check

        port = find_free_port(self._config.host_ip)
        self._client.join_rendezvous(
            addr=f"{self._config.host_ip}:{port}",
            local_devices=self._local_devices,
            rdzv_name="network-check",
            topology_key=self._config.topology_key,
        )
        world = self._client.wait_comm_world(
            rdzv_name="network-check", timeout=self._config.rdzv_timeout_s
        )
        global_rank = world.world[self._config.node_id]
        for probe_round in (0, 1):
            group = self._wait_probe_group(probe_round)
            if group is None or not group.needed:
                break
            elapsed, ok, local = run_node_check(
                node_rank=group.world[self._config.node_id],
                num_nodes=len(group.world),
                coordinator=group.coordinator,
                global_rank=global_rank,
            )
            self._client.report_network_check(probe_round, ok, elapsed,
                                              local_time=local)
        deadline = time.time() + 120
        while time.time() < deadline:
            status = self._client.get_network_check_status()
            if status.completed:
                bad = set(status.abnormal_nodes)
                if self._config.exclude_straggler:
                    bad |= set(status.straggler_nodes)
                if self._config.node_id in bad:
                    raise RuntimeError(
                        "this node failed the network check; excluding"
                    )
                return
            time.sleep(0.5)
        logger.warning("network check status never completed; proceeding")

    def _wait_probe_group(self, probe_round: int, timeout: float = 300.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            group = self._client.get_network_check_group(probe_round)
            if group.ready:
                return group
            time.sleep(0.5)
        logger.warning("probe round %d group never became ready", probe_round)
        return None


def launch_agent(config: AgentConfig) -> RunResult:
    agent = ElasticAgent(config)
    return agent.run()
