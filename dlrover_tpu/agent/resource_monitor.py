"""Agent-side node resource monitor.

Reference analog: dlrover/python/elastic_agent/monitor/resource.py
(ResourceMonitor: psutil CPU/mem + pynvml GPU -> master every 15s). TPU
differences: host stats come from psutil here in the agent; HBM usage can
only be observed from inside the JAX process that owns the chips, so the
trainer reports it separately (trainer/elastic_trainer.py) and the master
merges the two partial reports (fields <= 0 mean "not measured").
"""

from __future__ import annotations

import threading

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

# set from the TRAINER process (the one that owns the chips) and shipped
# to the master inside its pushed registry snapshot, where the
# exposition endpoint re-renders it with the node label
_device_memory_bytes = registry().gauge(
    "dlrover_tpu_device_memory_bytes",
    "per-device HBM from jax memory_stats() (kind: used | limit)",
    label_names=("device", "kind"),
)


try:
    import psutil
except ImportError:  # stats degrade, the agent must not
    psutil = None


def host_stats() -> tuple[float, int]:
    """(cpu_percent, used_memory_mb) for the whole host."""
    if psutil is None:
        return 0.0, 0
    try:
        cpu = psutil.cpu_percent(interval=None)
        mem = int(psutil.virtual_memory().used / (1 << 20))
        return cpu, mem
    except Exception:  # noqa: BLE001 - stats must never break the agent
        logger.exception("psutil host stats failed")
        return 0.0, 0


class ResourceMonitor:
    """Periodic host-stats reporter thread living in the agent."""

    def __init__(self, client, interval_s: float = 15.0,
                 tpu_chips: int = 0):
        self._client = client
        self._interval_s = interval_s
        self._tpu_chips = tpu_chips
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None

    def start(self) -> None:
        if psutil is None:
            logger.warning(
                "psutil unavailable; host resource monitoring disabled"
            )
            return
        self._thread = threading.Thread(
            target=self._loop, name="resource-monitor", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        # prime cpu_percent's interval-less mode (first call returns 0)
        host_stats()
        while not self._stopped.wait(self._interval_s):
            cpu, mem = host_stats()
            try:
                self._client.report_resource(
                    cpu_percent=cpu, used_memory_mb=mem,
                    tpu_chips=self._tpu_chips,
                )
            except (ConnectionError, RuntimeError, OSError) as e:
                logger.warning("resource report failed: %s", e)


def publish_device_memory() -> int:
    """Per-device HBM used/limit gauges + total used MB.

    Reads ``jax.local_devices()[i].memory_stats()`` — None on backends
    without it (CPU, some tunnels), so every field access is None-safe
    and a statless backend publishes nothing and returns 0. Must only be
    called from the process that owns the chips (the trainer)."""
    try:
        import jax

        total = 0
        for d in jax.local_devices():
            stats = d.memory_stats()
            if not stats:
                continue
            used = int(stats.get("bytes_in_use", 0) or 0)
            limit = int(stats.get("bytes_limit", 0) or 0)
            _device_memory_bytes.labels(str(d.id), "used").set(used)
            if limit > 0:
                _device_memory_bytes.labels(str(d.id), "limit").set(limit)
            total += used
        return total // (1 << 20)
    except Exception:  # noqa: BLE001
        return 0


def local_hbm_used_mb() -> int:
    """HBM bytes in use across this process's local devices (0 if the
    runtime doesn't expose memory_stats — e.g. CPU or tunneled backends).
    Also refreshes the per-device ``dlrover_tpu_device_memory_bytes``
    gauges as a side effect."""
    return publish_device_memory()
