"""Agent-side paral-config tuner: master suggestions -> JSON file.

Reference analog: dlrover/python/elastic_agent/config/paral_config_tuner.py
(:31 ParalConfigTuner — a thread syncing the master's ParallelConfig to a
JSON file named by an env var; the trainer's ElasticDataLoader hot-reloads
it). TPU nuance: batch-geometry knobs (grad accumulation, micro batch)
bake into the compiled program — those are applied at the next trainer
incarnation — while dataloader knobs (prefetch depth) hot-apply.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading

from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


def default_config_path(node_id: int) -> str:
    base = os.environ.get(EnvKey.IPC_DIR) or "/tmp"
    job = os.environ.get(EnvKey.JOB_NAME, "local")
    return os.path.join(base, f"paral_config_{job}_{node_id}.json")


class ParalConfigTuner:
    """Polls the master for config suggestions; mirrors them to a file."""

    def __init__(self, client, path: str = "", interval_s: float = 10.0,
                 on_update=None):
        self._client = client
        self.path = path or default_config_path(client.node_id)
        self._interval_s = interval_s
        self._on_update = on_update  # called with the config dict
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        self._version = -1

    def start(self, first_sync_deadline_s: float = 5.0) -> None:
        # bounded first sync: the config file should exist before the
        # first worker spawn (a restarted agent would otherwise start its
        # worker on an empty config and — with the first-sync callback
        # suppression — never apply a pre-existing suggestion). Bounded
        # because an unreachable master must not stall agent startup for
        # the RPC client's full retry budget; the poll thread finishes
        # the sync in the background.
        def first_sync():
            try:
                self.poll_once()
            except (ConnectionError, RuntimeError, OSError) as e:
                logger.warning("initial paral config sync failed: %s", e)

        t = threading.Thread(target=first_sync, daemon=True)
        t.start()
        t.join(first_sync_deadline_s)
        if t.is_alive():
            logger.warning(
                "initial paral config sync still pending after %.0fs; "
                "proceeding", first_sync_deadline_s,
            )
        self._thread = threading.Thread(
            target=self._loop, name="paral-config-tuner", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stopped.set()

    def poll_once(self) -> bool:
        """Fetch and mirror; True when a new version was written."""
        from dlrover_tpu.common.storage import atomic_write_file

        config = self._client.get_paral_config()
        if config.version == self._version:
            return False
        first_sync = self._version == -1
        data = dataclasses.asdict(config)
        atomic_write_file(json.dumps(data), self.path)
        # only record the sync AFTER the file is durably published — a
        # failed write must not mark the version as delivered
        self._version = config.version
        logger.info("paral config v%d written to %s", config.version,
                    self.path)
        # the startup sync mirrors whatever the master already has; only
        # CHANGES observed while running fire the callback — a freshly
        # spawned worker reads the file anyway, and restarting it for a
        # config it already applied would loop forever (restart_required
        # stays set on the master's latest version)
        if self._on_update is not None and not first_sync:
            self._on_update(data)
        return True

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval_s):
            try:
                self.poll_once()
            except (ConnectionError, RuntimeError, OSError) as e:
                logger.warning("paral config poll failed: %s", e)


class ParalConfigReader:
    """Trainer-side hot reload of the tuner's file (mtime-based)."""

    def __init__(self, path: str = ""):
        # no explicit path and no agent-provided env: stay inert — reading
        # another job's leftover file would apply the wrong batch geometry
        self.path = path or os.environ.get(EnvKey.PARAL_CONFIG_PATH, "")
        self._mtime = 0.0
        self._config: dict = {}

    def current(self) -> dict:
        """Latest config dict ({} before any suggestion arrives)."""
        if not self.path:
            return self._config
        try:
            mtime = os.path.getmtime(self.path)
        except OSError:
            return self._config
        if mtime != self._mtime:
            try:
                with open(self.path) as f:
                    self._config = json.load(f)
                self._mtime = mtime
                logger.info("reloaded paral config v%s",
                            self._config.get("version"))
            except (OSError, json.JSONDecodeError):
                logger.warning("paral config reload failed")
        return self._config

    def get(self, key: str, default=None):
        return self.current().get(key, default)
