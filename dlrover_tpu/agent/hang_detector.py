"""Agent-side hanging detection: progress-timeout -> worker restart.

Reference analog: atorch/atorch/fault_tolerance/hanging_detector.py:86
(HangingDetector: relaunch when training makes no progress within a
timeout) + the TorchTrainingMonitor file channel
(dlrover/python/elastic_agent/monitor/training.py). The master's hang
check (speed_monitor + job_master) sees a job-wide stall through step
reports; this detector is the NODE-local fast path — it catches a wedged
trainer process (deadlocked collective, stuck host callback) without
waiting for the master's global dead-window, and restarts in place.

Channel: the trainer touches a tiny JSON progress file (atomic rename) in
the job's IPC dir every few steps; the agent stats it. A file — not an RPC
or shm — so a fully wedged process can't take the channel down with it,
and the agent can read the last-good step after the child dies.

TPU note on the startup grace: the first step compiles the whole SPMD
program (20-40s single-chip, minutes for big meshes), and every
incarnation recompiles after a membership change. The grace period is
therefore per-spawn, not per-job: ``reset()`` on every (re)spawn.
"""

from __future__ import annotations

import json
import os
import time

from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.storage import atomic_write_file
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_verdicts_total = registry().counter(
    "dlrover_tpu_hang_checks_total",
    "node-local hang-detector verdicts",
    label_names=("verdict",),
)


def progress_path(node_id: int | None = None) -> str:
    from dlrover_tpu.common.multi_process import _socket_dir

    if node_id is None:
        node_id = int(os.environ.get(EnvKey.NODE_ID, "0"))
    return os.path.join(_socket_dir(), f"progress_node{node_id}.json")


class ProgressReporter:
    """Trainer-side: cheap heartbeat-with-step, rate-limited writes."""

    def __init__(self, node_id: int | None = None,
                 min_interval_s: float = 1.0):
        self._path = progress_path(node_id)
        self._min_interval_s = min_interval_s
        # -inf, not 0: monotonic() is host uptime, so 0 would silently
        # rate-limit away the FIRST report on a freshly booted machine
        self._last_write = float("-inf")

    def report(self, step: int) -> None:
        now = time.monotonic()
        if now - self._last_write < self._min_interval_s:
            return
        self._last_write = now
        try:
            atomic_write_file(
                json.dumps({"step": int(step), "ts": time.time()}),
                self._path,
            )
        except OSError as e:  # never let telemetry kill the step loop
            logger.warning("progress report failed: %s", e)


class HangDetector:
    """Agent-side: hung = alive process, no NEW progress for timeout_s.

    Progress = the reported step advancing. A trainer stuck inside one
    step (wedged collective) keeps rewriting the same step number — that
    still counts as hung once ``timeout_s`` passes without the step
    moving. Before the first report, ``startup_grace_s`` applies
    (compilation + data warmup).
    """

    def __init__(self, node_id: int | None = None, *,
                 timeout_s: float = 300.0,
                 startup_grace_s: float = 600.0):
        self._path = progress_path(node_id)
        self.timeout_s = timeout_s
        self.startup_grace_s = startup_grace_s
        self._spawned_at = time.monotonic()
        self._last_step = -1
        self._last_advance = self._spawned_at

    def reset(self) -> None:
        """Call on every (re)spawn: new incarnation, new grace period."""
        self._spawned_at = time.monotonic()
        self._last_step = -1
        self._last_advance = self._spawned_at
        try:
            os.unlink(self._path)
        except OSError:
            pass

    def last_step(self) -> int:
        return self._last_step

    def _read(self) -> int | None:
        try:
            with open(self._path) as f:
                return int(json.load(f)["step"])
        except (OSError, ValueError, KeyError):
            return None

    def check(self, now: float | None = None) -> bool:
        """True when the trainer should be considered hung."""
        now = time.monotonic() if now is None else now
        step = self._read()
        if step is not None and step > self._last_step:
            self._last_step = step
            self._last_advance = now
            _verdicts_total.labels("progress").inc()
            return False
        if self._last_step < 0:
            hung = now - self._spawned_at > self.startup_grace_s
        else:
            hung = now - self._last_advance > self.timeout_s
        _verdicts_total.labels("hung" if hung else "ok").inc()
        if hung:
            # one journal line per verdict: the agent kills + respawns
            # right after, so the restart span carries the consequence
            get_journal().emit(
                "hang_verdict", step=self._last_step,
                stalled_s=round(now - max(self._last_advance,
                                          self._spawned_at), 3),
            )
        return hung
