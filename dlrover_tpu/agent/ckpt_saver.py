"""Agent-side async checkpoint persister (flash checkpoint back half).

Reference analog: AsyncCheckpointSaver in
dlrover/python/elastic_agent/torch/ckpt_saver.py (:344; _sync_shm_to_storage
:515; save_shm_to_storage :631; commit protocol :745,856). The training
process snapshots pytrees into shared memory (checkpoint/shm_handler.py) and
enqueues a save event; this saver — living in the *agent* process so it
survives trainer crashes — drains events and persists shm -> storage with a
done-file + tracker commit protocol. On SIGTERM or before a restart the
agent calls ``save_shm_to_storage`` so no snapshot is ever lost.

Storage layout (one directory per step)::

    <ckpt_dir>/step-<N>/node_<id>.bin        this writer's shard bytes
                                             (persist-flagged pieces
                                             only — replica-group dedup,
                                             DESIGN.md §20), written via
                                             the chunked parallel path
    <ckpt_dir>/step-<N>/node_<id>.meta.json  leaf metas (+ per-piece
                                             crc32) + save config
    <ckpt_dir>/step-<N>/done_<id>_w<W>       per-writer marker carrying
                                             its manifest entry
    <ckpt_dir>/latest                        tracker: committed step number

Commit: every writer also ACKs the master (PersistAckReport); rank-0's
waiter polls the ack ledger (storage markers as the no-master fallback)
and writes the global manifest + tracker only once all W writers are
durable.
"""

from __future__ import annotations

import json
import os
import queue
import signal
import threading
import time
from typing import Optional

from dlrover_tpu.common import envspec
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.multi_process import SharedQueue
from dlrover_tpu.common.storage import (
    CheckpointStorage,
    ClassMeta,
    PosixDiskStorage,
    build_storage,
)
from dlrover_tpu.checkpoint import integrity
from dlrover_tpu.checkpoint.shm_handler import SharedMemoryHandler
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_persist_seconds = registry().histogram(
    "dlrover_tpu_ckpt_persist_seconds",
    "shm -> storage persist duration (write + done marker)",
)
_persist_parallel_seconds = registry().histogram(
    "dlrover_tpu_ckpt_persist_parallel_seconds",
    "this host's shard write through the chunked parallel storage "
    "path — flat in host count by design (no global writer phase)",
)
_persist_bytes = registry().counter(
    "dlrover_tpu_ckpt_persist_bytes_total",
    "checkpoint bytes persisted to storage",
)
_commit_seconds = registry().histogram(
    "dlrover_tpu_ckpt_commit_seconds",
    "all-shards-durable commit wait (rank-0 agent)",
)

EVENT_SAVE = "save"
EVENT_STOP = "stop"


def step_dir(ckpt_dir: str, step: int) -> str:
    return os.path.join(ckpt_dir, f"step-{step}")


def tracker_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, "latest")


def done_marker(node_id: int, num_shards: int) -> str:
    """Commit markers carry the writer world size: a re-save of the same
    step after the job reshaped must not count a previous incarnation's
    markers (stale ``done_3`` from a 4-node save would otherwise commit a
    2-node save early and blend divergent shard files into restores)."""
    return f"done_{node_id}_w{num_shards}"


def read_tracker(storage, ckpt_dir: str) -> tuple[int, int] | None:
    """(committed step, num_shards committed) or None. Accepts the legacy
    plain-int tracker (num_shards defaults to 1)."""
    path = tracker_path(ckpt_dir)
    if not storage.exists(path):
        return None
    text = storage.read_text(path).strip()
    if not text:
        return None
    try:
        data = json.loads(text)
        if isinstance(data, dict):
            return int(data["step"]), int(data.get("num_shards", 1))
    except (ValueError, KeyError):
        pass
    return int(text), 1


class AsyncCheckpointSaver:
    """One async persister per node id (an agent hosts exactly one; tests
    and multi-node-per-host simulations may hold several)."""

    _instances: dict[int, "AsyncCheckpointSaver"] = {}
    _lock = threading.Lock()

    def __init__(self, node_id: int):
        self.node_id = node_id
        self.shm_handler = SharedMemoryHandler(node_id, owner=True)
        self.event_queue = SharedQueue(f"ckpt_event_{node_id}", create=True)
        self._last_persisted_step = -1
        self._stopped = threading.Event()
        self._thread = threading.Thread(
            target=self._sync_loop, name="ckpt-saver", daemon=True
        )
        self._persist_lock = threading.Lock()
        self._commit_lock = threading.Lock()
        self._commit_waiters: dict[int, threading.Thread] = {}

    _signals_registered = False

    @classmethod
    def start(cls, node_id: int) -> "AsyncCheckpointSaver":
        with cls._lock:
            saver = cls._instances.get(node_id)
            if saver is None:
                saver = cls(node_id)
                saver._thread.start()
                cls._register_signal_handlers()
                cls._instances[node_id] = saver
            return saver

    @classmethod
    def reset(cls, node_id: int | None = None) -> None:
        with cls._lock:
            targets = (
                list(cls._instances) if node_id is None else
                [node_id] if node_id in cls._instances else []
            )
            for nid in targets:
                cls._instances.pop(nid).stop()

    @classmethod
    def _register_signal_handlers(cls) -> None:
        # persist the latest snapshots on graceful termination
        # (reference: ckpt_saver.py:470 register_signal_handler). One
        # handler for the process; it walks the live saver registry at fire
        # time, so savers added/reset later are handled correctly.
        if cls._signals_registered:
            return
        if threading.current_thread() is not threading.main_thread():
            return
        orig_term = signal.getsignal(signal.SIGTERM)

        def on_term(signum, frame):
            try:
                for saver in list(cls._instances.values()):
                    try:
                        saver.save_shm_to_storage(reason="SIGTERM")
                    except Exception:  # noqa: BLE001 - keep terminating
                        logger.exception("SIGTERM persist failed")
            finally:
                if callable(orig_term):
                    orig_term(signum, frame)
                else:
                    raise SystemExit(143)

        try:
            signal.signal(signal.SIGTERM, on_term)
            cls._signals_registered = True
        except ValueError:
            pass

    # ------------------------------------------------------------- main loop

    def _sync_loop(self) -> None:
        while not self._stopped.is_set():
            try:
                event = self.event_queue.get(timeout=0.5)
            except queue.Empty:
                continue
            if event.get("kind") == EVENT_STOP:
                break
            if event.get("kind") == EVENT_SAVE:
                try:
                    self._persist_step(int(event["step"]))
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "persist of step %s failed", event.get("step")
                    )

    def _persist_step(self, step: int, lock_timeout: float = 60.0,
                      commit_block_s: float = 0.0) -> bool:
        """Copy shm -> storage. Header and bytes are read under one hold of
        the writer lock (bounded acquire) so a concurrent trainer save can't
        leave us with a header/bytes mismatch, and a crashed lock holder
        can't deadlock the failover path (reference: ckpt_saver.py:556-565
        skips when any rank's lock is held)."""
        with self._persist_lock:
            if not self.shm_handler.lock.acquire(timeout=lock_timeout):
                logger.warning(
                    "shm writer lock busy for %.0fs; skipping persist of "
                    "step %s (dirty shm)", lock_timeout, step,
                )
                return False
            try:
                raw = self.shm_handler.read_raw()
                if raw is None:
                    logger.warning("no snapshot in shm; nothing to persist")
                    return False
                header, buf = raw
                if int(header["step"]) != step:
                    logger.warning(
                        "shm snapshot step %s != requested %s; persisting shm",
                        header["step"], step,
                    )
                    step = int(header["step"])
                if step <= self._last_persisted_step:
                    return True
                total = int(header["total_size"])
                content = bytes(buf[:total])
            finally:
                self.shm_handler.lock.release()
            if len(content) != total:
                logger.error(
                    "shm arena truncated: %d bytes < header total %d; "
                    "refusing to persist step %d", len(content), total, step,
                )
                return False
            self._write_files(header, content, step,
                              commit_block_s=commit_block_s)
            self._last_persisted_step = step
            return True

    @staticmethod
    def _repack_persist_pieces(header: dict, content: bytes
                               ) -> tuple[dict, bytes, dict]:
        """(header', content', pieces): drop pieces flagged
        ``persist=False`` (replica-group dedup — another host's agent
        writes that shard) and recompute offsets + per-piece CRC32s
        over the repacked bytes. ``pieces`` is this writer's manifest
        contribution: piece key -> {crc32, path, index, replica}."""
        index_map = header.get("sharded_index")
        metas = dict(header.get("metas", {}))
        if not index_map:
            return header, content, {}
        kept = {k: e for k, e in index_map.items()
                if e.get("persist", True)}
        new_metas: dict[str, dict] = {}
        pieces: dict[str, dict] = {}
        chunks: list[bytes] = []
        offset = 0
        for key in kept:
            info = metas.get(key)
            if info is None:
                continue
            nbytes = int(info["nbytes"])
            blob = content[info["offset"]:info["offset"] + nbytes]
            new_metas[key] = {**info, "offset": offset,
                              "crc32": integrity.crc32_bytes(blob)}
            pieces[key] = {
                "crc32": new_metas[key]["crc32"],
                "path": kept[key].get("path", key),
                "index": kept[key].get("index", []),
                "replica": int(kept[key].get("replica", 0)),
            }
            chunks.append(blob)
            pad = -(offset + nbytes) % 64
            if pad:
                chunks.append(b"\x00" * pad)
            offset += nbytes + pad
        header = dict(header)
        header["metas"] = new_metas
        header["sharded_index"] = kept
        header["total_size"] = offset
        return header, b"".join(chunks), pieces

    def _write_files(self, header: dict, content: bytes, step: int,
                     commit_block_s: float = 0.0) -> None:
        ckpt_dir = header.get("ckpt_dir", "")
        if not ckpt_dir:
            logger.warning("snapshot has no ckpt_dir; skipping persist")
            return
        storage = self._build_storage(header)
        start = time.monotonic()
        num_shards = int(header.get("num_shards", 1))
        # replica-group dedup: persist only the pieces this host is the
        # designated writer for (checkpoint/sharded.py flags them)
        header, content, pieces = self._repack_persist_pieces(
            header, content
        )
        with get_journal().span("ckpt_persist", step=step,
                                bytes=len(content)):
            sdir = step_dir(ckpt_dir, step)
            storage.makedirs(sdir)
            # integrity manifest: the shard's CRC32 rides in the meta
            # AND the done marker, so rank-0's COMMIT can list every
            # shard's checksum without re-reading the bytes
            # (checkpoint/integrity.py verifies against it at restore)
            crc = integrity.crc32_bytes(content)
            header = dict(header)
            header["crc32"] = crc
            header["bin_bytes"] = len(content)
            shard_entry = {"crc32": crc, "bytes": len(content),
                           "pieces": pieces}
            # one writer per host, chunked concurrent I/O: the blocking
            # cost of a save is this host's OWN shard, independent of
            # how many hosts the job has (Orbax-grade scaling)
            t_par = time.monotonic()
            with get_journal().span("ckpt_persist_shard", step=step,
                                    writer=str(self.node_id),
                                    pieces=len(pieces)):
                storage.write_parallel(
                    content,
                    os.path.join(sdir, f"node_{self.node_id}.bin"),
                    chunk_bytes=envspec.get_int(
                        EnvKey.CKPT_PERSIST_CHUNK_MB) << 20,
                    workers=envspec.get_int(EnvKey.CKPT_PERSIST_WORKERS),
                )
            _persist_parallel_seconds.observe(time.monotonic() - t_par)
            storage.write(
                json.dumps(header),
                os.path.join(sdir, f"node_{self.node_id}.meta.json"),
            )
            storage.write(
                json.dumps(shard_entry),
                os.path.join(sdir, done_marker(self.node_id, num_shards)),
            )
            # inside the span on purpose (§27): the ack report captures
            # this ckpt_persist context at mint, so the master's ledger
            # entry — even a redelivered one — joins this trace tree
            self._ack_persist(step, num_shards, shard_entry)
        _persist_seconds.observe(time.monotonic() - start)
        _persist_bytes.inc(len(content))
        self._maybe_commit(storage, header, step,
                           block_s=commit_block_s)
        logger.info(
            "persisted step %d (%d bytes) in %.2fs",
            step, len(content), time.monotonic() - start,
        )

    def _ack_persist(self, step: int, num_shards: int,
                     shard_entry: dict) -> None:
        """Tell the master this host's shard is durable. Best-effort:
        with no master (solo mode) or a flaky RPC the rank-0 committer
        falls back to the storage done-marker scan."""
        if not os.environ.get(EnvKey.MASTER_ADDR):
            return
        try:
            from dlrover_tpu.agent.master_client import MasterClient

            MasterClient.singleton().report_persist_ack(
                step, num_shards, shard_entry
            )
        except (ConnectionError, RuntimeError, OSError) as e:
            logger.warning("persist ack failed (step %d): %s", step, e)

    def _maybe_commit(self, storage: CheckpointStorage, header: dict,
                      step: int, block_s: float = 0.0) -> None:
        """Rank-0's agent updates the tracker once all shards are durable.

        The marker wait runs in a background thread: other shards may be
        minutes away (or never arrive, when a peer died mid-save), and
        blocking here would stall the agent's restart path — the exact
        path breakpoint saves run on (seen as a 5-minute rendezvous
        stall in the buddy e2e). ``block_s > 0`` additionally joins the
        waiter for that long — the pre-exit paths (SIGTERM, node
        relaunch) use it so a fast commit lands before the process dies,
        without re-introducing the unbounded stall. One waiter per step;
        a newer step's commit superseding an older one is fine (tracker
        is monotonic).
        """
        if int(header.get("node_rank", 0)) != 0:
            return
        ckpt_dir = header["ckpt_dir"]
        num_shards = int(header.get("num_shards", 1))
        with self._commit_lock:
            waiter = self._commit_waiters.get(step)
            if waiter is None:
                waiter = threading.Thread(
                    target=self._commit_wait,
                    name=f"ckpt-commit-{step}",
                    args=(storage, ckpt_dir, step, num_shards),
                    daemon=True,
                )
                self._commit_waiters[step] = waiter
                waiter.start()
        if block_s > 0:
            waiter.join(timeout=block_s)

    def _acked_shards(self, step: int, num_shards: int) -> dict | None:
        """The full shard-manifest map from the master's persist-ack
        ledger, or None when incomplete/unreachable. The RPC path is
        what keeps commit latency flat on object stores whose LIST is
        slow or eventually consistent; the storage scan below stays the
        no-master fallback."""
        if not os.environ.get(EnvKey.MASTER_ADDR):
            return None
        try:
            from dlrover_tpu.agent.master_client import MasterClient

            resp = MasterClient.singleton().persist_status(
                step, num_shards
            )
        except (ConnectionError, RuntimeError, OSError):
            return None
        return dict(resp.shards) if resp.complete else None

    def _commit_wait(self, storage: CheckpointStorage, ckpt_dir: str,
                     step: int, num_shards: int,
                     timeout_s: float = 300.0) -> None:
        """Rank-0's all-hosts-durable wait: every writer must ACK (via
        the master ledger) or leave a done marker (storage fallback)
        before the global manifest + tracker move. A host that died
        mid-save never acks, the wait times out, and the step stays
        invisible to restore — ``resolve_restore_plan`` then serves the
        previous committed step (the chaos acceptance scenario)."""
        sdir = step_dir(ckpt_dir, step)
        suffix = f"_w{num_shards}"
        start = time.monotonic()
        deadline = time.time() + timeout_s
        done: list = []
        try:
            while time.time() < deadline and not self._stopped.is_set():
                shards = self._acked_shards(step, num_shards)
                if shards is None:
                    done = [
                        f for f in storage.listdir(sdir)
                        if f.startswith("done_") and f.endswith(suffix)
                    ]
                    if len(done) >= num_shards:
                        # assemble the manifest from the done markers
                        # (each carries its writer's crc + piece map)
                        shards = {}
                        for f in done:
                            nid = f[len("done_"):-len(suffix)]
                            try:
                                shards[nid] = json.loads(
                                    storage.read_text(
                                        os.path.join(sdir, f))
                                )
                            except (ValueError, OSError):
                                shards[nid] = {}  # legacy empty marker
                if shards is not None:
                    # terminal COMMIT before the tracker moves: the
                    # global manifest of every shard's crc32 + piece
                    # index (restore verifies against it and rolls
                    # back — per shard when a twin exists — on any
                    # mismatch)
                    integrity.write_commit(storage, sdir, step,
                                           num_shards, shards)
                    storage.write(
                        json.dumps(
                            {"step": step, "num_shards": num_shards}
                        ),
                        tracker_path(ckpt_dir),
                    )
                    _commit_seconds.observe(time.monotonic() - start)
                    logger.info(
                        "committed checkpoint step %d (%d shards)",
                        step, num_shards,
                    )
                    return
                time.sleep(0.2)
            logger.error(
                "commit of step %d timed out (%d/%d shards done)", step,
                len(done), num_shards,
            )
        finally:
            with self._commit_lock:
                self._commit_waiters.pop(step, None)

    def _build_storage(self, header: dict) -> CheckpointStorage:
        meta = header.get("storage")
        if meta:
            try:
                return build_storage(ClassMeta.from_dict(meta))
            except Exception:  # noqa: BLE001
                logger.exception("bad storage meta; using posix disk")
        return PosixDiskStorage()

    # -------------------------------------------------------- breakpoint save

    def reset_writer_lock(self) -> None:
        """Release a lock orphaned by a crashed trainer (call pre-respawn)."""
        try:
            self.shm_handler.lock.reset()
        except Exception:  # noqa: BLE001 - never block a restart on this
            logger.exception("writer lock reset failed")

    def save_shm_to_storage(self, reason: str = "") -> None:
        """Persist whatever is in shm right now (pre-restart / SIGTERM).

        Uses a short bounded lock acquire: if the trainer crashed while
        holding the writer lock mid-save the shm is dirty anyway, and
        blocking here would deadlock the agent's restart path.
        Reference analog: ckpt_saver.py:631 save_shm_to_storage.
        """
        header = self.shm_handler.header()
        if not header:
            return
        step = int(header["step"])
        if step <= self._last_persisted_step:
            # already persisted — but its background COMMIT may still be
            # polling for peer shards; exiting now would orphan a fully
            # durable checkpoint the tracker never points at. Join it
            # briefly (same budget as the persist path's commit join).
            with self._commit_lock:
                waiter = self._commit_waiters.get(
                    self._last_persisted_step
                )
            if waiter is not None:
                waiter.join(timeout=15.0)
            return
        logger.info("breakpoint save of step %d (%s)", step, reason)
        # short commit join: this path often precedes process exit, and
        # a durable-but-uncommitted checkpoint is invisible to restore
        self._persist_step(step, lock_timeout=5.0, commit_block_s=15.0)

    def stop(self) -> None:
        self._stopped.set()
        self.shm_handler.close()
        self.event_queue.close()

    @property
    def last_persisted_step(self) -> int:
        return self._last_persisted_step
