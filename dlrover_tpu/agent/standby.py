"""Warm-standby trainers: pre-spawned, parked, promoted on failure.

The dominant per-failure cost on the recovery path is serial process
bring-up: a cold trainer spawn pays interpreter start + the Python/JAX
import graph (seconds) before it can even begin rendezvous-dependent
work. The agent therefore keeps ONE standby trainer per node that has
already paid those costs and is parked inside
``bootstrap.init_from_env`` waiting for a rendezvous payload. On worker
death, ``ElasticAgent`` *promotes* the standby — hands it the payload
over a file-based IPC handshake — instead of cold-starting a process,
then re-arms a fresh standby in the background.

What the standby pre-pays: process spawn, Python + JAX import, platform
config, compilation-cache setup, flight-recorder arming. What it must
NOT touch before promotion: the accelerator backend (TPU chips are
exclusive-access — the dying trainer still owns them) and
``jax.distributed.initialize`` (needs the coordinator address only the
completed rendezvous provides). Both happen immediately after the
payload lands.

Handshake (all under the IPC dir, atomic renames only):

- ``<base>.ready``   written by the parked child: imports done, parked.
- ``<base>.prepare`` written by the agent at failure time, BEFORE the
  rendezvous round: carries the checkpoint dir so the standby starts
  the storage restore prefetch (``checkpoint/engine.py``) concurrently
  with rendezvous — the overlapped-restore half of warm recovery.
- ``<base>``         the promotion payload: the env-var dict a cold
  spawn would have received; the child adopts it and resumes bring-up.

Disable with ``DLROVER_TPU_STANDBY=0`` (the promotion path is also
skipped whenever the standby died while parked — promotion falls back
to a cold spawn, so the feature can only ever help).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import tempfile
import threading
import time

from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.storage import atomic_write_file
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_promotions_total = registry().counter(
    "dlrover_tpu_standby_promotions_total",
    "trainer respawns served by promoting a pre-spawned standby",
    label_names=("warm",),
)
_warm_gauge = registry().gauge(
    "dlrover_tpu_standby_warm",
    "1 while a fully-parked standby trainer is available on this node",
)

_POLL_S = 0.05


def standby_enabled() -> bool:
    return os.environ.get(EnvKey.STANDBY, "1") != "0"


def _handshake_dir() -> str:
    return os.environ.get(EnvKey.IPC_DIR) or tempfile.gettempdir()


def _atomic_write(path: str, payload: dict) -> None:
    # one blessed publisher for every handshake file (tmp + fsync +
    # rename, and the chaos storage_write injection point rides along)
    atomic_write_file(json.dumps(payload), path)


class StandbyManager:
    """Agent-side: owns at most one parked standby trainer process."""

    def __init__(self, entrypoint: list[str], node_id: int,
                 base_env: dict | None = None):
        self._entrypoint = list(entrypoint)
        self._node_id = node_id
        self._base_env = base_env
        self._proc: subprocess.Popen | None = None
        self._payload_path = ""
        self._serial = 0
        self._lock = threading.Lock()
        self._armed_at = 0.0

    # ------------------------------------------------------------------ arm

    def arm(self) -> None:
        """Spawn a fresh standby (non-blocking: the child pays its import
        cost in the background). No-op if one is already parked alive."""
        with self._lock:
            if self._proc is not None and self._proc.poll() is None:
                return
            self._serial += 1
            base = os.path.join(
                _handshake_dir(),
                f"standby_{self._node_id}_{os.getpid()}_{self._serial}.json",
            )
            self._cleanup_files(base)
            env = dict(self._base_env or os.environ)
            env.update({
                EnvKey.NODE_ID: str(self._node_id),
                EnvKey.STANDBY_FILE: base,
            })
            # stale rank/coordinator vars from the agent's own env must
            # not leak into the parked child: promotion delivers them
            for key in (EnvKey.NODE_RANK, EnvKey.NODE_NUM,
                        EnvKey.COORDINATOR, EnvKey.RESTART_COUNT):
                env.pop(key, None)
            try:
                self._proc = subprocess.Popen(
                    self._entrypoint, env=env, start_new_session=True
                )
            except OSError as e:
                logger.warning("standby spawn failed: %s", e)
                self._proc = None
                return
            self._payload_path = base
            self._armed_at = time.monotonic()
            logger.info("standby trainer armed (pid %d)", self._proc.pid)

    def arm_async(self) -> None:
        threading.Thread(target=self.arm, name="standby-arm",
                         daemon=True).start()

    # ----------------------------------------------------------- inspection

    def is_warm(self) -> bool:
        """Alive AND fully parked (imports done)."""
        with self._lock:
            warm = (
                self._proc is not None and self._proc.poll() is None
                and os.path.exists(self._payload_path + ".ready")
            )
        _warm_gauge.set(1 if warm else 0)
        return warm

    # ------------------------------------------------------------- failover

    def prepare(self, ckpt_dir: str) -> bool:
        """Failure detected: tell the parked standby to start its restore
        prefetch NOW, so the storage read + integrity verification run
        concurrently with the rendezvous round the agent is about to
        enter. Safe to call only after the breakpoint persist completed
        (the prefetch must see the newest storage state)."""
        with self._lock:
            if not ckpt_dir or self._proc is None \
                    or self._proc.poll() is not None:
                return False
            try:
                _atomic_write(self._payload_path + ".prepare",
                              {"ckpt_dir": ckpt_dir})
            except OSError as e:
                logger.warning("standby prepare write failed: %s", e)
                return False
        return True

    def promote(self, env_update: dict) -> subprocess.Popen | None:
        """Hand the rendezvous payload to the parked standby; it becomes
        the live trainer. Returns None (caller cold-spawns) when no
        live standby exists."""
        with self._lock:
            proc, path = self._proc, self._payload_path
            if proc is None or proc.poll() is not None:
                self._proc = None
                _warm_gauge.set(0)
                return None
            warm = os.path.exists(path + ".ready")
            with get_journal().span(
                "standby_promote", pid=proc.pid, warm=warm,
                parked_s=round(time.monotonic() - self._armed_at, 3),
            ):
                try:
                    _atomic_write(path, {"env": env_update})
                except OSError as e:
                    logger.warning(
                        "standby promotion failed (%s); cold spawn", e)
                    return None
            _promotions_total.labels("1" if warm else "0").inc()
            _warm_gauge.set(0)
            self._proc = None
            logger.info(
                "promoted standby pid %d (warm=%s) to live trainer",
                proc.pid, warm,
            )
            return proc

    # ------------------------------------------------------------- teardown

    def discard(self) -> None:
        """Kill the parked standby (agent shutdown / feature turn-off)."""
        with self._lock:
            proc, self._proc = self._proc, None
            path, self._payload_path = self._payload_path, ""
        _warm_gauge.set(0)
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
                proc.wait(timeout=10)
            except (ProcessLookupError, subprocess.TimeoutExpired):
                pass
        if path:
            self._cleanup_files(path)

    @staticmethod
    def _cleanup_files(base: str) -> None:
        for suffix in ("", ".ready", ".prepare"):
            try:
                os.remove(base + suffix)
            except OSError:
                pass


def parked_standby_pids(ipc_dir: str | None = None) -> set[int]:
    """PIDs of currently-parked standbys on this host (from the
    ``.ready`` markers, which carry the child's pid and are removed at
    promotion). Kill-based harnesses (bench fault injection, sigkill
    e2e tests) use this to aim at the LIVE trainer — a parked standby
    has the same cmdline, and killing it would silently turn the next
    recovery cold without testing anything."""
    d = ipc_dir or _handshake_dir()
    pids: set[int] = set()
    try:
        names = os.listdir(d)
    except OSError:
        return pids
    for name in names:
        if not (name.startswith("standby_") and name.endswith(".ready")):
            continue
        try:
            with open(os.path.join(d, name), encoding="utf-8") as f:
                pids.add(int(f.read().strip()))
        except (OSError, ValueError):
            continue
    return pids


# -------------------------------------------------------------- child side


def park_if_standby() -> dict | None:
    """Called from ``bootstrap.init_from_env``: if this process was
    spawned as a standby, publish readiness and block until the agent
    delivers the promotion payload, then adopt its env vars and return
    the payload. Returns None in a normally-spawned trainer.

    A ``.prepare`` file observed while parked starts the checkpoint
    restore prefetch immediately (overlapping the master's rendezvous
    round); the registered prefetch is later consumed by the
    ``CheckpointEngine`` the promoted trainer builds.
    """
    path = os.environ.pop(EnvKey.STANDBY_FILE, "")
    if not path:
        return None
    try:
        # the agent polls for this marker: atomic publish so it can
        # never read a torn/empty pid
        atomic_write_file(str(os.getpid()), path + ".ready")
    except OSError as e:
        logger.warning("standby ready marker write failed: %s", e)
    logger.info("standby trainer parked; waiting for promotion")
    prefetch_started = False
    agent_pid = os.getppid()
    while True:
        if os.path.exists(path):
            break
        if os.getppid() != agent_pid:
            # the agent died (own-session child: its killpg missed us);
            # an orphaned standby polling forever would leak one parked
            # interpreter per hard-killed agent
            logger.info("standby orphaned (agent gone); exiting")
            raise SystemExit(0)
        if not prefetch_started and os.path.exists(path + ".prepare"):
            prefetch_started = True
            try:
                with open(path + ".prepare", encoding="utf-8") as f:
                    ckpt_dir = json.load(f).get("ckpt_dir", "")
                if ckpt_dir:
                    from dlrover_tpu.checkpoint.engine import (
                        start_restore_prefetch,
                    )

                    start_restore_prefetch(
                        ckpt_dir,
                        node_id=int(os.environ.get(EnvKey.NODE_ID, "0")),
                    )
                    logger.info(
                        "standby: restore prefetch started for %s "
                        "(overlapping rendezvous)", ckpt_dir,
                    )
            except (OSError, ValueError) as e:
                logger.warning("standby prepare read failed: %s", e)
        time.sleep(_POLL_S)
    try:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError) as e:
        # a torn payload would strand this process with no rank: die and
        # let the agent's monitor loop cold-spawn a replacement
        logger.error("standby payload unreadable: %s", e)
        raise SystemExit(1)
    env_update = payload.get("env", {})
    os.environ.update({k: str(v) for k, v in env_update.items()})
    for suffix in ("", ".ready", ".prepare"):
        try:
            os.remove(path + suffix)
        except OSError:
            pass
    logger.info(
        "standby promoted: rank %s of %s, coordinator %s",
        env_update.get(EnvKey.NODE_RANK),
        env_update.get(EnvKey.NODE_NUM),
        env_update.get(EnvKey.COORDINATOR),
    )
    return payload
