"""Typed client for all agent/trainer -> master calls.

Reference analog: dlrover/python/elastic_agent/master_client.py (:49
MasterClient, API surface :122-404). One singleton per process, address from
``EnvKey.MASTER_ADDR``.
"""

from __future__ import annotations

import os
import random
import threading
import time
import uuid
from collections import deque
from typing import Optional

from dlrover_tpu.common import envspec
from dlrover_tpu.common import messages as m
from dlrover_tpu.common.constants import (
    EnvKey,
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.rpc import RpcClient

logger = get_logger(__name__)

_reconcile_total = None
_redelivery_total = None


def _failover_metrics():
    """Lazy registration keeps this module import-light (it is pulled
    in by trainer children before jax init)."""
    global _reconcile_total, _redelivery_total
    if _reconcile_total is None:
        from dlrover_tpu.telemetry.metrics import registry

        _reconcile_total = registry().counter(
            "dlrover_tpu_agent_reconcile_total",
            "epoch-fence reconciles run after observing a master "
            "restart (re-register + full metrics push + redelivery "
            "replay)",
        )
        _redelivery_total = registry().counter(
            "dlrover_tpu_agent_redelivery_total",
            "redelivery-queue traffic for unacked one-way reports, by "
            "outcome",
            label_names=("outcome",),
        )
    return _reconcile_total, _redelivery_total


def _mint_sctx() -> str:
    """Span context (§27) captured at message MINT time: a queued
    report replayed by flush_redelivery later must carry the context
    of the work that produced it, not of the reconcile that flushed
    it. Import is local to keep this module import-light."""
    from dlrover_tpu.telemetry.journal import current_ctx

    return current_ctx()


class MasterClient:
    _instance: Optional["MasterClient"] = None
    _instance_lock = threading.Lock()

    def __init__(self, master_addr: str, node_id: int, transport=None,
                 snapshot_full_every: int | None = None,
                 port_file: str | None = None,
                 fallback_port_file: str | None = None,
                 epoch_observer=None,
                 link: tuple[str, str] | None = None):
        # ``transport`` is any object with RpcClient's call/close
        # surface; the fleet simulator passes an in-process loopback so
        # thousands of simulated agents exercise the genuine typed
        # client + serde path without a socket each.
        # ``link`` names the src/dst tiers for the net_partition chaos
        # point (§30); a rack-attached client (it has a fallback file)
        # starts on the agent->rack edge.
        if link is None:
            link = (("agent", "rack") if fallback_port_file
                    else ("agent", "root"))
        self._client = transport or RpcClient(master_addr, link=link)
        self.node_id = node_id
        # target-keyed re-dial (§28): the atomic port file THIS client's
        # target republishes after a restart. None falls back to the
        # root master's file (EnvKey.MASTER_PORT_FILE) — the pre-rack
        # behavior. A rack-attached agent passes its sub-master's file
        # plus the root's as ``fallback_port_file``: when the rack file
        # yields no fresh address the client degrades to dialing the
        # root directly, and returns to the rack the moment a restarted
        # sub-master republishes.
        self._port_file = port_file
        self._fallback_port_file = fallback_port_file
        # sticky re-dial (§30): which port file the client is currently
        # attached through, and the earliest time a fallback-pinned
        # client probes the rack file again. Without the pin, every
        # re-dial tried the (dead) rack address first and the client
        # flapped rack->root on every transient error.
        self._active_target = "primary"
        self._rack_retry_at = 0.0
        # replaces the built-in agent reconcile as the reaction to a
        # transport-envelope epoch change: the rack sub-master handles
        # a root restart by re-registering its rack instead (§28)
        self._epoch_observer = epoch_observer
        # per-role delta state for metrics pushes (one pushing loop per
        # role per process: heartbeat thread, trainer cadence, gateway)
        self._snapshot_full_every = snapshot_full_every
        self._delta_trackers: dict[str, "SnapshotDeltaTracker"] = {}
        # epoch fence (DESIGN.md §26): last master epoch observed on a
        # response (field or transport envelope); an INCREASE triggers
        # the reconcile, a decrease is a stale master and is ignored
        self._epoch_lock = threading.Lock()
        self._master_epoch = 0
        self._reconciling = False
        # bounded redelivery queue of unacked one-way reports
        # (PersistAckReport/FailureReport), replayed on reconnect with
        # their original rids — the master dedups, so replay can never
        # double-count
        self._redelivery: deque = deque()
        self._redelivery_bound = int(
            envspec.get_int(EnvKey.REDELIVERY_QUEUE, 64) or 64
        )
        self._wire_epoch_hook(self._client)

    def _wire_epoch_hook(self, transport) -> None:
        # RpcClient forwards the response-envelope epoch; other
        # transports (fleetsim loopback) fence via the explicit
        # HeartbeatResponse/CommWorldResponse fields instead
        if hasattr(transport, "on_epoch"):
            transport.on_epoch = \
                self._epoch_observer or self._observe_epoch

    # ------------------------------------------------------- epoch fence

    @property
    def master_epoch(self) -> int:
        with self._epoch_lock:
            return self._master_epoch

    def _observe_epoch(self, epoch: int) -> None:
        if epoch <= 0:
            return
        with self._epoch_lock:
            prev = self._master_epoch
            if epoch <= prev:
                return  # unchanged, or a stale/zombie master: fenced
            self._master_epoch = epoch
            first = prev == 0
            if self._reconciling:
                return
            self._reconciling = True
        if first:
            # first contact with any master: adopt, nothing to repair
            with self._epoch_lock:
                self._reconciling = False
            return
        try:
            self._reconcile(prev, epoch)
        finally:
            with self._epoch_lock:
                self._reconciling = False

    def _reconcile(self, old_epoch: int, new_epoch: int) -> None:
        """The epoch-fence reconcile: the master restarted between our
        last two RPCs. Re-register this node, force the next metrics
        push to a full snapshot (the restarted master's delta base is
        empty), and replay any unacked reports (rid-idempotent on the
        master's side)."""
        from dlrover_tpu.telemetry.journal import get_journal

        reconciles, _ = _failover_metrics()
        reconciles.inc()
        get_journal().emit(
            "agent_reconcile", node=self.node_id,
            old_epoch=old_epoch, new_epoch=new_epoch,
            queued=len(self._redelivery),
        )
        logger.warning(
            "master epoch changed %d -> %d (master restarted): "
            "reconciling (%d queued reports to replay)",
            old_epoch, new_epoch, len(self._redelivery),
        )
        try:
            self.report_node_event(
                NodeEventType.MODIFIED, NodeStatus.RUNNING.value
            )
        except (ConnectionError, TimeoutError, OSError) as e:
            logger.warning("reconcile re-register failed: %s", e)
        for tracker in self._delta_trackers.values():
            tracker.force_full()
        self.flush_redelivery()

    # --------------------------------------------------- redelivery queue

    def _send_or_queue(self, msg) -> bool:
        """Send a one-way report; on transport failure try one re-dial
        (the master may have restarted on a new port) and otherwise
        queue the message — same rid — for replay on reconnect."""
        try:
            self._client.call(msg)
            return True
        except (ConnectionError, TimeoutError, OSError) as first:
            if self.maybe_redial():
                try:
                    self._client.call(msg)
                    return True
                except (ConnectionError, TimeoutError, OSError):
                    pass
            _, redelivery = _failover_metrics()
            self._redelivery.append(msg)
            redelivery.labels("queued").inc()
            while len(self._redelivery) > self._redelivery_bound:
                self._redelivery.popleft()
                redelivery.labels("dropped").inc()
            logger.warning(
                "%s queued for redelivery (master unreachable: %s; "
                "%d queued)", type(msg).__name__, first,
                len(self._redelivery),
            )
            return False

    def flush_redelivery(self) -> int:
        """Replay queued reports in order; stops at the first transport
        failure (they stay queued). Returns how many were delivered."""
        _, redelivery = _failover_metrics()
        sent = 0
        while self._redelivery:
            msg = self._redelivery[0]
            try:
                self._client.call(msg)
            except (ConnectionError, TimeoutError, OSError):
                break
            self._redelivery.popleft()
            redelivery.labels("replayed").inc()
            sent += 1
        return sent

    @property
    def redelivery_pending(self) -> int:
        return len(self._redelivery)

    # ------------------------------------------------------------ re-dial

    def _read_port_file(self, path: str) -> str | None:
        """host:port from one atomic port file, or None when the file
        is missing/garbled or names the address already dialed."""
        try:
            with open(path) as f:
                port = int(f.read().strip())
        except (OSError, ValueError):
            return None
        host = self._client.addr.rsplit(":", 1)[0]
        new_addr = f"{host}:{port}"
        return None if new_addr == self._client.addr else new_addr

    def _arm_rack_retry(self, now: float) -> None:
        """Schedule the next rack-file probe while pinned to the
        fallback: RACK_RETRY_S jittered ±20% so a rack's worth of
        fallback-pinned agents don't re-probe (and potentially
        re-attach, re-register, re-join) in lockstep."""
        retry_s = float(envspec.get_float(EnvKey.RACK_RETRY_S) or 5.0)
        self._rack_retry_at = now + retry_s * random.uniform(0.8, 1.2)

    def maybe_redial(self, prefer_fallback: bool = False) -> bool:
        """Re-resolve this client's TARGET from its atomic port file —
        a restarted master (root or rack sub-master) binds a fresh port
        and republishes it there. The file is target-keyed (§28): a
        rack-attached client re-resolves its sub-master's own file, and
        when that yields nothing fresh falls back to the root's file
        (degraded direct-to-root). The re-dial is STICKY (§30): while
        pinned to the fallback it re-probes the rack file only every
        RACK_RETRY_S (jittered), instead of flapping back to a dead
        rack address on every transient error; a respawned sub-master
        reclaims its agents at the next probe. ``prefer_fallback``
        skips the rack probe entirely — the sub-master itself told this
        agent to go to the root (lease lapsed, fail-closed redirect).
        Returns True when the client moved to a new address."""
        if not isinstance(self._client, RpcClient):
            return False
        primary = self._port_file or envspec.get(EnvKey.MASTER_PORT_FILE)
        fallback = self._fallback_port_file
        now = time.monotonic()
        new_addr, target = None, ""
        if prefer_fallback and fallback:
            new_addr = self._read_port_file(fallback)
            target = "fallback"
            self._arm_rack_retry(now)
        else:
            probe_primary = bool(primary) and (
                self._active_target != "fallback"
                or now >= self._rack_retry_at
            )
            if probe_primary:
                new_addr = self._read_port_file(primary)
                if new_addr is not None:
                    target = "primary"
                elif self._active_target == "fallback":
                    # rack still gone/unchanged: back off the probe
                    self._arm_rack_retry(now)
            if new_addr is None and fallback:
                new_addr = self._read_port_file(fallback)
                if new_addr is not None:
                    target = "fallback"
                    self._arm_rack_retry(now)
        if new_addr is None:
            return False
        old = self._client
        fresh = old.clone(new_addr)
        # the partition edge follows the target tier (§30)
        if fallback:
            fresh.link = (("agent", "rack") if target == "primary"
                          else ("agent", "root"))
        self._wire_epoch_hook(fresh)
        self._client = fresh
        self._active_target = target
        old.close()
        logger.info("re-dialed master at %s (was %s, via %s file)",
                    new_addr, old.addr, target)
        return True

    # ------------------------------------------------------------- singleton

    @classmethod
    def singleton(cls) -> "MasterClient":
        with cls._instance_lock:
            if cls._instance is None:
                addr = os.environ.get(EnvKey.MASTER_ADDR, "")
                if not addr:
                    raise RuntimeError(
                        f"{EnvKey.MASTER_ADDR} is not set; is this process "
                        "running under the dlrover-tpu agent?"
                    )
                node_id = int(os.environ.get(EnvKey.NODE_ID, "0"))
                cls._instance = cls(addr, node_id)
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._instance_lock:
            if cls._instance is not None:
                cls._instance.close()
            cls._instance = None

    def close(self) -> None:
        self._client.close()

    # ------------------------------------------------------------ rendezvous

    def join_rendezvous(self, addr: str, local_devices: int,
                        rdzv_name: str = "training",
                        topology_key: str = "") -> int:
        resp = self._client.call(
            m.JoinRendezvousRequest(
                node_id=self.node_id, rdzv_name=rdzv_name, addr=addr,
                local_devices=local_devices, topology_key=topology_key,
            )
        )
        return resp.round

    def get_comm_world(self, rdzv_name: str = "training"
                       ) -> m.CommWorldResponse:
        resp = self._client.call(
            m.CommWorldRequest(node_id=self.node_id, rdzv_name=rdzv_name)
        )
        self._observe_epoch(int(getattr(resp, "master_epoch", 0) or 0))
        return resp

    def wait_comm_world(self, rdzv_name: str = "training",
                        timeout: float = 600.0,
                        poll_interval: float = 0.2) -> m.CommWorldResponse:
        """Polls through a master outage: transport errors re-resolve
        the master address from the port file and keep polling until
        the rendezvous timeout — a master restart mid-rendezvous is a
        delay, not an agent crash (DESIGN.md §26)."""
        deadline = time.time() + timeout
        last_err: Exception | None = None
        while time.time() < deadline:
            try:
                resp = self.get_comm_world(rdzv_name)
            except (ConnectionError, TimeoutError, OSError) as e:
                last_err = e
                self.maybe_redial()
                time.sleep(poll_interval)
                continue
            if resp.completed:
                return resp
            if getattr(resp, "redirect", False):
                # the rack sub-master failed closed (lease lapsed or
                # superseded, §30): finish this round directly against
                # the root instead of waiting out the rack
                self.maybe_redial(prefer_fallback=True)
            time.sleep(poll_interval)
        raise TimeoutError(
            f"rendezvous {rdzv_name!r} did not complete in {timeout}s"
            + (f" (last master error: {last_err})" if last_err else "")
        )

    def num_nodes_waiting(self, rdzv_name: str = "training") -> int:
        return self._client.call(
            m.NumNodesWaitingRequest(rdzv_name=rdzv_name)
        ).waiting_num

    # -------------------------------------------------------------- kv store

    def kv_set(self, key: str, value: bytes) -> None:
        self._client.call(m.KVStoreSetRequest(key=key, value=value))

    def kv_get(self, key: str) -> bytes | None:
        resp = self._client.call(m.KVStoreGetRequest(key=key))
        return resp.value if resp.found else None

    def kv_wait(self, key: str, timeout: float = 60.0) -> bytes | None:
        deadline = time.time() + timeout
        while time.time() < deadline:
            v = self.kv_get(key)
            if v is not None:
                return v
            time.sleep(0.1)
        return None

    def kv_add(self, key: str, amount: int = 1) -> int:
        return self._client.call(
            m.KVStoreAddRequest(key=key, amount=amount)
        ).number

    def barrier(self, name: str, world_size: int, timeout: float = 60.0
                ) -> bool:
        """All-node barrier over the master counter."""
        self.kv_add(f"barrier/{name}", 1)
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.kv_add(f"barrier/{name}", 0) >= world_size:
                return True
            time.sleep(0.1)
        return False

    def sync_join(self, sync_name: str) -> int:
        """Join a named sync group; returns the member count so far.

        Reference analog: MasterClient.join_sync (reference
        master_client.py); the master counts joiners in its kv store.
        """
        return self._client.call(
            m.SyncJoin(node_id=self.node_id, sync_name=sync_name)
        ).number

    def sync_finished(self, sync_name: str) -> int:
        """Current member count of a sync group without joining."""
        return self._client.call(
            m.SyncFinishedRequest(sync_name=sync_name)
        ).number

    # --------------------------------------------------------- compile cache

    def compile_cache_put(self, key: str, payload: bytes,
                          meta: dict | None = None) -> bool:
        resp = self._client.call(
            m.CompileCachePutRequest(
                node_id=self.node_id, key=key, payload=payload,
                meta=meta or {},
            )
        )
        return bool(resp.success)

    def compile_cache_get(self, key: str
                          ) -> tuple[bytes, dict] | None:
        resp = self._client.call(
            m.CompileCacheGetRequest(node_id=self.node_id, key=key)
        )
        return (resp.payload, resp.meta) if resp.found else None

    def compile_cache_query(self, topology: str
                            ) -> m.CompileCacheQueryResponse:
        """Coverage for a topology tag (kv_store.topology_tag) — the
        agent's reshard-with-fallback vs cold-restart decision input."""
        return self._client.call(
            m.CompileCacheQueryRequest(
                node_id=self.node_id, topology=topology
            )
        )

    # ------------------------------------------------------- persist acks

    def report_persist_ack(self, step: int, num_shards: int,
                           shard: dict, *, writer_id: int | str | None = None,
                           group: str = "") -> None:
        """Ack this host's durable checkpoint shard to the master's
        ledger; the rank-0 committer assembles the global manifest from
        these instead of polling storage (DESIGN.md §20). ``writer_id``
        overrides the manifest key for non-host writers (the embedding
        fabric acks ``emb-<i>`` shard servers under ``group=
        "embedding"`` so its ledger entries can never complete a dense
        commit of the same step/world, §25).

        Transport failures never raise: the ack is queued (with its
        rid) for replay on reconnect — the rank-0 committer's storage
        done-marker scan covers the gap meanwhile (§26)."""
        self._send_or_queue(
            m.PersistAckReport(
                node_id=(self.node_id if writer_id is None
                         else writer_id),
                step=step, num_shards=num_shards, shard=shard,
                group=group, rid=uuid.uuid4().hex, sctx=_mint_sctx(),
            )
        )

    def persist_status(self, step: int, num_shards: int, *,
                       group: str = "") -> m.PersistStatusResponse:
        return self._client.call(
            m.PersistStatusRequest(
                node_id=self.node_id, step=step, num_shards=num_shards,
                group=group,
            )
        )

    # ---------------------------------------------------- buddy replication

    def report_buddy_endpoint(self, addr: str) -> None:
        self._client.call(
            m.ReportBuddyEndpoint(node_id=self.node_id, addr=addr)
        )

    def report_preemption_notice(self, deadline_s: float = 0.0) -> None:
        self._client.call(
            m.PreemptionNotice(node_id=self.node_id,
                               deadline_s=deadline_s)
        )

    def query_buddy(self) -> m.BuddyQueryResponse:
        return self._client.call(
            m.BuddyQueryRequest(node_id=self.node_id)
        )

    # ------------------------------------------------------- health / status

    def report_heartbeat(self, restart_count: int = 0) -> str:
        resp = self._client.call(
            m.NodeHeartbeat(node_id=self.node_id,
                            restart_count=restart_count)
        )
        self._observe_epoch(int(getattr(resp, "master_epoch", 0) or 0))
        if self._redelivery:
            # the master is reachable again (maybe it never died, just
            # a partition): drain whatever queued meanwhile
            self.flush_redelivery()
        return resp.action

    def report_node_event(
        self,
        event_type: NodeEventType,
        status: str = "",
        exit_reason: NodeExitReason = NodeExitReason.UNKNOWN,
        message: str = "",
    ) -> None:
        self._client.call(
            m.NodeEventReport(
                node_id=self.node_id, event_type=event_type, status=status,
                exit_reason=exit_reason, message=message,
            )
        )

    def report_failure(self, error_data: str, restart_count: int = 0,
                       level: TrainingExceptionLevel =
                       TrainingExceptionLevel.PROCESS_ERROR) -> None:
        """Transport failures never raise: a failure report during a
        master outage is queued for rid-deduped replay — the agent's
        restart ladder must keep moving while the master is down
        (§26)."""
        self._send_or_queue(
            m.FailureReport(
                node_id=self.node_id, restart_count=restart_count,
                level=level, error_data=error_data,
                rid=uuid.uuid4().hex, sctx=_mint_sctx(),
            )
        )

    def report_resource(self, cpu_percent: float, used_memory_mb: int,
                        tpu_chips: int = 0, used_hbm_mb: int = 0) -> None:
        self._client.call(
            m.ResourceStats(
                node_id=self.node_id, cpu_percent=cpu_percent,
                used_memory_mb=used_memory_mb, tpu_chips=tpu_chips,
                used_hbm_mb=used_hbm_mb,
            )
        )

    def report_step(self, step: int) -> None:
        self._client.call(m.GlobalStepReport(node_id=self.node_id, step=step))

    def get_job_stats(self, include_series: bool = False
                      ) -> m.JobStatsResponse:
        return self._client.call(
            m.JobStatsRequest(node_id=self.node_id,
                              include_series=include_series)
        )

    def report_metrics(self, samples: list, role: str = "agent") -> None:
        """Push this process's metrics-registry snapshot
        (telemetry/metrics.py) for the master's aggregated exposition.

        Pushes are delta-compressed (telemetry/snapshot_delta.py):
        between periodic full snapshots only the families whose content
        changed since the last *acknowledged* push go on the wire — the
        tracker commits its base only after the RPC returned, so a lost
        push re-sends what the master missed."""
        tracker = self._delta_trackers.get(role)
        if tracker is None:
            from dlrover_tpu.telemetry.snapshot_delta import (
                SnapshotDeltaTracker,
            )

            tracker = self._delta_trackers[role] = SnapshotDeltaTracker(
                full_every=self._snapshot_full_every
            )
        payload, is_delta = tracker.prepare(samples)
        self._client.call(
            m.MetricsSnapshotRequest(
                node_id=self.node_id, role=role, samples=payload,
                is_delta=is_delta,
            )
        )
        tracker.commit()

    def report_autopilot_plan(self, plan_json: str,
                              alternatives_json: list | None = None,
                              step_batch: int = 0) -> None:
        """Arm the master's autopilot controller (DESIGN.md §24) with
        the plan this trainer launched and the planner's ranked
        alternatives — the retune menu a sustained plan-vs-measured
        contradiction picks from. ``step_batch`` states the running
        loader's per-step global batch so the controller never arms an
        alternative the trainer's apply path would veto."""
        self._client.call(
            m.AutopilotPlanReport(
                node_id=self.node_id, plan_json=plan_json,
                alternatives_json=list(alternatives_json or []),
                step_batch=int(step_batch),
            )
        )

    def report_debug_bundle(self, path: str, reason: str,
                            proc: str = "") -> None:
        """Tell the master a flight-recorder bundle landed on this node
        (telemetry/bundle.py), so one master query lists them all."""
        import socket

        self._client.call(
            m.DebugBundleReport(
                node_id=self.node_id, path=path, reason=reason,
                host=socket.gethostname(), proc=proc,
                timestamp=time.time(),
            )
        )

    def list_debug_bundles(self) -> list[m.DebugBundleReport]:
        return self._client.call(
            m.DebugBundleListRequest(node_id=self.node_id)
        ).bundles

    def request_profile(self, node_id: int, steps: int = 5
                        ) -> m.ProfileResponse:
        """Arm an on-demand jax.profiler capture on ``node_id`` for
        ``steps`` train steps (telemetry/efficiency.py); the xplane
        trace lands as a debug bundle on that node."""
        return self._client.call(
            m.ProfileRequest(node_id=node_id, steps=steps)
        )

    def get_running_nodes(self) -> list[m.NodeMeta]:
        return self._client.call(m.RunningNodesRequest()).nodes

    # --------------------------------------------------------- data sharding

    def report_dataset_params(self, params: m.DatasetShardParams) -> None:
        self._client.call(params)

    def get_task(self, dataset_name: str) -> m.ShardTask:
        return self._client.call(
            m.TaskRequest(node_id=self.node_id, dataset_name=dataset_name)
        )

    def report_task_result(self, task_id: int, dataset_name: str,
                           success: bool = True, error: str = "") -> None:
        self._client.call(
            m.TaskResult(
                task_id=task_id, dataset_name=dataset_name,
                node_id=self.node_id, success=success, error=error,
            )
        )

    def recover_shards(self, node_id: int | None = None) -> None:
        self._client.call(
            m.RecoverShardsRequest(
                node_id=self.node_id if node_id is None else node_id
            )
        )

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        return self._client.call(
            m.ShardCheckpointRequest(dataset_name=dataset_name)
        ).content

    def restore_shard_checkpoint(self, dataset_name: str, content: str
                                 ) -> None:
        self._client.call(
            m.ShardCheckpoint(dataset_name=dataset_name, content=content)
        )

    # -------------------------------------------------------- network check

    def report_network_check(self, probe_round: int, succeeded: bool,
                             elapsed_time: float,
                             local_time: float = 0.0) -> None:
        self._client.call(
            m.NetworkCheckResult(
                node_id=self.node_id, round=probe_round, succeeded=succeeded,
                elapsed_time=elapsed_time, local_time=local_time,
            )
        )

    def get_network_check_group(self, probe_round: int
                                ) -> m.NetworkCheckGroupResponse:
        return self._client.call(
            m.NetworkCheckGroupRequest(
                node_id=self.node_id, probe_round=probe_round
            )
        )

    def get_network_check_status(self) -> m.NetworkCheckStatusResponse:
        return self._client.call(
            m.NetworkCheckStatusRequest(node_id=self.node_id)
        )

    # -------------------------------------------------------------- config

    def get_paral_config(self) -> m.ParalConfig:
        return self._client.call(m.ParalConfigRequest(node_id=self.node_id))

    def report_paral_config(self, config: m.ParalConfig) -> None:
        self._client.call(config)

    def report_job_exit(self, success: bool, reason: str = "") -> None:
        self._client.call(
            m.JobExitRequest(node_id=self.node_id, success=success,
                             reason=reason)
        )

    # ------------------------------------- rack sub-master tier (§28)

    def forward(self, msg):
        """Pass a message built elsewhere through to this client's
        target unchanged — the rack sub-master's relay for agent
        messages it does not aggregate (failure reports, node events,
        anything outside its local scope)."""
        return self._client.call(msg)

    def register_submaster(self, rack_id: str, addr: str = ""
                           ) -> m.SubMasterRegisterResponse:
        """Announce a rack sub-master to the root; the minted epoch in
        the response is what the sub-master stamps on its agent-facing
        replies (the rack tier's §26 fence)."""
        return self._client.call(
            m.SubMasterRegisterRequest(rack_id=rack_id, addr=addr)
        )

    def rack_join(self, rack_id: str, joins: list,
                  rdzv_name: str = "training") -> m.RackJoinResponse:
        """Push one rack's buffered rendezvous joins upstream as a
        single batch (each entry: {node_id, addr, local_devices,
        topology_key})."""
        return self._client.call(
            m.RackJoinRequest(rack_id=rack_id, rdzv_name=rdzv_name,
                              joins=list(joins))
        )

    def rack_world(self, rack_id: str, acked_round: int = 0,
                   rdzv_name: str = "training",
                   cursor: int = 0) -> m.RackWorldResponse:
        """Pull the comm-world versioned against the last acked round;
        the root answers with a compact member diff when it still holds
        that round's world. Payloads are chunk-bounded: a nonzero
        ``next_cursor`` on the response resumes the transfer here."""
        return self._client.call(
            m.RackWorldRequest(rack_id=rack_id, rdzv_name=rdzv_name,
                               acked_round=acked_round, cursor=cursor)
        )

    def report_rack_merged(self, rack_id: str, heartbeats: list,
                           snapshots: list, acks: list,
                           epoch: int = 0) -> m.RackMergedResponse:
        """One merged upstream push per sub-master flush tick: the
        rack's aggregated heartbeats, metrics-snapshot deltas and
        persist-acks (original rids preserved for the root's dedup).
        ``epoch`` stamps the sender's rack incarnation so the root can
        fence a superseded sub-master's resumed pushes (§30); 0 is the
        legacy unstamped form, accepted unfenced."""
        return self._client.call(
            m.RackMergedReport(rack_id=rack_id,
                               heartbeats=list(heartbeats),
                               snapshots=list(snapshots),
                               acks=list(acks), epoch=int(epoch))
        )
