"""Node-health probe payload: matmul + collective over the probe group.

Reference analog: dlrover/trainer/torch/node_check/nvidia_gpu.py (:26) and
utils.py (bm_all_gather, matmul, mock_error via MOCK_ERR_RANK). On TPU the
probe is a jitted bf16 matmul (MXU exercise) plus, when a multi-node probe
group exists, a psum over the group (ICI/DCN exercise). Runs in a
subprocess so a wedged chip cannot hang the agent.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

from dlrover_tpu.common import envspec
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

# import-time read by design (envspec: restart_required) — the probe
# budget must be identical across every probe child of one agent
PROBE_TIMEOUT_S = envspec.get_float(EnvKey.PROBE_TIMEOUT)
GLOBAL_RANK_ENV = EnvKey.GLOBAL_RANK


def _probe_payload() -> float:
    """The in-process probe; returns elapsed seconds. Exits nonzero on fault."""
    mock_rank = os.environ.get(EnvKey.MOCK_ERR_RANK)
    # fault injection keys on the node's GLOBAL rendezvous rank — probe
    # groups renumber ranks within each pair, and the mock must follow the
    # node, not its position in a pair
    node_rank = int(os.environ.get(EnvKey.NODE_RANK, "0"))
    global_rank = int(os.environ.get(GLOBAL_RANK_ENV, str(node_rank)))
    if mock_rank is not None and int(mock_rank) == global_rank:
        raise RuntimeError("mock error injected by MOCK_ERR_RANK")

    import jax
    import jax.numpy as jnp

    platform = os.environ.get(EnvKey.PLATFORM)
    if platform:  # hermetic tests force the CPU backend
        try:
            jax.config.update("jax_platforms", platform)
        except RuntimeError:
            pass

    num_nodes = int(os.environ.get(EnvKey.NODE_NUM, "1"))
    coordinator = os.environ.get(EnvKey.COORDINATOR, "")
    if num_nodes > 1 and coordinator:
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=num_nodes,
            process_id=node_rank,
        )

    start = time.monotonic()
    size = 2048
    x = jnp.ones((size, size), dtype=jnp.bfloat16)

    @jax.jit
    def matmul_chain(a):
        for _ in range(8):
            a = a @ a / size
        return a

    y = matmul_chain(x)
    y.block_until_ready()
    # compute-only time: this is the straggler signal — the collective
    # below gates on the slowest group member, so its wall clock cannot
    # distinguish a slow chip from a slow partner
    local_elapsed = time.monotonic() - start

    if num_nodes > 1:
        # 16M-element allreduce across every device in the probe group
        # (reference probe size: bm_all_gather's 16M elements).
        per_dev = 16 * 1024 * 1024 // max(1, jax.device_count())
        data = jnp.ones((jax.local_device_count(), per_dev), jnp.float32)
        reduced = jax.pmap(lambda v: jax.lax.psum(v, "probe"),
                           axis_name="probe")(data)
        reduced.block_until_ready()
    return time.monotonic() - start, local_elapsed


def run_node_check(node_rank: int, num_nodes: int, coordinator: str,
                   global_rank: int | None = None
                   ) -> tuple[float, bool, float]:
    """Run the probe in a subprocess.

    Returns (elapsed_s, succeeded, local_elapsed_s) — the last being the
    compute-only portion used for straggler detection.
    """
    env = dict(os.environ)
    env[EnvKey.NODE_RANK] = str(node_rank)
    env[EnvKey.NODE_NUM] = str(num_nodes)
    env[EnvKey.COORDINATOR] = coordinator
    env[GLOBAL_RANK_ENV] = str(
        global_rank if global_rank is not None else node_rank
    )
    start = time.monotonic()
    try:
        out = subprocess.run(
            [sys.executable, "-m", "dlrover_tpu.agent.node_check"],
            env=env, timeout=PROBE_TIMEOUT_S, capture_output=True, text=True,
        )
    except subprocess.TimeoutExpired:
        logger.error("node check timed out after %ss", PROBE_TIMEOUT_S)
        return PROBE_TIMEOUT_S, False, 0.0
    if out.returncode != 0:
        logger.error("node check failed: %s", out.stderr[-2000:])
        return time.monotonic() - start, False, 0.0
    try:
        result = json.loads(out.stdout.strip().splitlines()[-1])
        elapsed = result["elapsed"]
        local = result.get("local", 0.0)
    except (json.JSONDecodeError, IndexError, KeyError):
        elapsed, local = time.monotonic() - start, 0.0
    return elapsed, True, local


def main() -> int:
    elapsed, local = _probe_payload()
    print(json.dumps({"elapsed": elapsed, "local": local}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
