"""Jitted train-step factory: strategy in, compiled SPMD step out.

Reference analog: the tail of auto_accelerate (atorch/atorch/auto/
accelerate.py:406 model_transform + returned optim/dataloader wiring). In
torch the strategy mutates the model (FSDP wrap, TP module swap, AMP hooks);
here it parameterizes one ``jax.jit``: parameter/optimizer-state shardings,
bf16 compute casts, remat policy, and gradient accumulation all become
compile-time properties of a single XLA program.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.mesh import batch_axes
from dlrover_tpu.parallel.partition import constrain as _constrain
from dlrover_tpu.parallel.strategy import Strategy

logger = get_logger(__name__)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any


def _path_names(path) -> tuple[str, ...]:
    names = []
    for p in path:
        if hasattr(p, "key"):
            names.append(str(p.key))
        elif hasattr(p, "name"):
            names.append(str(p.name))
        elif hasattr(p, "idx"):
            names.append(str(p.idx))
        else:
            names.append(str(p))
    return tuple(names)


def derive_opt_specs(optimizer, params: Any, param_specs: Any) -> Any:
    """PartitionSpecs for the optimizer state (ZeRO: follow the params).

    Optax states embed parameter-structured subtrees (Adam's mu/nu); each
    opt-state leaf whose path ends with a parameter's path inherits that
    parameter's spec, everything else (counts, scalars) replicates. This is
    the reference's ZeRO/FSDP optimizer-state sharding
    (atorch/atorch/auto/opt_lib/zero_optimization.py:115) as a spec-mapping.
    """
    param_leaves = {
        _path_names(path): spec
        for path, spec in jax.tree_util.tree_flatten_with_path(
            param_specs,
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        )[0]
    }
    opt_shape = jax.eval_shape(optimizer.init, params)

    def spec_of(path, leaf) -> PartitionSpec:
        names = _path_names(path)
        for p_path, spec in param_leaves.items():
            if len(names) >= len(p_path) and names[-len(p_path):] == p_path:
                if leaf.shape:  # scalars always replicate
                    return spec
        return PartitionSpec()

    flat, treedef = jax.tree_util.tree_flatten_with_path(opt_shape)
    return jax.tree_util.tree_unflatten(
        treedef, [spec_of(p, l) for p, l in flat]
    )


def zero_shard_specs(specs: Any, example: Any, mesh: Mesh) -> Any:
    """ZeRO-style cross-replica sharding (Xu et al., 2004.13336): give
    every REPLICATED leaf's first divisible dim to the mesh's data axes,
    leaving already-sharded leaves untouched. Used for optimizer-state
    sharding by the ``zero1``/``zero2`` strategies here and by the MPMD
    per-stage weight-update programs (``parallel/mpmd.py``) — the math
    is identical to replicated (a layout choice, not an algorithm
    change); XLA derives the update all-gather from the out shardings.
    ``specs``/``example`` are same-structure trees of PartitionSpec and
    array(-shape) leaves."""
    z_axes = batch_axes(mesh)
    z_n = 1
    for a in z_axes:
        z_n *= mesh.shape[a]
    z_axis = z_axes if len(z_axes) > 1 else (
        z_axes[0] if z_axes else None)

    def _spec(spec, leaf):
        if spec != PartitionSpec() or leaf.ndim == 0 or z_axis is None:
            return spec
        for d, size in enumerate(leaf.shape):
            if size % z_n == 0 and size >= z_n:
                return PartitionSpec(*([None] * d), z_axis)
        return spec

    return jax.tree.map(
        _spec, specs, example,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


@dataclasses.dataclass
class CompiledTrain:
    """Everything a training loop needs, pre-sharded and jitted."""

    mesh: Mesh
    strategy: Strategy
    state_shardings: TrainState
    batch_sharding: Any
    init: Callable[..., TrainState]          # (rng, *init_args) -> state
    step: Callable[[TrainState, Any], tuple[TrainState, dict]]
    constrain: Callable[[jax.Array, tuple], jax.Array]
    # set by the elastic compile-cache path (parallel/compile_cache.py)
    # when `step` was swapped for a pre-compiled AOT executable: True =
    # served from cache (warm), False = compiled cold this incarnation,
    # None = plain jit path (compiles lazily at the first dispatch)
    cache_hit: bool | None = None
    # compiled-program FLOPs per step call (XLA cost analysis), fed to
    # the live MFU gauge (telemetry/efficiency.py). Set by the AOT path
    # (AotStep.flops — cached in the compile-cache envelope so warm
    # loads never re-lower); 0.0 = unknown (plain jit path on a device
    # with no known peak never needs it)
    flops_per_step: float = 0.0


def compile_train(
    *,
    strategy: Strategy,
    mesh: Mesh,
    loss_fn: Callable[[Any, Any], jax.Array],
    init_params_fn: Callable[..., Any],
    logical_params: Any,
    optimizer: optax.GradientTransformation,
    batch_spec: PartitionSpec | None = None,
    init_args: tuple = (),
) -> CompiledTrain:
    """Build the sharded init and train-step functions.

    ``loss_fn(params, micro_batch) -> scalar``; gradient accumulation over a
    leading accum dim of the batch is handled here (reference analog:
    ElasticTrainer's fixed-global-batch accumulation,
    dlrover/trainer/torch/elastic/trainer.py:181 — but resolved statically
    per compile instead of per optimizer call).
    """
    rules = strategy.rule_table()
    pin = partial(_constrain, rules=rules, mesh=mesh)

    param_specs = strategy.specs(logical_params, mesh)
    param_shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), param_specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )
    if batch_spec is None:
        # batch leaves are [accum, per_step_batch, ...]: shard the batch
        # dim (1) over the data axes, never the accumulation dim (0)
        axes = batch_axes(mesh)
        batch_spec = PartitionSpec(
            None,
            axes if len(axes) > 1 else (axes[0] if axes else None),
        )
    batch_sharding = NamedSharding(mesh, batch_spec)

    def _init(rng, *args) -> TrainState:
        params = init_params_fn(rng, *args)
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=optimizer.init(params),
        )

    # shardings for the full state
    example = jax.eval_shape(_init, jax.random.PRNGKey(0), *init_args)
    opt_specs = derive_opt_specs(optimizer, example.params, param_specs)
    extra = getattr(strategy, "extra", {}) or {}
    if extra.get("zero1") or extra.get("zero2"):
        # ZeRO-1: optimizer state shards over the data axes even though
        # params stay replicated — each leaf's first divisible dim gets
        # the axis; the update all-gather comes from out_shardings. The
        # math is identical to dp (layout, not algorithm).
        opt_specs = zero_shard_specs(opt_specs, example.opt_state, mesh)
    state_shardings = TrainState(
        step=NamedSharding(mesh, PartitionSpec()),
        params=param_shardings,
        opt_state=jax.tree.map(
            lambda s: NamedSharding(mesh, s), opt_specs,
            is_leaf=lambda x: isinstance(x, PartitionSpec),
        ),
    )

    init = jax.jit(_init, out_shardings=state_shardings)

    policy = strategy.remat_policy()
    grad_loss = loss_fn
    if policy is not None:
        grad_loss = jax.checkpoint(loss_fn, policy=policy)
    value_and_grad = jax.value_and_grad(grad_loss)

    def _loss_and_grads(params: Any, batch: Any) -> tuple[jax.Array, Any]:
        # batch leaves: [accum, per_step_batch, ...]
        accum = jax.tree_util.tree_leaves(batch)[0].shape[0]

        if accum == 1:
            return value_and_grad(
                params, jax.tree.map(lambda x: x[0], batch)
            )

        def micro(carry, mb):
            loss_acc, grads_acc = carry
            loss, grads = value_and_grad(params, mb)
            return (
                loss_acc + loss,
                jax.tree.map(jnp.add, grads_acc, grads),
            ), None

        zero = (
            jnp.zeros((), jnp.float32),
            jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            ),
        )
        (loss, grads), _ = jax.lax.scan(micro, zero, batch)
        return loss / accum, jax.tree.map(lambda g: g / accum, grads)

    compute = _loss_and_grads
    if extra.get("grad_compression"):
        # int8-quantized gradient reduce across the data axes (reference:
        # ATorch's quant-reduce comm compression). The grad psum XLA would
        # insert implicitly is replaced by an explicit shard_map region:
        # local grads -> quantized all-gather -> local dequant mean.
        # Scope matches the reference's DDP compression: params must be
        # replicated (the data axes are the only reduction).
        from dlrover_tpu.ops.collectives import (
            quantized_tree_mean,
            shard_map_nocheck,
        )

        sharded = [
            s for s in jax.tree_util.tree_leaves(
                param_specs,
                is_leaf=lambda x: isinstance(x, PartitionSpec),
            ) if s != PartitionSpec()
        ]
        if sharded:
            raise ValueError(
                "grad_compression requires replicated parameters (pure "
                f"data parallelism); found sharded specs {sharded[:3]}"
            )
        axes = batch_axes(mesh)

        axis_sizes = dict(mesh.shape)

        def _local(params, batch):
            loss, grads = _loss_and_grads(params, batch)
            grads = quantized_tree_mean(grads, axes, axis_sizes)
            return jax.lax.pmean(loss, axes), grads

        compute = shard_map_nocheck(
            _local,
            mesh=mesh,
            in_specs=(PartitionSpec(), batch_spec),
            out_specs=(PartitionSpec(), PartitionSpec()),
        )

    # ZeRO-2: constrain gradients to the moment shards' layout so the
    # cross-data-axis gradient sum lowers to a reduce_scatter and each
    # device updates only its shard (the all-gather moves to the
    # parameter update, where ZeRO-1 already pays it)
    grad_constraint = None
    if extra.get("zero2"):
        # the param-shaped moment layout: run the PARAM specs through
        # the same first-divisible-dim rule the moments used, so a
        # zero2 strategy with sharded params keeps grads and moments on
        # one layout instead of resharding between them
        mu_specs = zero_shard_specs(param_specs, example.params, mesh)

        def grad_constraint(grads):
            return jax.tree.map(
                lambda g, s: jax.lax.with_sharding_constraint(
                    g, NamedSharding(mesh, s)
                ),
                grads, mu_specs,
            )

    def _step(state: TrainState, batch: Any) -> tuple[TrainState, dict]:
        loss, grads = compute(state.params, batch)
        if grad_constraint is not None:
            grads = grad_constraint(grads)
        updates, opt_state = optimizer.update(
            grads, state.opt_state, state.params
        )
        params = optax.apply_updates(state.params, updates)
        new_state = TrainState(
            step=state.step + 1, params=params, opt_state=opt_state
        )
        metrics = {
            "loss": loss.astype(jnp.float32),
            "grad_norm": optax.global_norm(grads).astype(jnp.float32),
        }
        return new_state, metrics

    replicated = NamedSharding(mesh, PartitionSpec())
    step = jax.jit(
        _step,
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=(state_shardings,
                       {"loss": replicated, "grad_norm": replicated}),
        donate_argnums=(0,),
    )

    return CompiledTrain(
        mesh=mesh,
        strategy=strategy,
        state_shardings=state_shardings,
        batch_sharding=batch_sharding,
        init=init,
        step=step,
        constrain=pin,
    )
