"""Shared-memory batch exchange: data-worker processes feed the trainer.

Reference analog: ATorch's ShmDataContext / ShmDataloader
(atorch/atorch/data/shm_context.py:139 — CPU "coworker" pods prepare
samples and hand them to the GPU trainer over shared memory). TPU-host
shape: data preparation (tokenization, decoding, augmentation) runs in
separate PROCESSES on the host VM — the trainer process must spend its
Python time driving the chips, not collating — and ready batches cross
process boundaries as raw bytes in a slotted shared-memory ring, no
pickling on the hot path.

Layout: ``capacity`` fixed-size slots in one SharedMemoryArena. Two
SharedQueues carry slot indices: ``free`` (consumer -> producers) and
``ready`` (producers -> consumer). A slot holds a 4-byte header length,
a JSON header (array names/shapes/dtypes/offsets), then the raw bytes.
"""

from __future__ import annotations

import json
import multiprocessing
import queue as queue_mod
import struct
import time
from typing import Any, Callable, Iterator

import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.multi_process import (
    SharedMemoryArena,
    SharedQueue,
)
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

# The efficiency observatory's data_wait phase (telemetry/efficiency.py)
# says THAT the trainer starved; these two say WHY: a producer blocked on
# a free slot means the consumer is the bottleneck (ring full), a low
# ready depth at get() time means the producers are (ring empty).
_slot_wait = registry().histogram(
    "dlrover_tpu_shm_slot_wait_seconds",
    "shm data producers' wait for a free ring slot (consumer-bound "
    "when high)",
)
_ready_depth = registry().gauge(
    "dlrover_tpu_shm_ready_batches",
    "ready batches in the shm ring observed at each consumer get() "
    "(producer-bound when ~0 while the trainer waits on data)",
)

_LEN = struct.Struct("<I")


def _write_batch(buf: memoryview, offset: int, slot_size: int,
                 batch: dict[str, np.ndarray]) -> None:
    metas = {}
    data_off = 0
    arrays = {}
    for name, arr in batch.items():
        arr = np.ascontiguousarray(arr)
        metas[name] = {
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "offset": data_off,
        }
        arrays[name] = arr
        data_off += arr.nbytes
    header = json.dumps(metas).encode()
    total = _LEN.size + len(header) + data_off
    if total > slot_size:
        raise ValueError(
            f"batch of {total} bytes exceeds slot size {slot_size}"
        )
    buf[offset:offset + _LEN.size] = _LEN.pack(len(header))
    start = offset + _LEN.size
    buf[start:start + len(header)] = header
    base = start + len(header)
    for name, arr in arrays.items():
        o = base + metas[name]["offset"]
        buf[o:o + arr.nbytes] = arr.tobytes()


def _read_batch(buf: memoryview, offset: int) -> dict[str, np.ndarray]:
    (hlen,) = _LEN.unpack(bytes(buf[offset:offset + _LEN.size]))
    start = offset + _LEN.size
    metas = json.loads(bytes(buf[start:start + hlen]))
    base = start + hlen
    out = {}
    for name, info in metas.items():
        dtype = np.dtype(info["dtype"])
        count = int(np.prod(info["shape"]) or 1)
        o = base + info["offset"]
        out[name] = np.frombuffer(
            buf, dtype=dtype, count=count, offset=o
        ).reshape(info["shape"]).copy()  # own the data before slot reuse
    return out


class ShmBatchQueue:
    """The slotted ring. One consumer (owner) + N producer processes."""

    def __init__(self, name: str, slot_size: int = 16 << 20,
                 capacity: int = 8, create: bool = False):
        self.name = name
        self.slot_size = slot_size
        self.capacity = capacity
        self._arena = SharedMemoryArena.open_or_create(
            f"shmdl_{name}", slot_size * capacity
        ) if create else SharedMemoryArena.open(f"shmdl_{name}")
        self._free = SharedQueue(f"shmdl_free_{name}", create=create)
        self._ready = SharedQueue(f"shmdl_ready_{name}", create=create)
        if create:
            for i in range(capacity):
                self._free.put({"slot": i})

    # ------------------------------------------------------------- producer

    def put(self, batch: dict[str, np.ndarray],
            timeout: float | None = None) -> None:
        t0 = time.monotonic()
        item = self._free.get(timeout=timeout)
        _slot_wait.observe(time.monotonic() - t0)
        slot = int(item["slot"])
        _write_batch(self._arena.buf, slot * self.slot_size,
                     self.slot_size, batch)
        self._ready.put({"slot": slot})

    def put_end(self) -> None:
        self._ready.put({"end": True})

    # ------------------------------------------------------------- consumer

    def get(self, timeout: float | None = None
            ) -> dict[str, np.ndarray] | None:
        """Next batch, or None at end-of-stream."""
        try:
            _ready_depth.set(self._ready.qsize())
        except Exception:  # noqa: BLE001 - depth is advisory telemetry
            pass
        item = self._ready.get(timeout=timeout)
        if item.get("end"):
            return None
        slot = int(item["slot"])
        batch = _read_batch(self._arena.buf, slot * self.slot_size)
        self._free.put({"slot": slot})
        return batch

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        while True:
            batch = self.get()
            if batch is None:
                return
            yield batch

    def close(self, unlink: bool = False) -> None:
        if unlink:
            self._arena.unlink()
        self._arena.close()
        self._free.close()
        self._ready.close()


def _worker_main(queue_name: str, slot_size: int,
                 produce: Callable[[int], Iterator[dict]],
                 worker_id: int) -> None:
    q = ShmBatchQueue(queue_name, slot_size=slot_size, create=False)
    try:
        for batch in produce(worker_id):
            q.put(batch)
        q.put_end()
    except Exception:  # noqa: BLE001 - end the stream, don't hang the consumer
        logger.exception("shm data worker %d failed", worker_id)
        q.put_end()
    finally:
        q.close()


class ShmDataWorkers:
    """Spawn N producer processes feeding one ShmBatchQueue.

    ``produce(worker_id) -> iterator of batch dicts``; must be picklable
    (top-level function / functools.partial). The consumer iterates the
    returned queue; the stream ends after every worker sent its end
    marker.
    """

    def __init__(self, name: str, produce: Callable[[int], Iterator[dict]],
                 num_workers: int = 1, slot_size: int = 16 << 20,
                 capacity: int = 8):
        self.queue = ShmBatchQueue(
            name, slot_size=slot_size, capacity=capacity, create=True
        )
        ctx = multiprocessing.get_context("spawn")
        self._procs = [
            ctx.Process(
                target=_worker_main,
                args=(name, slot_size, produce, i),
                daemon=True,
            )
            for i in range(num_workers)
        ]
        for p in self._procs:
            p.start()
        self._ends_pending = num_workers

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        while self._ends_pending > 0:
            try:
                batch = self.queue.get(timeout=120)
            except queue_mod.Empty:
                logger.error("shm data workers stalled; ending stream")
                return
            if batch is None:
                self._ends_pending -= 1
                continue
            yield batch

    def close(self) -> None:
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            p.join(timeout=10)
        self.queue.close(unlink=True)
