"""Remote data workers: CPU hosts stream ready batches to trainers.

Reference analog: ATorch's coworker data service — dedicated CPU pods
prepare samples and GPU trainers consume them over gRPC
(atorch/atorch/service/coworker_data_service.py, data/shm_context.py
``CoworkerDataset``). The same-host half of that design is
``trainer/shm_dataloader.py`` (process-local shm ring); this module is
the cross-host half: a TPU-VM trainer pulls ready-made batches from
data-worker processes running on separate CPU hosts, so tokenization /
decoding / augmentation never competes with the Python thread driving
the chips.

Design (TPU-first, matching the repo's no-pickle transport rules):
- Pull protocol over one TCP connection per client: the trainer sends a
  tiny JSON request frame, the worker answers with one batch frame —
  a 1-byte tag (``B`` batch / ``E`` end), a JSON meta header (array
  names/shapes/dtypes/offsets — the shm ring's slot layout, promoted to
  a wire format) and the arrays' raw bytes. No pickling anywhere.
- Each batch goes to exactly ONE client (the dynamic-sharding
  semantic): a shared iterator behind a lock, so N trainer hosts
  draining one worker see a partition, not copies.
- ``RemoteBatchLoader`` fans in from many workers: one puller thread
  per address feeding a bounded local queue (backpressure = queue depth
  + the pull protocol itself).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import queue as queue_mod
from typing import Callable, Iterator

import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.rpc import recv_frame, send_frame

logger = get_logger(__name__)

_TAG_BATCH = b"B"
_TAG_END = b"E"
# protocol error (e.g. version-skewed request kind): distinct from the
# end-of-data marker so a confused client raises instead of reading a
# clean short epoch
_TAG_ERR = b"X"
_LEN = struct.Struct("<I")


def encode_batch(batch: dict[str, np.ndarray]) -> bytes:
    """Batch -> tag + length-prefixed JSON meta + concatenated raw bytes."""
    metas = {}
    chunks = []
    off = 0
    for name, arr in batch.items():
        arr = np.ascontiguousarray(arr)
        metas[name] = {
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
            "offset": off,
        }
        chunks.append(arr.tobytes())
        off += arr.nbytes
    header = json.dumps(metas).encode()
    return b"".join([_TAG_BATCH, _LEN.pack(len(header)), header] + chunks)


def decode_batch(payload: bytes) -> dict[str, np.ndarray] | None:
    """Inverse of :func:`encode_batch`; ``None`` for the end marker.
    Raises ``ValueError`` on an error frame or an unknown tag."""
    if payload[:1] == _TAG_END:
        return None
    if payload[:1] == _TAG_ERR:
        raise ValueError(
            f"data worker protocol error: {payload[1:].decode(errors='replace')}"
        )
    if payload[:1] != _TAG_BATCH:
        raise ValueError(f"bad batch frame tag {payload[:1]!r}")
    (hlen,) = _LEN.unpack(payload[1:1 + _LEN.size])
    start = 1 + _LEN.size
    metas = json.loads(payload[start:start + hlen])
    base = start + hlen
    out = {}
    for name, info in metas.items():
        dtype = np.dtype(info["dtype"])
        count = int(np.prod(info["shape"]))
        # copy: frombuffer views are read-only and pin the whole payload
        # alive; the shm loader hands back owned arrays, so the remote
        # path must too or portable preprocessing breaks
        out[name] = np.frombuffer(
            payload, dtype=dtype, count=count, offset=base + info["offset"]
        ).reshape(info["shape"]).copy()
    return out


class DataServiceServer:
    """One data worker: serves a batch iterator to pulling trainers.

    ``produce`` is called once; its iterator is shared across all client
    connections behind a lock — each batch is delivered exactly once.
    """

    def __init__(self, produce: Callable[[], Iterator[dict]],
                 host: str = "0.0.0.0", port: int = 0):
        self._produce = produce
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.5)
        self._iter: Iterator[dict] | None = None
        self._iter_lock = threading.Lock()
        self._stop = threading.Event()
        self._failed = False
        self._threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._accept_loop, daemon=True
        )

    @property
    def port(self) -> int:
        return self._sock.getsockname()[1]

    def start(self) -> "DataServiceServer":
        self._iter = self._produce()
        self._accept_thread.start()
        logger.info("data service serving on port %d", self.port)
        return self

    def _next_batch(self) -> dict | None:
        with self._iter_lock:
            assert self._iter is not None
            try:
                return next(self._iter, None)
            except Exception:
                # flag the failure while STILL holding the lock: a
                # concurrent connection must never observe the dead
                # generator's StopIteration before seeing _failed
                self._failed = True
                raise

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            )
            t.start()
            # prune finished handlers: reconnect-per-epoch clients would
            # otherwise grow this list for the life of the worker
            self._threads = [x for x in self._threads if x.is_alive()]
            self._threads.append(t)

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            while not self._stop.is_set():
                try:
                    req = json.loads(recv_frame(conn))
                except (ConnectionError, OSError, ValueError):
                    return
                if req.get("kind") != "next":
                    send_frame(
                        conn,
                        _TAG_ERR + f"unknown request kind "
                                   f"{req.get('kind')!r}".encode(),
                    )
                    return
                try:
                    batch = self._next_batch()
                except Exception:
                    # a broken produce() iterator must not masquerade as
                    # clean end-of-data: log loudly and drop the
                    # connection mid-protocol so clients see a worker
                    # FAILURE (logged + sentinel), not a short epoch
                    logger.exception("produce() raised; failing worker")
                    self._failed = True
                    self._stop.set()
                    return
                if self._failed:
                    # the generator died on another connection: this one
                    # would see StopIteration->None and read as a clean
                    # end — drop it mid-protocol instead
                    return
                try:
                    if batch is None:
                        send_frame(conn, _TAG_END)
                        return
                    send_frame(conn, encode_batch(batch))
                except (ConnectionError, OSError):
                    logger.warning(
                        "client dropped mid-send; batch lost (at-most-once "
                        "on the wire — wrap produce() with the sharding "
                        "client for at-least-once recovery)"
                    )
                    return

    def stop(self) -> None:
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


class RemoteBatchLoader:
    """Trainer side: fan-in iterator over one or more data workers.

    A worker FAILURE (unreachable, dropped connection, protocol error) is
    not a clean end-of-stream: the affected address is recorded in
    ``failed_workers`` for the current iteration, and with
    ``strict=True`` the iterator raises at exhaustion instead of handing
    the training loop a silently short epoch.
    """

    def __init__(self, addrs: list[str], prefetch: int = 4,
                 connect_timeout: float = 10.0, strict: bool = False):
        self._addrs = list(addrs)
        self._prefetch = prefetch
        self._timeout = connect_timeout
        self._strict = strict
        # addresses whose puller ended on a failure (not clean EOF)
        # during the CURRENT iteration; inspect after exhaustion to
        # distinguish a truncated epoch from a drained one
        self.failed_workers: list[str] = []
        self._stop = threading.Event()
        # each __iter__ call is a generation with its own queue; bumping
        # the generation retires the previous iteration's pullers so an
        # abandoned epoch can't leak threads or bleed batches into the
        # next one
        self._gen = 0

    def _retired(self, gen: int) -> bool:
        return self._stop.is_set() or gen != self._gen

    def _put(self, q: queue_mod.Queue, gen: int, item) -> bool:
        """Generation-aware bounded put — a closed loader or a newer
        iteration must not leave pullers parked on a full queue."""
        while not self._retired(gen):
            try:
                q.put(item, timeout=0.2)
                return True
            except queue_mod.Full:
                continue
        return False

    def _pull(self, addr: str, q: queue_mod.Queue, gen: int,
              failed: list[str]) -> None:
        # the finally-sentinel is load-bearing: __iter__ counts one
        # sentinel per puller, so EVERY exit path must emit it or the
        # training loop waits forever
        try:
            try:
                host, port = addr.rsplit(":", 1)
                conn = socket.create_connection(
                    (host or "127.0.0.1", int(port)),
                    timeout=self._timeout,
                )
                conn.settimeout(None)
            except (OSError, ValueError) as e:
                logger.warning("data worker %s unreachable: %s", addr, e)
                failed.append(addr)
                return
            with conn:
                while not self._retired(gen):
                    try:
                        send_frame(
                            conn, json.dumps({"kind": "next"}).encode()
                        )
                        batch = decode_batch(recv_frame(conn))
                    except (ConnectionError, OSError, ValueError) as e:
                        # ValueError: version-skewed peer sent a frame
                        # that isn't the batch protocol, or the worker
                        # answered with an explicit error frame
                        logger.warning(
                            "data worker %s dropped: %s", addr, e
                        )
                        failed.append(addr)
                        break
                    if batch is None or not self._put(q, gen, batch):
                        break
        finally:
            self._put(q, gen, None)

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        """Each iteration reconnects to every worker and streams until
        all are drained. Workers hand each batch to exactly one
        connection, so a second epoch sees whatever the produce()
        iterators still hold (restart the services for a fresh epoch);
        starting a new iteration retires any still-running previous one.
        """
        if self._stop.is_set():
            raise RuntimeError("RemoteBatchLoader is closed")
        self._gen += 1
        gen = self._gen
        failed: list[str] = []
        self.failed_workers = failed
        q: queue_mod.Queue = queue_mod.Queue(maxsize=self._prefetch)
        threads = [
            threading.Thread(
                target=self._pull, args=(a, q, gen, failed), daemon=True,
                name=f"data-pull-g{gen}-{a}",
            )
            for a in self._addrs
        ]
        for t in threads:
            t.start()
        done = 0
        while done < len(threads):
            try:
                item = q.get(timeout=0.2)
            except queue_mod.Empty:
                if self._retired(gen):
                    return
                continue
            if item is None:
                done += 1
                continue
            yield item
        if failed and self._strict:
            raise RuntimeError(
                f"epoch truncated: data workers failed: {failed}"
            )

    def close(self) -> None:
        self._stop.set()
