"""ElasticTrainer: fixed global batch under changing world size.

Reference analog: dlrover/trainer/torch/elastic/trainer.py:181
(ElasticTrainer with GradientState and _ElasticOptimizer: gradient
accumulation steps are recomputed from the live world size so the effective
global batch — and therefore the loss trajectory — is invariant to
elasticity). TPU-native difference: a membership change restarts the process
and recompiles the step anyway (XLA bakes the mesh into the program), so the
accumulation factor is resolved once per incarnation, not per optimizer call.
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Iterator

import jax
import numpy as np

from dlrover_tpu.common import envspec
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.mesh import data_parallel_size
from dlrover_tpu.telemetry.efficiency import EfficiencyMonitor
from dlrover_tpu.telemetry.journal import get_journal, spawn_ctx
from dlrover_tpu.telemetry.metrics import registry
from dlrover_tpu.trainer.train_step import CompiledTrain, TrainState

logger = get_logger(__name__)

_step_seconds = registry().histogram(
    "dlrover_tpu_train_step_seconds",
    "train_step wall time (dispatch-to-dispatch; first call of an "
    "incarnation carries the XLA compile)",
)
_steps_total = registry().counter(
    "dlrover_tpu_train_steps_total",
    "optimizer steps executed by this process",
)
_compile_seconds = registry().histogram(
    "dlrover_tpu_compile_seconds",
    "first-dispatch wall time per incarnation (trace + XLA compile, or "
    "the AOT executable's near-zero re-dispatch; device compute of the "
    "step itself is excluded)",
)


class BatchAssembler:
    """Shape sample streams into [accum, batch, ...] step batches."""

    def __init__(self, accum: int, batch_size: int):
        self.accum = accum
        self.batch_size = batch_size

    def batches(
        self, samples: Iterator[Any],
        collate: Callable[[list], dict[str, np.ndarray]],
    ) -> Iterator[dict[str, np.ndarray]]:
        need = self.accum * self.batch_size
        buf: list = []
        for s in samples:
            buf.append(s)
            if len(buf) == need:
                flat = collate(buf)
                yield {
                    k: v.reshape((self.accum, self.batch_size) + v.shape[1:])
                    for k, v in flat.items()
                }
                buf = []


class ElasticTrainer:
    """Drives a compiled train step at a fixed global batch.

    ``compiled`` is either a ``CompiledTrain`` (one SPMD program) or
    any duck-type of it — the MPMD pipeline runtime
    (``parallel.mpmd.MpmdTrain``) plugs in here unchanged: its ``mesh``
    is stage 0's submesh (whose data axis is the batch-sharding world),
    its ``step`` is the host-side 1F1B scheduler, and its per-stage
    metrics (``dlrover_tpu_pipeline_*``) ride the same snapshot pushes
    as everything else.
    """

    def __init__(
        self,
        compiled: "CompiledTrain | Any",
        global_batch_size: int,
        micro_batch_size: int,
        report_step_interval: int = 1,
        master_client=None,
        model_name: str = "",
    ):
        self.compiled = compiled
        dp = data_parallel_size(compiled.mesh)
        if global_batch_size % (micro_batch_size * dp):
            raise ValueError(
                f"global batch {global_batch_size} not divisible by "
                f"micro_batch {micro_batch_size} × dp {dp}"
            )
        self.accum = global_batch_size // (micro_batch_size * dp)
        self.global_batch_size = global_batch_size
        # per-step GLOBAL batch dim of the compiled step (sharded over dp)
        self.step_batch_size = micro_batch_size * dp
        # multi-process SPMD: each node assembles only the rows its own
        # devices consume; jax assembles the global array from per-process
        # shards (make_array_from_process_local_data). The master's data
        # sharding already hands each node distinct samples.
        self.num_processes = jax.process_count()
        if self.step_batch_size % self.num_processes:
            raise ValueError(
                f"per-step batch {self.step_batch_size} not divisible by "
                f"{self.num_processes} processes"
            )
        self.local_step_batch = self.step_batch_size // self.num_processes
        self.assembler = BatchAssembler(self.accum, self.local_step_batch)
        self._report_interval = report_step_interval
        self._host_step = 0  # avoids blocking on the device step counter
        # node-local progress heartbeat for the agent's hang detector
        # (agent/hang_detector.py); file writes, rate-limited, never on
        # the device-dispatch path
        from dlrover_tpu.agent.hang_detector import ProgressReporter

        self._progress = ProgressReporter()
        self._first_dispatch = True
        self._last_metrics_push = float("-inf")
        self._metrics_push_interval_s = 1.0
        self._client = master_client
        if self._client is None and os.environ.get(EnvKey.MASTER_ADDR):
            from dlrover_tpu.agent.master_client import MasterClient

            self._client = MasterClient.singleton()
        # efficiency observatory (telemetry/efficiency.py): live MFU +
        # step-phase attribution + on-demand profiler capture. The block
        # phase syncs on the step's replicated metrics each step, which
        # trades the one-step host/device overlap for clean host-vs-
        # device attribution; DLROVER_TPU_STEP_PHASES=0 keeps the
        # fire-and-forget dispatch (phases then report dispatch-time
        # only).
        self._phase_block = envspec.get_bool(EnvKey.STEP_PHASES)
        from dlrover_tpu.utils.profiler import device_peak_flops

        self.efficiency = EfficiencyMonitor(
            model=model_name,
            strategy=getattr(compiled.strategy, "name", "") or "",
            flops_per_step=getattr(compiled, "flops_per_step", 0.0),
            peak_flops=device_peak_flops(),
            num_devices=jax.device_count(),
            on_bundle=self._report_profile_bundle,
        )
        self._last_step_end = 0.0
        # autopilot retune hook (autopilot/apply.py, DESIGN.md §24):
        # called once per step with (step, state); returning
        # (new_compiled, new_state) swaps the running program in place
        # — the no-restart strategy retune path
        self.retune_hook = None
        logger.info(
            "elastic trainer: dp=%d accum=%d global_batch=%d (fixed)",
            dp, self.accum, global_batch_size,
        )

    def swap_compiled(self, compiled: "CompiledTrain | Any") -> None:
        """Install a retuned step program mid-run (same batch geometry
        — the applier's ``can_apply`` guards that). The next dispatch
        is treated as a first dispatch so its compile/load cost lands
        in the recompile cost class, and the MFU gauge re-bases on the
        new program's FLOPs; the rolling step window resets so the
        post-retune median (the value the autopilot history records
        against the new plan) never spans pre-retune steps."""
        self.compiled = compiled
        self._first_dispatch = True
        flops = getattr(compiled, "flops_per_step", 0.0) or 0.0
        if flops > 0:
            self.efficiency.set_flops(flops)
        self.efficiency.reset_window()
        logger.info(
            "swapped compiled step program (strategy %s)",
            getattr(getattr(compiled, "strategy", None), "name", "?"),
        )

    def _report_profile_bundle(self, path: str) -> None:
        """List an on-demand profiler capture in the master's bundle
        ledger, next to crash/hang bundles."""
        if self._client is None:
            return
        node = os.environ.get(EnvKey.NODE_ID, "?")
        self._client.report_debug_bundle(
            path, "profile", proc=f"node{node} trainer"
        )

    def train_step(self, state: TrainState, batch: dict
                   ) -> tuple[TrainState, dict]:
        step_start = time.monotonic()
        if self.num_processes > 1:
            sharding = self.compiled.batch_sharding
            batch = jax.tree.map(
                lambda x: jax.make_array_from_process_local_data(
                    sharding, np.ascontiguousarray(x),
                    (x.shape[0], x.shape[1] * self.num_processes)
                    + x.shape[2:],
                ),
                batch,
            )
        else:
            batch = jax.device_put(batch, self.compiled.batch_sharding)
        t_dispatch = time.monotonic()
        self.efficiency.observe_phase("h2d", t_dispatch - step_start)
        state, metrics = self.compiled.step(state, batch)
        t_block = time.monotonic()
        # up to dispatch-return: on a first call this carries the trace
        # + XLA compile (or the AOT executable's ~0 re-dispatch), never
        # the step's device compute — that lands in the block phase
        dispatch_wall = t_block - step_start
        self.efficiency.observe_phase("dispatch", t_block - t_dispatch)
        if self._phase_block:
            # block_until_ready on the replicated metrics scalars is the
            # host-vs-device separator: everything still in flight after
            # dispatch returns is device compute, attributed as "block"
            jax.block_until_ready(metrics)
            self.efficiency.observe_phase(
                "block", time.monotonic() - t_block
            )
        # host-side counter: reading state.step would block async dispatch
        self._host_step += 1
        step = self._host_step
        step_wall = time.monotonic() - step_start
        _step_seconds.observe(step_wall)
        _steps_total.inc()
        if self._first_dispatch:
            # the incarnation's first call traces + compiles (or loads
            # the persistent compile cache) before dispatching — the
            # recompile cost class the lost-time report attributes.
            # Timed to dispatch-return (pre-block), so the first step's
            # own device compute never inflates the recompile category;
            # the report's median netting stays as a clamp for journals
            # from builds where dispatch was synchronous.
            self._first_dispatch = False
            _compile_seconds.observe(dispatch_wall)
            # cache_hit distinguishes the warm path (AOT executable
            # served by the compile cache — this event times only the
            # load + one step) from a cold XLA compile; the lost-time
            # report splits the recompile category on it
            hit = getattr(self.compiled, "cache_hit", None)
            # spawn_ctx (§27): the incarnation's recompile attaches
            # under the recovery incident that respawned this trainer
            get_journal().emit(
                "compile", dur=dispatch_wall, step=step,
                cache_hit=bool(hit) if hit is not None else None,
                remote_parent=spawn_ctx(),
            )
            self._maybe_install_flops(state, batch)
        else:
            get_journal().emit("train_step", dur=step_wall, step=step)
        # step cadence (previous end -> this end) feeds the rolling MFU:
        # it includes data_wait/callbacks/ckpt, i.e. real throughput
        now = time.monotonic()
        cadence = (now - self._last_step_end if self._last_step_end
                   else step_wall)
        self._last_step_end = now
        self.efficiency.end_step(step, cadence)
        self._progress.report(step)
        if self._client is not None and step % self._report_interval == 0:
            try:
                self._client.report_step(step)
                # HBM is only observable from the process that owns the
                # chips: report it alongside the step (the agent's monitor
                # covers host cpu/mem; the master merges partial reports)
                from dlrover_tpu.agent.resource_monitor import (
                    local_hbm_used_mb,
                )

                hbm = local_hbm_used_mb()
                if hbm > 0:
                    self._client.report_resource(
                        cpu_percent=0.0, used_memory_mb=0, used_hbm_mb=hbm
                    )
                # push the registry snapshot (rate-limited): carries the
                # step-duration histogram the master's continuous
                # straggler detector consumes (telemetry/anomaly.py) and
                # the per-device HBM gauges, both re-exposed under this
                # node's label by the master's /metrics
                now = time.monotonic()
                if (now - self._last_metrics_push
                        >= self._metrics_push_interval_s):
                    self._last_metrics_push = now
                    self._client.report_metrics(
                        registry().snapshot(), role="trainer"
                    )
            except (ConnectionError, RuntimeError, OSError) as e:
                # telemetry is best-effort: a master mid-failover answers
                # with RpcError (surfaced as RuntimeError) — don't kill
                # the training loop over it
                logger.warning("step report failed: %s", e)
        return state, metrics

    def _maybe_install_flops(self, state: TrainState, batch: dict) -> None:
        """Plain-jit fallback for the live MFU gauge: when the AOT path
        didn't supply FLOPs and the device has a known peak (real TPU —
        never on the CPU test backend), count the compiled program once
        via the already-populated compile cache. Uses the NEW state's
        avals (the donated input's buffers are gone, its avals are not
        what ``.lower`` needs anyway)."""
        if self.efficiency.flops_per_step > 0 \
                or not self.efficiency.peak_flops \
                or not hasattr(self.compiled.step, "lower"):
            return
        try:
            from dlrover_tpu.utils.profiler import compiled_flops

            flops = compiled_flops(self.compiled.step, state, batch)
            if flops > 0:
                self.efficiency.set_flops(flops)
        except Exception:  # noqa: BLE001 - MFU is telemetry, not training
            logger.exception("post-compile FLOPs count failed")

    def run(
        self,
        state: TrainState,
        samples: Iterator[Any],
        collate: Callable[[list], dict[str, np.ndarray]],
        max_steps: int | None = None,
        on_step: Callable[[int, dict], None] | None = None,
        checkpointer: Callable[[int, TrainState], None] | None = None,
        checkpoint_interval: int = 0,
    ) -> TrainState:
        return self.run_batches(
            state, self.assembler.batches(samples, collate),
            max_steps=max_steps, on_step=on_step,
            checkpointer=checkpointer,
            checkpoint_interval=checkpoint_interval,
        )

    def run_batches(
        self,
        state: TrainState,
        batches: Iterator[dict],
        max_steps: int | None = None,
        on_step: Callable[[int, dict], None] | None = None,
        checkpointer: Callable[[int, TrainState], None] | None = None,
        checkpoint_interval: int = 0,
    ) -> TrainState:
        """Train over pre-assembled [accum, local_batch, ...] batches
        (e.g. a PrefetchLoader)."""
        start = time.monotonic()
        # one sync at entry so a restored state's step carries forward
        self._host_step = int(state.step)
        if max_steps is not None and self._host_step >= max_steps:
            # a restored finished job must not assemble (and discard) a
            # batch, let alone run extra steps
            logger.info("restored at step %d >= max_steps %d; nothing to do",
                        self._host_step, max_steps)
            return state
        # data_wait/ckpt are observed here (train_step owns h2d/dispatch/
        # block); the ckpt phase of step N folds into step N+1's
        # accumulator — per-step attribution is one step skewed for it,
        # aggregate histograms are exact
        it = iter(batches)
        try:
            while True:
                t0 = time.monotonic()
                try:
                    batch = next(it)
                except StopIteration:
                    break
                self.efficiency.observe_phase(
                    "data_wait", time.monotonic() - t0
                )
                state, metrics = self.train_step(state, batch)
                step = self._host_step
                if on_step is not None:
                    # metrics stay on device: fetching here would
                    # serialize host and device every step; callbacks
                    # device_get at their own cadence
                    on_step(step, metrics)
                if (checkpointer is not None and checkpoint_interval
                        and step % checkpoint_interval == 0):
                    t0 = time.monotonic()
                    checkpointer(step, state)
                    self.efficiency.observe_phase(
                        "ckpt", time.monotonic() - t0
                    )
                if self.retune_hook is not None:
                    swapped = self.retune_hook(step, state)
                    if swapped is not None:
                        new_compiled, state = swapped
                        self.swap_compiled(new_compiled)
                if max_steps is not None and step >= max_steps:
                    break
        finally:
            # a capture armed mid-loop must not leak an open trace
            self.efficiency.close()
        logger.info(
            "training loop exited at step %d after %.1fs",
            self._host_step, time.monotonic() - start,
        )
        return state
