"""Packed-token datasets: real-data training input without a loader fleet.

Reference analog: the reference's data layer spans the estimator file
readers (dlrover/trainer/tensorflow/reader/file_reader.py), the master's
TextDatasetSplitter (line-offset shards), and atorch's elastic_dataset —
all built around "the master hands out index ranges; workers map indices
to samples". This module supplies the sample side for LLM pretraining
data the TPU-idiomatic way:

- ``PackedTokenDataset``: a flat binary token file, memory-mapped; sample
  i is the contiguous window ``[i*seq, i*seq + seq + 1)`` (the +1 feeds
  the next-token target). Zero-copy reads, O(1) per sample, and the
  index space composes directly with the master's dynamic sharding
  (ElasticDataset hands out exactly these indices).
- ``TextLineDataset``: newline-delimited text with a byte-offset index
  built on first open (TextDatasetSplitter's layout, worker-side) and a
  caller-supplied tokenizer for on-the-fly encoding.
- ``pack_tokens``: offline packer turning a token-id iterator into the
  flat binary file (what a preprocessing job would emit).

Static shapes by construction: every sample is exactly ``seq + 1``
tokens, so the compiled train step never re-specializes on data length —
ragged text is absorbed at pack time, not in the jit.
"""

from __future__ import annotations

import os
from typing import Callable, Iterable, Iterator

import numpy as np

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

TOKEN_DTYPE = np.uint32  # vocab < 4B; fixed so files are portable


def pack_tokens(token_iter: Iterable[int] | Iterator[np.ndarray],
                path: str, *, chunk: int = 1 << 20) -> int:
    """Write a stream of token ids (ints or id arrays) to a flat binary
    file. Returns the total token count."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    total = 0
    buf: list[int] = []
    with open(path, "wb") as f:
        def flush(items):
            nonlocal total
            arr = np.asarray(items, TOKEN_DTYPE)
            arr.tofile(f)
            total += arr.size

        for item in token_iter:
            if isinstance(item, (list, np.ndarray)):
                if buf:
                    flush(buf)
                    buf = []
                flush(np.asarray(item).reshape(-1))
            else:
                buf.append(int(item))
                if len(buf) >= chunk:
                    flush(buf)
                    buf = []
        if buf:
            flush(buf)
    return total


class PackedTokenDataset:
    """Flat binary token file -> fixed-length training windows.

    ``ds[i]`` is ``{"tokens": uint32[seq + 1]}`` — the shape the
    transformer example's CLM loss consumes. ``stride`` defaults to
    ``seq`` (disjoint windows); smaller strides oversample boundaries.
    """

    def __init__(self, path: str, seq: int, stride: int = 0):
        self.path = path
        self.seq = seq
        self.stride = stride or seq
        size = os.path.getsize(path)
        if size % np.dtype(TOKEN_DTYPE).itemsize:
            raise ValueError(
                f"{path} is not a whole number of {TOKEN_DTYPE} tokens"
            )
        self._tokens = np.memmap(path, dtype=TOKEN_DTYPE, mode="r")
        n = self._tokens.size
        if n < seq + 1:
            raise ValueError(
                f"{path} holds {n} tokens < one window of {seq + 1}"
            )
        self._len = (n - (seq + 1)) // self.stride + 1

    def __len__(self) -> int:
        return self._len

    def __getitem__(self, i: int) -> dict:
        if not 0 <= i < self._len:
            raise IndexError(i)
        lo = i * self.stride
        # np.array copies out of the mmap: samples must not pin pages
        # once collated into a batch
        return {"tokens": np.array(
            self._tokens[lo: lo + self.seq + 1], np.int32
        )}


class TextLineDataset:
    """Newline-delimited text + tokenizer -> fixed-length windows.

    The byte-offset line index is built once per open (the worker-side
    twin of the master's TextDatasetSplitter, dataset_splitter.py);
    lines tokenize lazily and are truncated/padded to ``seq + 1``.
    """

    def __init__(self, path: str, seq: int,
                 tokenize: Callable[[str], list[int]],
                 pad_id: int = 0):
        self.path = path
        self.seq = seq
        self.tokenize = tokenize
        self.pad_id = pad_id
        offsets = [0]
        with open(path, "rb") as f:
            for line in f:
                offsets.append(offsets[-1] + len(line))
        # drop the EOF sentinel; empty trailing line never indexes
        self._offsets = np.asarray(offsets[:-1], np.int64)
        self._f = open(path, "rb")

    def __len__(self) -> int:
        return len(self._offsets)

    def __getitem__(self, i: int) -> dict:
        if not 0 <= i < len(self._offsets):
            raise IndexError(i)
        self._f.seek(self._offsets[i])
        text = self._f.readline().decode("utf-8").rstrip("\n")
        ids = self.tokenize(text)[: self.seq + 1]
        out = np.full((self.seq + 1,), self.pad_id, np.int32)
        out[: len(ids)] = ids
        return {"tokens": out}

    def close(self) -> None:
        self._f.close()
