"""High-level Trainer: the HF-Trainer-class UX over the strategy layer.

Reference analog: atorch/atorch/trainer/atorch_trainer.py:129 (AtorchTrainer:
train/eval loops, logging, checkpoint save policies with rotation, best-model
tracking, resume semantics) and atorch/atorch/trainer/atorch_args.py:21
(AtorchArguments). TPU-native differences:

- The reference wraps a mutable torch module and drives auto_accelerate
  imperatively; here the model surface is a ``loss_fn`` factory compiled once
  into a single SPMD program (``trainer/train_step.py``), and the Trainer owns
  only host-side control flow — epochs, logging cadence, eval cadence, save
  policy, resume. Everything under ``jit`` stays pure.
- Checkpointing is the flash-checkpoint engine (shm snapshot + async persist,
  ``checkpoint/engine.py``), so ``save_steps`` costs sub-second blocking time
  and rotation/best-model bookkeeping happens against the committed tracker.
- Metric tensors stay on device between logging steps: the loop never calls
  ``device_get`` per step, preserving async dispatch (the reference's
  equivalent concern is CUDA-stream sync in its logging hot path).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import time
from typing import Any, Callable, Iterable, Sequence

import jax
import numpy as np
import optax

from dlrover_tpu.agent.ckpt_saver import read_tracker, step_dir
from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.checkpoint.shm_handler import _leaf_paths
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.mesh import batch_axes, data_parallel_size
from dlrover_tpu.parallel.strategy import PRESETS, Strategy
from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer
from dlrover_tpu.trainer.train_step import CompiledTrain, compile_train

logger = get_logger(__name__)

IntervalStrategy = str  # "no" | "steps" | "epoch"


@dataclasses.dataclass
class TrainingArguments:
    """Host-side training configuration (AtorchArguments analog).

    Batch semantics: ``global_batch_size`` is invariant under elasticity
    (the ElasticTrainer resolves gradient accumulation from the live dp
    size); ``micro_batch_size`` is the per-device-step slice.
    """

    output_dir: str = "trainer_out"
    max_steps: int = -1                  # >0 overrides num_train_epochs
    num_train_epochs: float = 1.0
    global_batch_size: int = 32
    micro_batch_size: int = 0            # 0 -> one accumulation step
    eval_batch_size: int = 0             # 0 -> global_batch_size
    seed: int = 0
    shuffle: bool = True

    logging_steps: int = 10
    logging_first_step: bool = True

    eval_strategy: IntervalStrategy = "no"
    eval_steps: int = 0                  # used when eval_strategy == "steps"

    save_strategy: IntervalStrategy = "no"
    save_steps: int = 0                  # used when save_strategy == "steps"
    save_total_limit: int | None = None
    # flash-checkpoint hot path: shm-only snapshots between persisted saves
    # (0 disables). Restart-in-place restores from the newest snapshot even
    # if it was never persisted.
    memory_save_steps: int = 0

    metric_for_best_model: str | None = None   # e.g. "eval_loss"
    greater_is_better: bool = False
    load_best_model_at_end: bool = False

    resume_from_checkpoint: bool = True

    def __post_init__(self):
        if self.micro_batch_size <= 0:
            self.micro_batch_size = self.global_batch_size
        if self.eval_batch_size <= 0:
            self.eval_batch_size = self.global_batch_size
        if self.eval_strategy == "steps" and self.eval_steps <= 0:
            raise ValueError("eval_strategy='steps' requires eval_steps > 0")
        if self.save_strategy == "steps" and self.save_steps <= 0:
            raise ValueError("save_strategy='steps' requires save_steps > 0")
        if self.load_best_model_at_end and not self.metric_for_best_model:
            self.metric_for_best_model = "eval_loss"

    # ---- serialization (config-system parity: Strategy-style round trip)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "TrainingArguments":
        return cls(**json.loads(text))


@dataclasses.dataclass
class TrainerState:
    """Host-side progress bookkeeping, persisted as trainer_state.json.

    The device-side step counter lives in TrainState; this mirror carries
    what the devices can't: epoch position, log history, best-model metric.
    """

    global_step: int = 0
    epoch: float = 0.0
    best_metric: float | None = None
    best_step: int | None = None
    log_history: list = dataclasses.field(default_factory=list)

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "TrainerState":
        return cls(**json.loads(text))


@dataclasses.dataclass
class TrainerControl:
    """Mutable flow-control flags callbacks may set (HF TrainerControl)."""

    should_training_stop: bool = False
    should_log: bool = False
    should_evaluate: bool = False
    should_save: bool = False


class TrainerCallback:
    """Hook points around the loop. Mutate ``control`` to steer flow."""

    def on_train_begin(self, args, state, control, **kw): ...
    def on_epoch_begin(self, args, state, control, **kw): ...
    def on_step_end(self, args, state, control, **kw): ...
    def on_log(self, args, state, control, logs=None, **kw): ...
    def on_evaluate(self, args, state, control, metrics=None, **kw): ...
    def on_save(self, args, state, control, **kw): ...
    def on_epoch_end(self, args, state, control, **kw): ...
    def on_train_end(self, args, state, control, **kw): ...
    # fired when train() is about to re-raise an exception; release
    # resources here (on_train_end does NOT fire on the failure path)
    def on_train_error(self, args, state, control, **kw): ...


class LoggingCallback(TrainerCallback):
    """Default logger: structured line per log event + JSONL file."""

    def __init__(self, path: str | None = None):
        self._path = path

    def on_log(self, args, state, control, logs=None, **kw):
        if not logs:
            return
        logger.info(
            "step %d: %s", state.global_step,
            " ".join(f"{k}={v:.5g}" if isinstance(v, float) else f"{k}={v}"
                     for k, v in logs.items()),
        )
        if self._path:
            with open(self._path, "a") as f:
                f.write(json.dumps(
                    {"step": state.global_step, **logs}) + "\n")


class GoodputCallback(TrainerCallback):
    """Write the per-step goodput event log (utils/goodput.py) from the
    Trainer loop; aggregate offline with ``compute_goodput``."""

    def __init__(self, path: str):
        self._path = path
        self._recorder = None

    def on_train_begin(self, args, state, control, **kw):
        from dlrover_tpu.common.constants import EnvKey
        from dlrover_tpu.utils.goodput import GoodputRecorder

        restart = int(os.environ.get(EnvKey.RESTART_COUNT, "0"))
        self._recorder = GoodputRecorder(self._path, restart)

    def on_step_end(self, args, state, control, **kw):
        if self._recorder is not None:
            self._recorder.step(state.global_step)

    def on_train_end(self, args, state, control, **kw):
        if self._recorder is not None:
            self._recorder.done()
            self._recorder.close()
            self._recorder = None

    def on_train_error(self, args, state, control, **kw):
        # no "done" event: a crashed incarnation looks the same as a
        # SIGKILLed one to the aggregator — only release the handle
        if self._recorder is not None:
            self._recorder.close()
            self._recorder = None


class EarlyStoppingCallback(TrainerCallback):
    """Stop after ``patience`` evaluations without improvement."""

    def __init__(self, patience: int = 3, threshold: float = 0.0):
        self.patience = patience
        self.threshold = threshold
        self._bad_evals = 0
        # own best-so-far: state.best_metric is already updated to THIS
        # eval by the time callbacks fire, so comparing against it would
        # count every new best as "no improvement"
        self._best: float | None = None

    def on_evaluate(self, args, state, control, metrics=None, **kw):
        key = args.metric_for_best_model or "eval_loss"
        value = (metrics or {}).get(key)
        if value is None:
            return
        sign = 1.0 if args.greater_is_better else -1.0
        if self._best is None or sign * (value - self._best) > self.threshold:
            self._best = value
            self._bad_evals = 0
        else:
            self._bad_evals += 1
            if self._bad_evals >= self.patience:
                logger.info(
                    "early stop: %s stalled for %d evals", key, self.patience
                )
                control.should_training_stop = True


class CallbackHandler:
    def __init__(self, callbacks: Sequence[TrainerCallback]):
        self.callbacks = list(callbacks)

    def fire(self, event: str, args, state, control, **kw):
        for cb in self.callbacks:
            getattr(cb, event)(args, state, control, **kw)


def _default_collate(samples: list) -> dict[str, np.ndarray]:
    if isinstance(samples[0], dict):
        return {
            k: np.stack([s[k] for s in samples]) for k in samples[0]
        }
    return {"batch": np.stack(samples)}


class Trainer:
    """Train/eval/save driver over one compiled SPMD step.

    Model surface (mirrors compile_train):
      - ``loss_fn_for(strategy, mesh) -> loss_fn(params, micro_batch)`` or a
        plain ``loss_fn`` when it doesn't depend on the layout;
      - ``init_params_fn(rng)`` + ``logical_params`` (axis names) so the
        strategy layer can place every tensor;
      - ``optimizer`` (optax), optionally ``lr_schedule(step)`` for logging.

    Data surface: ``train_dataset`` is a Sequence (len/getitem -> epoch +
    shuffle semantics) or any re-iterable; ``collate_fn(list) -> dict of
    np.ndarray`` stacks samples. Elastic runs pass a master-fed
    ElasticDataset here unchanged.
    """

    def __init__(
        self,
        *,
        args: TrainingArguments,
        optimizer: optax.GradientTransformation,
        init_params_fn: Callable[..., Any],
        logical_params: Any,
        loss_fn: Callable[[Any, Any], jax.Array] | None = None,
        loss_fn_for: Callable[[Strategy, Any], Callable] | None = None,
        train_dataset: Iterable | None = None,
        eval_dataset: Iterable | None = None,
        collate_fn: Callable[[list], dict[str, np.ndarray]] | None = None,
        compute_metrics: Callable[[Any, Any], dict] | None = None,
        strategy: Strategy | str | None = None,
        callbacks: Sequence[TrainerCallback] | None = None,
        lr_schedule: Callable[[int], float] | None = None,
        engine: CheckpointEngine | None = None,
        example_batch: Any | None = None,
    ):
        self.args = args
        self.train_dataset = train_dataset
        self.eval_dataset = eval_dataset
        self.collate_fn = collate_fn or _default_collate
        self.compute_metrics = compute_metrics
        self.lr_schedule = lr_schedule

        if strategy == "auto":
            # auto_accelerate-style search, cached in output_dir (the
            # load_strategy analog): restarts reuse the tuned pick.
            # ``example_batch`` carries ONE SAMPLE's shapes; the real
            # [accum=1, global_batch, ...] layout is derived from args
            # so the fit check sizes the workload actually trained
            # (full global batch in one step — the conservative bound).
            if example_batch is None:
                raise ValueError(
                    "strategy='auto' requires example_batch (per-sample "
                    "shapes; the Trainer adds the batch dims)"
                )
            lf_for = loss_fn_for
            if lf_for is None:
                if loss_fn is None:
                    raise ValueError(
                        "strategy='auto' requires loss_fn or loss_fn_for"
                    )
                lf_for = lambda s, m: loss_fn  # noqa: E731

            from dlrover_tpu.parallel.auto import cached_auto_strategy

            gb = args.global_batch_size
            sized_batch = jax.tree_util.tree_map(
                lambda a: np.zeros(
                    (1, gb, *np.shape(a)), np.asarray(a).dtype
                ),
                example_batch,
            )
            strategy, _ = cached_auto_strategy(
                os.path.join(args.output_dir, "strategy.json"),
                loss_fn_for=lf_for,
                init_params_fn=init_params_fn,
                logical_params=logical_params,
                optimizer=optimizer,
                example_batch=sized_batch,
            )
        elif isinstance(strategy, str):
            strategy = PRESETS[strategy]()
        elif strategy is None:
            strategy = PRESETS["dp"]()
        self.strategy = strategy
        self.mesh = strategy.build_mesh()
        if loss_fn_for is not None:
            loss_fn = loss_fn_for(strategy, self.mesh)
        if loss_fn is None:
            raise ValueError("need loss_fn or loss_fn_for")
        self._eval_loss_fn = loss_fn

        self.compiled: CompiledTrain = compile_train(
            strategy=strategy,
            mesh=self.mesh,
            loss_fn=loss_fn,
            init_params_fn=init_params_fn,
            logical_params=logical_params,
            optimizer=optimizer,
        )
        self.elastic = ElasticTrainer(
            self.compiled,
            global_batch_size=args.global_batch_size,
            micro_batch_size=args.micro_batch_size,
        )

        os.makedirs(args.output_dir, exist_ok=True)
        self.ckpt_dir = os.path.join(args.output_dir, "checkpoints")
        self._owns_engine = engine is None
        self.engine = engine or CheckpointEngine(self.ckpt_dir)
        self.state = TrainerState()
        self.control = TrainerControl()
        log_path = os.path.join(args.output_dir, "log_history.jsonl")
        self.callback_handler = CallbackHandler(
            [LoggingCallback(log_path)] + list(callbacks or [])
        )
        self._eval_step_fn = None
        self._train_state = None  # device TrainState, set by train()
        self._last_save_step = -1

    # ------------------------------------------------------------ data plumbing

    def _steps_per_epoch(self) -> int | None:
        ds = self.train_dataset
        if ds is not None and hasattr(ds, "__len__"):
            return max(1, len(ds) // self.args.global_batch_size)
        return None

    def _epoch_samples(self, epoch: int, skip_steps: int = 0) -> Iterable:
        """One epoch's sample stream (seeded shuffle for Sequences).

        Multi-process SPMD: every process derives the same permutation,
        truncates it to a multiple of the process count (unequal
        per-process counts would desync the collective step), then takes
        its strided slice — each remaining sample lands on exactly one
        process and every process yields the same number of step batches.
        Elastic runs use a master-fed dataset instead (pre-sharded).

        ``skip_steps`` drops already-consumed step batches at the SAMPLE
        level (mid-epoch resume) — slicing here instead of draining
        assembled batches keeps restart-in-place sub-second.
        """
        ds = self.train_dataset
        np_ = self.elastic.num_processes
        skip_samples = skip_steps * self.elastic.assembler.accum \
            * self.elastic.assembler.batch_size
        if hasattr(ds, "__len__") and hasattr(ds, "__getitem__"):
            order = np.arange(len(ds))
            if self.args.shuffle:
                order = np.random.default_rng(
                    self.args.seed + epoch).permutation(len(ds))
            if np_ > 1:
                order = order[:len(order) - len(order) % np_]
                order = order[jax.process_index()::np_]
            return (ds[int(i)] for i in order[skip_samples:])
        import itertools

        return itertools.islice(iter(ds), skip_samples, None)

    def _sample_iter(self, ds: Iterable, shard: bool = True) -> Iterable:
        """Eval sample stream, optionally sharded across processes.

        Sharded Sequences are padded by wrap-around to a process-count
        multiple then strided: equal batch counts on every process
        (collective safety), every sample scored at least once
        (drop_last=False; the <np wrapped samples weigh double in the
        mean). ``shard=False`` (predict) and plain iterables read the
        full stream on every process.
        """
        np_ = self.elastic.num_processes
        if hasattr(ds, "__len__") and hasattr(ds, "__getitem__"):
            if shard and np_ > 1:
                idx = list(range(len(ds)))
                idx += idx[:(-len(idx)) % np_]  # wrap-pad to a multiple
                idx = idx[jax.process_index()::np_]
            else:
                idx = range(len(ds))
            return (ds[int(i)] for i in idx)
        return iter(ds)

    @staticmethod
    def _batched(samples: Iterable, n: int) -> Iterable[tuple[list, int]]:
        """(buffer, true_count) chunks of n samples; the last chunk is
        padded by repetition so compiled shapes stay static, with
        true_count telling the caller how many rows are real."""
        buf: list = []
        for s in samples:
            buf.append(s)
            if len(buf) == n:
                yield buf, n
                buf = []
        if buf:
            true = len(buf)
            yield (buf * math.ceil(n / true))[:n], true

    def _eval_local_batch(self) -> int:
        """Per-process eval batch: global eval batch rounded up to a
        multiple of the data-parallel extent (sharding divisibility),
        split across processes, never zero."""
        dp = data_parallel_size(self.mesh)
        global_bsz = max(self.args.eval_batch_size, dp)
        global_bsz = ((global_bsz + dp - 1) // dp) * dp
        return max(1, global_bsz // self.elastic.num_processes)

    def num_examples(self) -> int | None:
        ds = self.train_dataset
        return len(ds) if ds is not None and hasattr(ds, "__len__") else None

    # ------------------------------------------------------------------ resume

    def _init_or_resume(self) -> Any:
        state = self.compiled.init(jax.random.PRNGKey(self.args.seed))
        if not self.args.resume_from_checkpoint:
            return state
        shard_of = dict(_leaf_paths(self.compiled.state_shardings))
        loaded = self.engine.load(
            state,
            put=lambda name, arr: jax.device_put(arr, shard_of[name]),
            zero_copy=True,
        )
        if loaded is None:
            return state
        step, state = loaded
        self.state.global_step = step
        ts_path = os.path.join(self.args.output_dir, "trainer_state.json")
        if os.path.exists(ts_path):
            with open(ts_path) as f:
                saved = TrainerState.from_json(f.read())
            # the checkpoint step wins over the (possibly newer) json
            saved.global_step = step
            self.state = saved
        logger.info("resumed at step %d", step)
        return state

    # ---------------------------------------------------------------- training

    def train(self) -> TrainerState:
        try:
            return self._train()
        except BaseException:
            # resource-releasing hook for callbacks holding files/threads
            # (on_train_end only fires on the success path)
            self.callback_handler.fire(
                "on_train_error", self.args, self.state, self.control
            )
            raise

    def _train(self) -> TrainerState:
        args = self.args
        state = self._init_or_resume()
        steps_per_epoch = self._steps_per_epoch()
        if args.max_steps > 0:
            total_steps = args.max_steps
        elif steps_per_epoch is not None:
            total_steps = int(steps_per_epoch * args.num_train_epochs)
        else:
            raise ValueError(
                "max_steps required for datasets without __len__"
            )
        self.callback_handler.fire(
            "on_train_begin", args, self.state, self.control
        )
        pending_metrics: list = []
        last_log_step = self.state.global_step
        last_log_time = time.monotonic()

        def flush_logs(step: int):
            nonlocal pending_metrics, last_log_step, last_log_time
            if not pending_metrics:
                return
            fetched = jax.device_get(pending_metrics)
            logs = {
                k: float(np.mean([m[k] for m in fetched]))
                for k in fetched[0]
            }
            now = time.monotonic()
            dsteps = step - last_log_step
            if dsteps > 0 and now > last_log_time:
                rate = dsteps / (now - last_log_time)
                logs["steps_per_sec"] = rate
                logs["samples_per_sec"] = rate * args.global_batch_size
            if self.lr_schedule is not None:
                logs["learning_rate"] = float(self.lr_schedule(step))
            if steps_per_epoch:
                self.state.epoch = step / steps_per_epoch
                logs["epoch"] = round(self.state.epoch, 4)
            pending_metrics = []
            last_log_step, last_log_time = step, now
            self.state.log_history.append(
                {"step": step, **logs})
            self.callback_handler.fire(
                "on_log", args, self.state, self.control, logs=logs
            )

        epoch = int(self.state.global_step // steps_per_epoch
                    ) if steps_per_epoch else 0
        done = self.state.global_step >= total_steps
        while not done and not self.control.should_training_stop:
            self.callback_handler.fire(
                "on_epoch_begin", args, self.state, self.control
            )
            # mid-epoch resume: same seed -> same order, so skipping the
            # consumed steps' samples realigns the stream
            skip = (self.state.global_step % steps_per_epoch
                    if steps_per_epoch else 0)
            batches = self.elastic.assembler.batches(
                self._epoch_samples(epoch, skip_steps=skip),
                self.collate_fn,
            )
            made_progress = False
            for batch in batches:
                made_progress = True
                state, metrics = self.elastic.train_step(state, batch)
                self.state.global_step += 1
                step = self.state.global_step
                pending_metrics.append(metrics)
                self.callback_handler.fire(
                    "on_step_end", args, self.state, self.control
                )
                if (self.control.should_log
                        or (args.logging_first_step
                            and step == 1)
                        or (args.logging_steps
                            and step % args.logging_steps == 0)):
                    self.control.should_log = False
                    flush_logs(step)
                if (self.control.should_evaluate
                        or (args.eval_strategy == "steps"
                            and step % args.eval_steps == 0)):
                    self.control.should_evaluate = False
                    self._evaluate_during_training(state)
                if (self.control.should_save
                        or (args.save_strategy == "steps"
                            and step % args.save_steps == 0)):
                    self.control.should_save = False
                    self._save_checkpoint(step, state)
                elif (args.memory_save_steps
                        and step % args.memory_save_steps == 0):
                    # zero-stall where safe; the engine self-gates
                    # (sharded/CPU fall back to the sync path)
                    self.engine.save_to_memory_async(step, state)
                if step >= total_steps or self.control.should_training_stop:
                    break
            if not made_progress:
                # a non-restartable stream ran dry short of total_steps:
                # stop rather than spin on empty epochs
                logger.warning(
                    "dataset exhausted at step %d (< %d); stopping",
                    self.state.global_step, total_steps,
                )
                break
            epoch += 1
            if steps_per_epoch:
                self.state.epoch = self.state.global_step / steps_per_epoch
            if (args.eval_strategy == "epoch"
                    and not self.control.should_training_stop):
                self._evaluate_during_training(state)
            if (args.save_strategy == "epoch"
                    and not self.control.should_training_stop):
                self._save_checkpoint(self.state.global_step, state)
            self.callback_handler.fire(
                "on_epoch_end", args, self.state, self.control
            )
            done = self.state.global_step >= total_steps
        flush_logs(self.state.global_step)
        state = self._finalize(state)
        self._train_state = state
        self.callback_handler.fire(
            "on_train_end", args, self.state, self.control
        )
        return self.state

    def _finalize(self, state):
        args = self.args
        if args.save_strategy != "no":
            step = self.state.global_step
            if self._last_save_step < step:
                self._save_checkpoint(step, state)
            waited = self.engine.wait_for_persist(step)
            if waited:
                # in-loop rotations see whatever the async persister had
                # committed at the time; with the final step durable,
                # this pass makes the retained set deterministic
                self._rotate_checkpoints(step)
            else:
                # the final step never became durable: rotating now
                # could delete the only restorable older step
                logger.warning(
                    "final checkpoint (step %d) not durable after "
                    "%.0fs (newest committed: %d); skipping rotation",
                    step, waited.waited_s, waited.persisted_step,
                )
        if args.load_best_model_at_end and self.state.best_step is not None:
            best = self.state.best_step
            if best != self.state.global_step:
                loaded = self._load_step(best, state)
                if loaded is None:
                    logger.warning(
                        "best-model reload failed (step %d not restorable);"
                        " keeping the final weights", best,
                    )
                else:
                    state = loaded
                    logger.info(
                        "loaded best model (step %d, %s=%.5g)", best,
                        args.metric_for_best_model, self.state.best_metric,
                    )
        return state

    def _load_step(self, step: int, template):
        """The pinned-step restore, or None when it can't be honored."""
        if not self.engine.replicated:
            logger.warning(
                "best-model reload needs the replicated engine"
            )
            return None
        # NB: a later step's commit also satisfies this wait — the pinned
        # load below is what actually verifies step N is on disk
        waited = self.engine.wait_for_persist(step)
        if not waited:
            logger.warning(
                "best-model step %d not durable after %.0fs; the "
                "pinned reload will likely fail", step, waited.waited_s,
            )
        shard_of = dict(_leaf_paths(self.compiled.state_shardings))
        loaded = self.engine.load(
            template,
            put=lambda name, arr: jax.device_put(arr, shard_of[name]),
            zero_copy=True,
            step=step,
        )
        return None if loaded is None else loaded[1]

    # ------------------------------------------------------------- checkpoints

    def _durable_save(self, step: int, state) -> bool:
        """save_to_storage with a bounded retry: the snapshot skips while
        the async persister holds the shm lock, and silently dropping a
        scheduled save would hand a restart an older step."""
        for _ in range(20):
            if self.engine.save_to_storage(step, state):
                return True
            time.sleep(0.25)
        logger.warning(
            "checkpoint at step %d dropped: persister busy for >5s", step
        )
        return False

    def _save_checkpoint(self, step: int, state) -> None:
        if not self._durable_save(step, state):
            return
        self._last_save_step = step
        with open(os.path.join(
                self.args.output_dir, "trainer_state.json"), "w") as f:
            f.write(self.state.to_json())
        self.callback_handler.fire(
            "on_save", self.args, self.state, self.control
        )
        self._rotate_checkpoints(step)

    def _persisted_steps(self) -> list[int]:
        steps = []
        for name in self.engine.storage.listdir(self.ckpt_dir):
            if name.startswith("step-"):
                try:
                    steps.append(int(name.split("-", 1)[1]))
                except ValueError:
                    continue
        return sorted(steps)

    def _rotate_checkpoints(self, current_step: int) -> None:
        """Delete oldest persisted checkpoints beyond save_total_limit.

        Never deletes: the best step (when best-model tracking is on), the
        tracker-committed step, or anything the async persister hasn't
        committed yet (a newer uncommitted dir isn't counted against the
        limit — deleting it would race the persister).
        """
        limit = self.args.save_total_limit
        if not limit or limit < 1:
            return
        committed = read_tracker(self.engine.storage, self.ckpt_dir)
        committed_step = committed[0] if committed else -1
        protected = {committed_step, current_step}
        if self.args.load_best_model_at_end and self.state.best_step:
            protected.add(self.state.best_step)
        all_steps = self._persisted_steps()
        # deletable: committed (persister is done with them) and unprotected
        deletable = [
            s for s in all_steps if s <= committed_step and s not in protected
        ]
        n_kept_always = len(all_steps) - len(deletable)
        allowed = max(0, limit - n_kept_always)
        drop = deletable[:len(deletable) - allowed] if allowed else deletable
        for s in drop:
            self.engine.storage.delete(step_dir(self.ckpt_dir, s))
            logger.info("rotated out checkpoint step %d", s)

    # ------------------------------------------------------------- evaluation

    def _build_eval_step(self):
        if self._eval_step_fn is not None:
            return self._eval_step_fn
        from jax.sharding import NamedSharding, PartitionSpec

        axes = batch_axes(self.mesh)
        spec = PartitionSpec(
            axes if len(axes) > 1 else (axes[0] if axes else None)
        )
        self._eval_batch_sharding = NamedSharding(self.mesh, spec)
        replicated = NamedSharding(self.mesh, PartitionSpec())
        loss_fn = self._eval_loss_fn
        metrics_fn = self.compute_metrics

        def _eval(params, batch):
            out = {"eval_loss": loss_fn(params, batch)}
            if metrics_fn is not None:
                out.update({
                    f"eval_{k}": v for k, v in metrics_fn(
                        params, batch).items()
                })
            return out

        self._eval_step_fn = jax.jit(
            _eval,
            in_shardings=(self.compiled.state_shardings.params,
                          self._eval_batch_sharding),
            out_shardings=replicated,
        )
        return self._eval_step_fn

    def _put_eval_batch(self, batch: dict) -> dict:
        sharding = self._eval_batch_sharding
        if self.elastic.num_processes > 1:
            return jax.tree.map(
                lambda x: jax.make_array_from_process_local_data(
                    sharding, np.ascontiguousarray(x),
                    (x.shape[0] * self.elastic.num_processes,)
                    + x.shape[1:],
                ),
                batch,
            )
        return jax.device_put(batch, sharding)

    def evaluate(self, eval_dataset: Iterable | None = None,
                 params: Any | None = None) -> dict[str, float]:
        """Mean metrics over the eval set (sharded forward, no grads)."""
        ds = eval_dataset if eval_dataset is not None else self.eval_dataset
        if ds is None:
            raise ValueError("no eval_dataset")
        if params is None:
            if self._train_state is None:
                raise ValueError("no params: train first or pass params")
            params = self._train_state.params
        eval_step = self._build_eval_step()
        local_bsz = self._eval_local_batch()
        per_batch: list = []
        # padding keeps the compiled shape; weighting is by batch, matching
        # the reference's drop_last=False mean
        for buf, _true in self._batched(self._sample_iter(ds), local_bsz):
            batch = self.collate_fn(buf)
            per_batch.append(eval_step(params, self._put_eval_batch(batch)))
        if not per_batch:
            return {}
        fetched = jax.device_get(per_batch)
        return {
            k: float(np.mean([m[k] for m in fetched])) for k in fetched[0]
        }

    def _evaluate_during_training(self, state) -> None:
        metrics = self.evaluate(params=state.params)
        self.state.log_history.append(
            {"step": self.state.global_step, **metrics})
        key = self.args.metric_for_best_model
        if key and key in metrics:
            value = metrics[key]
            sign = 1.0 if self.args.greater_is_better else -1.0
            if (self.state.best_metric is None
                    or sign * (value - self.state.best_metric) > 0):
                self.state.best_metric = value
                self.state.best_step = self.state.global_step
                if self.args.load_best_model_at_end:
                    # the best step must be durable to be reloadable
                    self._durable_save(self.state.global_step, state)
        self.callback_handler.fire(
            "on_evaluate", self.args, self.state, self.control,
            metrics=metrics,
        )

    def predict(self, dataset: Iterable,
                forward_fn: Callable[[Any, Any], Any],
                params: Any | None = None) -> list:
        """Run ``forward_fn(params, batch)`` over a dataset; returns host
        arrays per batch (the reference's Trainer.predict analog).
        Every process reads the FULL dataset (complete outputs
        everywhere; multi-process runs duplicate the forward work)."""
        if params is None:
            if self._train_state is None:
                raise ValueError("no params: train first or pass params")
            params = self._train_state.params
        self._build_eval_step()  # for the batch sharding
        fn = jax.jit(forward_fn)
        local_bsz = self._eval_local_batch()
        outs: list = []
        for buf, true in self._batched(
                self._sample_iter(dataset, shard=False),
                local_bsz):
            batch = self.collate_fn(buf)
            out = jax.device_get(fn(params, self._put_eval_batch(batch)))
            if true < local_bsz:
                # drop the padding rows so callers see len(dataset) outputs
                out = jax.tree.map(lambda x: x[:true], out)
            outs.append(out)
        return outs

    # ---------------------------------------------------------------- cleanup

    def close(self) -> None:
        if self._owns_engine:
            self.engine.close()
