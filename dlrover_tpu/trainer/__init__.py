from dlrover_tpu.trainer.train_step import (  # noqa: F401
    CompiledTrain,
    TrainState,
    compile_train,
    zero_shard_specs,
)
from dlrover_tpu.trainer.elastic_trainer import ElasticTrainer  # noqa: F401
from dlrover_tpu.trainer.sharding_client import (  # noqa: F401
    IndexShardingClient,
    ShardingClient,
)
from dlrover_tpu.trainer.trainer import (  # noqa: F401
    EarlyStoppingCallback,
    GoodputCallback,
    Trainer,
    TrainerCallback,
    TrainerControl,
    TrainerState,
    TrainingArguments,
)
