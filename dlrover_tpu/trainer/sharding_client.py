"""Worker-side dynamic data sharding clients.

Reference analog: dlrover/python/elastic_agent/sharding/client.py
(ShardingClient:29 with at-least-once reporting and shard checkpointing;
IndexShardingClient:231 dispensing per-sample indices). Workers pull
[start, end) shards from the master's TaskManager so data assignment follows
live membership — the mechanism that keeps epochs exact across elasticity.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Iterator

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.messages import DatasetShardParams, ShardTask
from dlrover_tpu.agent.master_client import MasterClient

logger = get_logger(__name__)


class ShardingClient:
    """Fetch shards, report completion, checkpoint shard progress."""

    def __init__(
        self,
        dataset_name: str,
        dataset_size: int,
        shard_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        storage_type: str = "table",
        master_client: MasterClient | None = None,
        fetch_timeout: float = 60.0,
    ):
        self._client = master_client or MasterClient.singleton()
        self.dataset_name = dataset_name
        self._fetch_timeout = fetch_timeout
        self._current: ShardTask | None = None
        self._client.report_dataset_params(
            DatasetShardParams(
                dataset_name=dataset_name,
                dataset_size=dataset_size,
                shard_size=shard_size,
                num_epochs=num_epochs,
                shuffle=shuffle,
                storage_type=storage_type,
            )
        )

    def fetch_shard(self) -> ShardTask | None:
        """Next shard, or None when the dataset is exhausted.

        An invalid task can mean either "all epochs done" or "queue briefly
        empty while peers' in-flight shards may still fail back onto it", so
        poll until the timeout before concluding exhaustion.
        """
        deadline = time.time() + self._fetch_timeout
        while True:
            task = self._client.get_task(self.dataset_name)
            if task.valid:
                self._current = task
                return task
            if task.finished or time.time() >= deadline:
                return None
            time.sleep(0.5)

    def report_done(self, task: ShardTask | None = None,
                    success: bool = True, error: str = "") -> None:
        task = task or self._current
        if task is None:
            return
        self._client.report_task_result(
            task.task_id, self.dataset_name, success=success, error=error
        )
        if task is self._current:
            self._current = None

    def shard_checkpoint(self) -> str:
        return self._client.get_shard_checkpoint(self.dataset_name)

    def restore_shard_checkpoint(self, content: str) -> None:
        self._client.restore_shard_checkpoint(self.dataset_name, content)

    def iter_shards(self) -> Iterator[ShardTask]:
        """At-least-once shard stream: completion reported when the caller
        advances to the next shard."""
        while True:
            task = self.fetch_shard()
            if task is None:
                return
            yield task
            self.report_done(task)


class IndexShardingClient(ShardingClient):
    """Per-sample index stream over the shard protocol.

    A background thread keeps the index queue fed so sample consumption
    never stalls on an RPC (reference: IndexShardingClient:231).
    """

    def __init__(self, *args, prefetch_shards: int = 2, **kwargs):
        super().__init__(*args, **kwargs)
        self._indices: queue.Queue = queue.Queue(
            maxsize=max(1, prefetch_shards) * 4096
        )
        self._done = threading.Event()
        self._fill_error: BaseException | None = None
        self._thread = threading.Thread(
            target=self._fill, name="index-sharding", daemon=True
        )
        self._thread.start()

    def _put(self, item) -> bool:
        """Bounded put that gives up when the client is closed."""
        while not self._done.is_set():
            try:
                self._indices.put(item, timeout=0.5)
                return True
            except queue.Full:
                continue
        return False

    def _fill(self) -> None:
        try:
            while not self._done.is_set():
                task = self.fetch_shard()
                if task is None:
                    break
                for idx in task.indices():
                    if not self._put((idx, None)):
                        return
                # sentinel marks shard boundary for completion reporting
                if not self._put((None, task)):
                    return
        except BaseException as e:  # noqa: BLE001 - surfaced to the consumer
            self._fill_error = e
            logger.exception("index prefetch thread failed")
        finally:
            self._put((None, None))

    def next_index(self, timeout: float = 120.0) -> int | None:
        """Next sample index, or None at end of data.

        Raises if the prefetch thread died (e.g. master unreachable) so an
        RPC failure is never mistaken for end-of-epoch.
        """
        deadline = time.time() + timeout
        while True:
            remain = deadline - time.time()
            if remain <= 0:
                return None
            try:
                idx, boundary = self._indices.get(timeout=min(remain, 1.0))
            except queue.Empty:
                continue
            if idx is not None:
                return idx
            if boundary is None:
                if self._fill_error is not None:
                    raise RuntimeError(
                        "index prefetch failed"
                    ) from self._fill_error
                return None
            self.report_done(boundary)

    def close(self) -> None:
        self._done.set()
