"""Elastic data pipeline: master-fed samples with background prefetch.

Reference analog: ATorch's data layer (atorch/atorch/data/ —
ElasticDataset:19 backed by the shard client, elastic_dataloader.py built
from the paral-config file, preloader.py GPU prefetch) and the trainer's
ElasticDataLoader (dlrover/trainer/torch/elastic/dataloader.py:26). TPU
shape: a background thread pulls sample indices from the master's dynamic
sharding, materializes + collates them into step batches, and keeps a
bounded queue full so the train loop never stalls on data; the queue depth
hot-reloads from the paral-config file.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator

import numpy as np

from dlrover_tpu.agent.config_tuner import ParalConfigReader
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


class ElasticDataset:
    """Sample-index stream: master-fed under the agent, local otherwise."""

    def __init__(self, dataset_size: int, *, name: str = "train",
                 shard_size: int = 256, num_epochs: int = 1,
                 shuffle: bool = True, under_agent: bool | None = None):
        self.dataset_size = dataset_size
        if under_agent is None:
            import os

            from dlrover_tpu.common.constants import EnvKey

            under_agent = bool(os.environ.get(EnvKey.MASTER_ADDR))
        self._client = None
        if under_agent:
            from dlrover_tpu.trainer.sharding_client import (
                IndexShardingClient,
            )

            self._client = IndexShardingClient(
                dataset_name=name,
                dataset_size=dataset_size,
                shard_size=shard_size,
                num_epochs=num_epochs,
                shuffle=shuffle,
            )
        self._num_epochs = num_epochs

    def indices(self) -> Iterator[int]:
        if self._client is not None:
            while True:
                idx = self._client.next_index()
                if idx is None:
                    return
                yield idx
        else:
            for _ in range(self._num_epochs):
                yield from range(self.dataset_size)

    def close(self) -> None:
        if self._client is not None:
            self._client.close()


class PrefetchLoader:
    """Background batch assembly with a bounded, hot-tunable queue.

    ``sample_fn(index) -> sample``; ``collate(list) -> dict of arrays``;
    batches come out shaped [accum, batch, ...] ready for the compiled
    step. Queue depth follows the paral-config ``prefetch_batches`` knob.
    """

    def __init__(
        self,
        dataset: ElasticDataset,
        sample_fn: Callable[[int], Any],
        collate: Callable[[list], dict[str, np.ndarray]],
        accum: int,
        batch_size: int,
        prefetch_batches: int = 2,
        config_reader: ParalConfigReader | None = None,
    ):
        self._dataset = dataset
        self._sample_fn = sample_fn
        self._collate = collate
        self._accum = accum
        self._batch_size = batch_size
        self._config = config_reader
        self._depth = max(1, prefetch_batches)
        # unbounded queue; depth is enforced by the producer's wait loop so
        # a hot-tuned larger target can actually take effect
        self._queue: queue.Queue = queue.Queue()
        self._stopped = threading.Event()
        self._error: BaseException | None = None
        self._thread = threading.Thread(
            target=self._fill, name="prefetch-loader", daemon=True
        )
        self._thread.start()

    def _target_depth(self) -> int:
        if self._config is not None:
            suggested = int(self._config.get("prefetch_batches", 0) or 0)
            if suggested > 0:
                return suggested
        return self._depth

    def _samples(self):
        for idx in self._dataset.indices():
            if self._stopped.is_set():
                return
            yield self._sample_fn(idx)

    def _fill(self) -> None:
        from dlrover_tpu.trainer.elastic_trainer import BatchAssembler

        assembler = BatchAssembler(self._accum, self._batch_size)
        try:
            for batch in assembler.batches(self._samples(), self._collate):
                while not self._stopped.is_set():
                    if self._queue.qsize() < self._target_depth():
                        self._queue.put(batch)
                        break
                    self._stopped.wait(0.05)
                if self._stopped.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 - surfaced to consumer
            self._error = e
            logger.exception("prefetch thread failed")
        finally:
            self._queue.put(None)

    def __iter__(self) -> Iterator[dict[str, np.ndarray]]:
        while True:
            batch = self._queue.get()
            if batch is None:
                if self._error is not None:
                    raise RuntimeError(
                        "prefetch failed"
                    ) from self._error
                return
            yield batch

    def close(self) -> None:
        # the producer only ever waits on _stopped (the queue is
        # unbounded), so set() fully unblocks it; draining the queue here
        # could steal the end-of-stream sentinel from a live consumer
        self._stopped.set()
        self._dataset.close()
