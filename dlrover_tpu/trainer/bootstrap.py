"""Trainer-process bring-up from the agent-provided environment.

Reference analog: torchelastic workers read RANK/WORLD_SIZE/MASTER_ADDR set
by the agent (dlrover/python/elastic_agent/torch/training.py worker env
assembly). TPU-natively the agent hands the JAX coordination service address
from the completed rendezvous and the trainer calls
``jax.distributed.initialize`` — after that every process sees the global
device set and a single ``Mesh`` spans hosts.
"""

from __future__ import annotations

import dataclasses
import os

from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


def exit_oom() -> None:
    """Report out-of-memory to the agent via the exit-code contract."""
    from dlrover_tpu.agent.failure_policy import EXIT_CODE_OOM

    os._exit(EXIT_CODE_OOM)


def exit_hardware_fault() -> None:
    """Report an unrecoverable chip/host fault: the agent escalates to node
    relaunch instead of restarting in place."""
    from dlrover_tpu.agent.failure_policy import EXIT_CODE_HARDWARE

    os._exit(EXIT_CODE_HARDWARE)


class failure_contract:
    """Context manager translating runtime faults to the exit-code contract.

    Wrap the training loop::

        with bootstrap.failure_contract():
            trainer.run(...)

    XLA RESOURCE_EXHAUSTED (HBM/host OOM) exits 210 so the agent reports
    OOM to the master's resource optimizer; everything else propagates and
    becomes a software error.
    """

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is None:
            return False
        text = f"{exc_type.__name__}: {exc}"
        if "RESOURCE_EXHAUSTED" in text or isinstance(exc, MemoryError):
            logger.error("out of memory: %s", text[:2000])
            exit_oom()
        return False


@dataclasses.dataclass
class RunContext:
    job_name: str = "local"
    node_id: int = 0
    node_rank: int = 0
    num_nodes: int = 1
    restart_count: int = 0
    coordinator: str = ""
    master_addr: str = ""
    under_agent: bool = False


def init_from_env(initialize_distributed: bool = True) -> RunContext:
    """Read the agent contract from env; multi-node: join the JAX cluster.

    Safe to call without an agent (standalone notebooks/benchmarks): returns
    a single-node context and skips ``jax.distributed.initialize``.

    ``DLROVER_TPU_PLATFORM`` forces the JAX platform (tests set ``cpu`` for
    hermetic multi-device runs) — a plain ``JAX_PLATFORMS`` env var loses to
    an eagerly registered TPU plugin, the live config does not.
    """
    platform = os.environ.get("DLROVER_TPU_PLATFORM")
    if platform:
        import jax

        try:
            jax.config.update("jax_platforms", platform)
        except RuntimeError:
            logger.warning("backend already initialized; cannot force %s",
                           platform)
    ctx = RunContext(
        job_name=os.environ.get(EnvKey.JOB_NAME, "local"),
        node_id=int(os.environ.get(EnvKey.NODE_ID, "0")),
        node_rank=int(os.environ.get(EnvKey.NODE_RANK, "0")),
        num_nodes=int(os.environ.get(EnvKey.NODE_NUM, "1")),
        restart_count=int(os.environ.get(EnvKey.RESTART_COUNT, "0")),
        coordinator=os.environ.get(EnvKey.COORDINATOR, ""),
        master_addr=os.environ.get(EnvKey.MASTER_ADDR, ""),
        under_agent=bool(os.environ.get(EnvKey.MASTER_ADDR)),
    )
    if initialize_distributed and ctx.num_nodes > 1 and ctx.coordinator:
        import jax

        logger.info(
            "joining jax cluster: rank %d/%d coordinator %s (restart %d)",
            ctx.node_rank, ctx.num_nodes, ctx.coordinator, ctx.restart_count,
        )
        jax.distributed.initialize(
            coordinator_address=ctx.coordinator,
            num_processes=ctx.num_nodes,
            process_id=ctx.node_rank,
        )
    return ctx
