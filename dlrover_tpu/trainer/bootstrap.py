"""Trainer-process bring-up from the agent-provided environment.

Reference analog: torchelastic workers read RANK/WORLD_SIZE/MASTER_ADDR set
by the agent (dlrover/python/elastic_agent/torch/training.py worker env
assembly). TPU-natively the agent hands the JAX coordination service address
from the completed rendezvous and the trainer calls
``jax.distributed.initialize`` — after that every process sees the global
device set and a single ``Mesh`` spans hosts.
"""

from __future__ import annotations

import dataclasses
import os

from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


def exit_oom() -> None:
    """Report out-of-memory to the agent via the exit-code contract."""
    from dlrover_tpu.agent.failure_policy import EXIT_CODE_OOM

    os._exit(EXIT_CODE_OOM)


def exit_hardware_fault() -> None:
    """Report an unrecoverable chip/host fault: the agent escalates to node
    relaunch instead of restarting in place."""
    from dlrover_tpu.agent.failure_policy import EXIT_CODE_HARDWARE

    os._exit(EXIT_CODE_HARDWARE)


class failure_contract:
    """Context manager translating runtime faults to the exit-code contract.

    Wrap the training loop::

        with bootstrap.failure_contract():
            trainer.run(...)

    XLA RESOURCE_EXHAUSTED (HBM/host OOM) exits 210 so the agent reports
    OOM to the master's resource optimizer; everything else propagates and
    becomes a software error.
    """

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is None:
            return False
        text = f"{exc_type.__name__}: {exc}"
        if "RESOURCE_EXHAUSTED" in text or isinstance(exc, MemoryError):
            logger.error("out of memory: %s", text[:2000])
            exit_oom()
        return False


@dataclasses.dataclass
class RunContext:
    job_name: str = "local"
    node_id: int = 0
    node_rank: int = 0
    num_nodes: int = 1
    restart_count: int = 0
    coordinator: str = ""
    master_addr: str = ""
    under_agent: bool = False


def setup_compilation_cache(path: str | None = None) -> str | None:
    """Point XLA's persistent compilation cache at a host-local dir.

    The goodput lever for elasticity x static compilation (SURVEY §7 hard
    parts): every restart-in-place re-traces the same program, and without
    this cache each incarnation pays the full XLA compile (tens of seconds
    to minutes at scale) before its first step. With it, a restarted
    process deserializes the executable in ~1s, so the per-failure cost is
    rendezvous + restore, not recompilation. The reference has no analog —
    torch re-executes eagerly — this cost class only exists under XLA, and
    this is its native fix.

    Default path is host-local (/tmp), keyed by job name: every
    incarnation, the parked standby, and a co-started serving replica
    of ONE job share a single cache dir (a per-process dir would
    silently re-pay every compile), while co-hosted jobs stay apart.
    ``DLROVER_TPU_COMPILE_CACHE_DIR`` pins the *location* only (shared
    NFS, ramdisk, pre-warmed image path) — the platform gating below
    still decides whether the XLA cache is safe to enable at all.
    ``DLROVER_TPU_COMPILE_CACHE`` keeps its stronger legacy meaning:
    an explicit dir there enables the cache anywhere. Either set to
    ``off`` disables.
    """
    import jax

    explicit = path or os.environ.get(EnvKey.COMPILE_CACHE_DIR)
    shared = os.environ.get(EnvKey.COMPILE_CACHE_SHARED_DIR)
    for v in (explicit, shared):
        if v and v.lower() in ("off", "none", "0"):
            return None
    if not explicit:
        # already configured (JAX_COMPILATION_CACHE_DIR env or caller):
        # don't override a deliberate per-job cache location
        if jax.config.jax_compilation_cache_dir:
            return jax.config.jax_compilation_cache_dir
        # XLA:CPU's AOT cache deserialization is unreliable
        # (machine-feature mismatch on load -> misexecuting executables
        # that wedge cross-device collectives; observed with jax 0.9).
        # The cache is a TPU-path feature, so the default requires a
        # POSITIVE TPU indicator — an env sniff for "not cpu" would
        # enable it on a bare CPU run with no platform env set at all.
        # (The backend itself can't be queried here: that would
        # initialize it before jax.distributed.initialize.)
        platform = (os.environ.get(EnvKey.PLATFORM)
                    or os.environ.get("JAX_PLATFORMS", "")).lower()
        if "cpu" in platform:
            return None  # explicitly CPU: never cache
        if not any(p in platform for p in ("tpu", "axon")):
            # TPU VMs usually leave JAX_PLATFORMS unset; libtpu being
            # importable is the positive indicator there
            import importlib.util

            if importlib.util.find_spec("libtpu") is None:
                return None
    job = os.environ.get(EnvKey.JOB_NAME, "") or "default"
    cache_dir = explicit or shared or os.path.join(
        "/tmp/dlrover_tpu_xla_cache", job)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast compiles: restart storms re-pay them N times
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except (RuntimeError, AttributeError) as e:
        logger.warning("compilation cache unavailable: %s", e)
        return None
    return cache_dir


def init_from_env(initialize_distributed: bool = True) -> RunContext:
    """Read the agent contract from env; multi-node: join the JAX cluster.

    Safe to call without an agent (standalone notebooks/benchmarks): returns
    a single-node context and skips ``jax.distributed.initialize``.

    ``DLROVER_TPU_PLATFORM`` forces the JAX platform (tests set ``cpu`` for
    hermetic multi-device runs) — a plain ``JAX_PLATFORMS`` env var loses to
    an eagerly registered TPU plugin, the live config does not.
    """
    platform = os.environ.get(EnvKey.PLATFORM)
    if platform:
        import jax

        try:
            jax.config.update("jax_platforms", platform)
        except RuntimeError:
            logger.warning("backend already initialized; cannot force %s",
                           platform)
    setup_compilation_cache()
    if os.environ.get(EnvKey.MASTER_ADDR):
        # arm the flight recorder's C-level SIGUSR2 stack dump
        # (telemetry/bundle.py): faulthandler dumps without the GIL, so
        # the agent can read this process's stacks even when it is
        # wedged inside a collective — the evidence a hang verdict's
        # debug bundle scoops up before the kill
        from dlrover_tpu.telemetry.bundle import arm_child_dump

        arm_child_dump()
    if os.environ.get(EnvKey.STANDBY_FILE):
        # warm-standby trainer (agent/standby.py): everything above —
        # interpreter + import graph, platform config, compile cache,
        # flight recorder — is pre-paid; park here until the agent
        # promotes this process with the rendezvous payload. The
        # accelerator backend and jax.distributed.initialize must wait
        # for promotion (chips are exclusive to the live trainer, and
        # the coordinator address only exists after rendezvous).
        from dlrover_tpu.agent.standby import park_if_standby

        park_if_standby()
    ctx = RunContext(
        job_name=os.environ.get(EnvKey.JOB_NAME, "local"),
        node_id=int(os.environ.get(EnvKey.NODE_ID, "0")),
        node_rank=int(os.environ.get(EnvKey.NODE_RANK, "0")),
        num_nodes=int(os.environ.get(EnvKey.NODE_NUM, "1")),
        restart_count=int(os.environ.get(EnvKey.RESTART_COUNT, "0")),
        coordinator=os.environ.get(EnvKey.COORDINATOR, ""),
        master_addr=os.environ.get(EnvKey.MASTER_ADDR, ""),
        under_agent=bool(os.environ.get(EnvKey.MASTER_ADDR)),
    )
    if initialize_distributed and ctx.num_nodes > 1 and ctx.coordinator:
        import jax

        logger.info(
            "joining jax cluster: rank %d/%d coordinator %s (restart %d)",
            ctx.node_rank, ctx.num_nodes, ctx.coordinator, ctx.restart_count,
        )
        init_kwargs = {}
        init_timeout = os.environ.get(EnvKey.INIT_TIMEOUT, "")
        if init_timeout:
            # launcher-scaled join timeout (run.py auto_configure): a
            # large fleet's restart storm outlives the 300 s default
            init_kwargs["initialization_timeout"] = int(float(init_timeout))
        jax.distributed.initialize(
            coordinator_address=ctx.coordinator,
            num_processes=ctx.num_nodes,
            process_id=ctx.node_rank,
            **init_kwargs,
        )
    return ctx
