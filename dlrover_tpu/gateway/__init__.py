"""Elastic serving gateway: replica pool + health-aware routing +
telemetry-driven autoscaling over ``serving.InferenceEngine``."""

from dlrover_tpu.gateway.autoscale import (  # noqa: F401
    DisaggAutoscaler,
    DisaggSignals,
    GatewayAutoscaler,
    GatewaySignals,
    p95_from_buckets,
)
from dlrover_tpu.gateway.control import MasterLink  # noqa: F401
from dlrover_tpu.gateway.pool import (  # noqa: F401
    EngineReplica,
    PoolScaler,
    ReplicaPool,
    ReplicaState,
    RequestWork,
)
from dlrover_tpu.gateway.router import Router, ShardRing  # noqa: F401
from dlrover_tpu.gateway.server import (  # noqa: F401
    AdmissionController,
    AdmissionError,
    Gateway,
    GatewayHTTPServer,
    GatewayResult,
)
from dlrover_tpu.serving import (  # noqa: F401
    InferenceEngine,
    KVBundle,
    PrefillEngine,
    PrefillResult,
    Request,
    Result,
    SamplingParams,
)
