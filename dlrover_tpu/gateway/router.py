"""Health-aware request routing: least-outstanding + prefix affinity.

The data-plane half of the Podracer actor-pool idea: a thin router over
N single-engine replicas scales decode throughput linearly — IF two
things hold. (1) Load balance: route to the replica with the fewest
outstanding requests, so no replica queues while another idles.
(2) Cache locality: each replica owns its own prefix KV cache
(serving/engine.py's chunk-aligned LRU), so prompts sharing an aligned
prefix should land on the replica that already holds that prefix's KV
rows — spraying them round-robin would re-prefill the shared system
prompt once per replica and hit on none.

Affinity is advisory, load is binding: the prefix owner is preferred
only while it has a free decode slot or is no busier than the
least-loaded alternative; a saturated owner loses the request to the
least-loaded replica (re-prefilling is cheaper than queueing behind a
full batch while slots idle elsewhere).

The affinity map mirrors the engine's cache-key discipline: keys are
FINAL chunk-aligned prefixes, lookups probe only stored key lengths
(bounded work on long prompts), and the map is LRU-bounded. Entries for
a dead replica are forgotten so affinity never routes to a ghost.
"""

from __future__ import annotations

import threading
from typing import Sequence

from dlrover_tpu.common.hashring import HashRing, hash_point
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


class Router:
    def __init__(self, prefill_len: int, *,
                 max_affinity_entries: int = 1024):
        if prefill_len < 1:
            raise ValueError("prefill_len must be >= 1")
        self._prefill_len = prefill_len
        self._max = max_affinity_entries
        self._lock = threading.Lock()
        # aligned-prefix key -> replica id, insertion-ordered (LRU)
        self._affinity: dict[tuple, int] = {}
        self._lens: dict[int, int] = {}  # key length -> stored count

    # ------------------------------------------------------------ routing

    def route(self, prompt: Sequence[int], replicas: Sequence):
        """Pick a replica for ``prompt`` from ``replicas`` (READY ones,
        objects with ``id`` / ``outstanding`` / ``slots``); None when
        the list is empty."""
        if not replicas:
            return None
        by_id = {r.id: r for r in replicas}
        least = min(replicas, key=lambda r: (r.outstanding, r.id))
        owner = self._affinity_owner(prompt, by_id)
        if owner is not None:
            busy = owner.outstanding
            if busy < owner.slots or busy <= least.outstanding:
                return owner
        return least

    def _affinity_owner(self, prompt: Sequence[int], by_id: dict):
        P = self._prefill_len
        top = len(prompt) // P * P
        with self._lock:
            for length in sorted(self._lens, reverse=True):
                if length > top:
                    continue
                rid = self._affinity.get(tuple(prompt[:length]))
                if rid is not None and rid in by_id:
                    return by_id[rid]
        return None

    # --------------------------------------------------------- bookkeeping

    def record(self, prompt: Sequence[int], replica_id: int) -> None:
        """Remember that ``replica_id`` now holds the KV rows for this
        prompt's final aligned prefix (call at dispatch time)."""
        P = self._prefill_len
        top = len(prompt) // P * P
        if not top:
            return
        key = tuple(prompt[:top])
        with self._lock:
            if self._affinity.pop(key, None) is None:
                self._lens[top] = self._lens.get(top, 0) + 1
            self._affinity[key] = replica_id
            while len(self._affinity) > self._max:
                evicted = next(iter(self._affinity))
                self._affinity.pop(evicted)
                self._dec_len(len(evicted))

    def forget(self, replica_id: int) -> None:
        """Drop every affinity entry owned by a detached replica."""
        with self._lock:
            dead = [k for k, rid in self._affinity.items()
                    if rid == replica_id]
            for key in dead:
                self._affinity.pop(key)
                self._dec_len(len(key))

    def _dec_len(self, length: int) -> None:
        left = self._lens[length] - 1
        if left:
            self._lens[length] = left
        else:
            del self._lens[length]


class ShardRing:
    """Prefix-affinity consistent hashing across GATEWAY shards.

    One gateway process tops out well before "millions of users"; a
    horizontal front tier must keep cache locality as it scales out.
    The key is the prompt's FIRST aligned chunk (the shared-system-
    prompt start): every prompt of a prefix family hashes to the same
    shard, so that shard's replicas accumulate the family's prefix KV
    — keying on the final aligned boundary would scatter a family
    across shards by total length. The ring itself is the shared
    ``common/hashring.HashRing`` (blake2s points, ``vnodes`` virtual
    points per shard — the same construction the embedding fabric's
    owner map uses): adding or removing a shard moves ~1/N of the
    keyspace instead of reshuffling everything, so a front-tier
    scale-out invalidates a bounded slice of cache locality.

    Thread-safe; shards are opaque ids (URL, pod name, index).
    """

    def __init__(self, prefill_len: int,
                 shards: Sequence[str] = (), *, vnodes: int = 64):
        if prefill_len < 1:
            raise ValueError("prefill_len must be >= 1")
        self._prefill_len = prefill_len
        self._ring = HashRing(shards, vnodes=vnodes)

    def _key(self, prompt: Sequence[int]) -> bytes:
        P = self._prefill_len
        head = tuple(prompt[:P]) if len(prompt) >= P else tuple(prompt)
        return ",".join(str(t) for t in head).encode()

    # ------------------------------------------------------------ membership

    def add_shard(self, shard: str) -> None:
        self._ring.add(shard)

    def remove_shard(self, shard: str) -> None:
        self._ring.remove(shard)

    def shards(self) -> list[str]:
        return self._ring.members()

    # --------------------------------------------------------------- routing

    def shard_for(self, prompt: Sequence[int]) -> str | None:
        """The shard owning this prompt's prefix family; None with no
        shards registered."""
        return self._ring.owner_of_point(hash_point(self._key(prompt)))
