"""Telemetry-driven replica autoscaling through the ScalePlan path.

The serving twin of ``master/auto_scaler.py``: a timer loop turns
telemetry into a ``ScalePlan`` and hands it to a ``Scaler`` (normally
``gateway.pool.PoolScaler``; a ``cluster/scaler.py`` PodScaler works
the same way when replicas are pods). Signals, all from the PR-1
telemetry registry via the gateway:

- queue depth (``dlrover_tpu_gateway_queue_depth``): admitted requests
  not yet completed;
- slot occupancy (``dlrover_tpu_gateway_slot_occupancy``): busy decode
  slots / total;
- p95 request latency, computed over the WINDOW since the previous tick
  by differencing cumulative ``dlrover_tpu_gateway_request_seconds``
  bucket counts (a cumulative p95 would take minutes to notice a
  regression the window sees immediately).

Policy (deliberately hysteretic — scale-up is one hot tick, scale-down
needs ``down_ticks`` consecutive cold ones, because a replica build
costs a prefill/install/step compile):

- UP when the queue is deeper than one full batch per live replica, or
  occupancy > ``up_occupancy``, or window p95 > ``target_p95_s``;
- DOWN when the queue is empty and occupancy < ``down_occupancy`` for
  ``down_ticks`` ticks;
- always emit a plan when live != target (a killed replica is restored
  on the next tick without waiting for load signals to notice).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Sequence

from dlrover_tpu.cluster.crd import ScalePlan
from dlrover_tpu.cluster.scaler import Scaler
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_scale_events = registry().counter(
    "dlrover_tpu_gateway_scale_events_total",
    "autoscaler plans issued, by direction and pool",
    label_names=("direction", "pool"),
)


def p95_from_buckets(bounds: Sequence[float],
                     bucket_counts: Sequence[int]) -> float:
    """p95 estimate from histogram bucket deltas: the upper bound of
    the bucket holding the 95th percentile (conservative; +Inf bucket
    reports the largest finite bound)."""
    total = sum(bucket_counts)
    if not total:
        return 0.0
    rank = 0.95 * total
    cumulative = 0
    for i, n in enumerate(bucket_counts):
        cumulative += n
        if cumulative >= rank:
            return float(bounds[i]) if i < len(bounds) \
                else float(bounds[-1])
    return float(bounds[-1])


@dataclasses.dataclass
class GatewaySignals:
    """One tick's view of the serving telemetry (windowed p95 already
    computed — ``GatewayAutoscaler.tick`` does the differencing)."""

    queue_depth: int
    slot_occupancy: float
    p95_s: float
    live: int
    slots_per_replica: int = 8


class GatewayAutoscaler:
    def __init__(self, gateway, scaler: Scaler, *,
                 min_replicas: int = 1, max_replicas: int = 4,
                 interval_s: float = 2.0,
                 target_p95_s: float = 0.0,
                 up_occupancy: float = 0.85,
                 down_occupancy: float = 0.3,
                 down_ticks: int = 3,
                 group: str = "serving",
                 signals_fn: Callable[[], GatewaySignals] | None = None):
        if min_replicas < 0 or max_replicas < min_replicas:
            raise ValueError("need 0 <= min_replicas <= max_replicas")
        self._gateway = gateway
        self._scaler = scaler
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self._interval_s = interval_s
        self.target_p95_s = target_p95_s  # 0 = latency signal off
        self._up_occupancy = up_occupancy
        self._down_occupancy = down_occupancy
        self._down_ticks = down_ticks
        self._group = group
        self._signals_fn = signals_fn
        self.target: int | None = None  # adopted from `live` on tick 1
        self._cold_streak = 0
        self._prev_buckets: list[int] | None = None
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "GatewayAutoscaler":
        self._thread = threading.Thread(
            target=self._loop, name="gateway-autoscaler", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - planning must not die
                logger.exception("gateway autoscale tick failed")

    # ------------------------------------------------------------- signals

    def _signals(self) -> GatewaySignals:
        if self._signals_fn is not None:
            return self._signals_fn()
        gw = self._gateway
        bounds, buckets, _count, _sum = gw.request_hist_snapshot()
        prev = self._prev_buckets or [0] * len(buckets)
        delta = [max(0, b - p) for b, p in zip(buckets, prev)]
        self._prev_buckets = buckets
        slots_total = gw.pool.slots_total()
        live = gw.pool.live_count()
        return GatewaySignals(
            queue_depth=gw.admission.pending,
            slot_occupancy=gw.pool.occupancy(),
            p95_s=p95_from_buckets(bounds, delta),
            live=live,
            slots_per_replica=max(1, slots_total // max(1, live)),
        )

    # ------------------------------------------------------------ decision

    def decide(self, sig: GatewaySignals) -> int:
        """Pure policy: next replica target from one tick's signals."""
        if self.target is None:
            self.target = min(self.max_replicas,
                              max(self.min_replicas, sig.live))
        hot = (
            sig.queue_depth > sig.slots_per_replica * max(1, sig.live)
            or sig.slot_occupancy > self._up_occupancy
            or (self.target_p95_s > 0
                and sig.p95_s > self.target_p95_s)
        )
        cold = (sig.queue_depth == 0
                and sig.slot_occupancy < self._down_occupancy)
        if hot:
            self._cold_streak = 0
            self.target = min(self.max_replicas, self.target + 1)
        elif cold:
            self._cold_streak += 1
            if self._cold_streak >= self._down_ticks:
                self._cold_streak = 0
                self.target = max(self.min_replicas, self.target - 1)
        else:
            self._cold_streak = 0
        return self.target

    def tick(self) -> None:
        sig = self._signals()
        before = self.target
        target = self.decide(sig)
        if before is not None and target != before:
            direction = "up" if target > before else "down"
            _scale_events.labels(direction, "serving").inc()
            logger.info(
                "gateway scale %s: %d -> %d (queue=%d occ=%.2f "
                "p95=%.2fs)", direction, before, target,
                sig.queue_depth, sig.slot_occupancy, sig.p95_s,
            )
        elif sig.live < target:
            # a replica died (kill/preempt): restore the count even
            # though load signals alone wouldn't trigger a plan
            _scale_events.labels("restore", "serving").inc()
            logger.warning("gateway restore: %d live < target %d",
                           sig.live, target)
        elif sig.live == target:
            return
        self._scaler.scale(ScalePlan(
            job_name="gateway",
            replica_resources={self._group: target},
            reason=f"gateway autoscale (live={sig.live}, "
                   f"queue={sig.queue_depth}, "
                   f"occ={sig.slot_occupancy:.2f}, "
                   f"p~{sig.p95_s:.2f}s)",
        ))


# --------------------------------------------------- disaggregated pools


@dataclasses.dataclass
class DisaggSignals:
    """One tick's view of a disaggregated gateway: the PREFILL pool is
    sized by its prompt backlog, the DECODE pool by slot occupancy /
    admitted queue — two different saturation modes that must not
    thrash against one shared signal."""

    prefill_backlog: int       # prompts queued/in-flight in prefill pool
    prefill_live: int
    decode_queue: int          # bundles awaiting a decode slot
    decode_occupancy: float
    decode_live: int
    slots_per_replica: int = 8
    p95_s: float = 0.0


class _PoolPolicy:
    """Per-pool hysteresis: up on one hot tick, down only after
    ``down_ticks`` consecutive cold ones (a replica build compiles)."""

    def __init__(self, min_replicas: int, max_replicas: int,
                 down_ticks: int):
        if min_replicas < 0 or max_replicas < min_replicas:
            raise ValueError("need 0 <= min_replicas <= max_replicas")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self._down_ticks = down_ticks
        self._cold_streak = 0
        self.target: int | None = None

    def decide(self, hot: bool, cold: bool, live: int) -> int:
        if self.target is None:
            self.target = min(self.max_replicas,
                              max(self.min_replicas, live))
        if hot:
            self._cold_streak = 0
            self.target = min(self.max_replicas, self.target + 1)
        elif cold:
            self._cold_streak += 1
            if self._cold_streak >= self._down_ticks:
                self._cold_streak = 0
                self.target = max(self.min_replicas, self.target - 1)
        else:
            self._cold_streak = 0
        return self.target


class DisaggAutoscaler:
    """Scale prefill and decode pools independently through one
    ScalePlan: ``replica_resources={"prefill": P, "decode": D}``,
    executed by each pool's ``PoolScaler`` (group "prefill" /
    "decode"). Prefill-bound load (deep prompt backlog, idle decode
    slots) grows only the prefill pool; decode-bound load (high slot
    occupancy, empty prefill queue) grows only the decode pool.
    """

    def __init__(self, gateway, prefill_scaler: Scaler,
                 decode_scaler: Scaler, *,
                 min_prefill: int = 1, max_prefill: int = 4,
                 min_decode: int = 1, max_decode: int = 4,
                 interval_s: float = 2.0,
                 target_p95_s: float = 0.0,
                 up_occupancy: float = 0.85,
                 down_occupancy: float = 0.3,
                 backlog_per_prefill: float = 2.0,
                 down_ticks: int = 3,
                 signals_fn: Callable[[], DisaggSignals] | None = None):
        self._gateway = gateway
        self._prefill_scaler = prefill_scaler
        self._decode_scaler = decode_scaler
        self._interval_s = interval_s
        self.target_p95_s = target_p95_s
        self._up_occupancy = up_occupancy
        self._down_occupancy = down_occupancy
        self._backlog_per_prefill = backlog_per_prefill
        self.prefill_policy = _PoolPolicy(min_prefill, max_prefill,
                                          down_ticks)
        self.decode_policy = _PoolPolicy(min_decode, max_decode,
                                         down_ticks)
        self._signals_fn = signals_fn
        self._prev_buckets: list[int] | None = None
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "DisaggAutoscaler":
        self._thread = threading.Thread(
            target=self._loop, name="gateway-disagg-autoscaler",
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 - planning must not die
                logger.exception("disagg autoscale tick failed")

    # ------------------------------------------------------------- signals

    def _signals(self) -> DisaggSignals:
        if self._signals_fn is not None:
            return self._signals_fn()
        gw = self._gateway
        bounds, buckets, _count, _sum = gw.request_hist_snapshot()
        prev = self._prev_buckets or [0] * len(buckets)
        delta = [max(0, b - p) for b, p in zip(buckets, prev)]
        self._prev_buckets = buckets
        wait_prefill, wait_decode = gw.undispatched_counts()
        slots_total = gw.pool.slots_total()
        decode_live = gw.pool.live_count()
        return DisaggSignals(
            prefill_backlog=(gw.prefill_pool.outstanding_total()
                             + wait_prefill),
            prefill_live=gw.prefill_pool.live_count(),
            decode_queue=wait_decode,
            decode_occupancy=gw.pool.occupancy(),
            decode_live=decode_live,
            slots_per_replica=max(
                1, slots_total // max(1, decode_live)),
            p95_s=p95_from_buckets(bounds, delta),
        )

    # ------------------------------------------------------------ decision

    def decide(self, sig: DisaggSignals) -> tuple[int, int]:
        """Pure policy: (prefill target, decode target)."""
        prefill_hot = (
            sig.prefill_backlog
            > self._backlog_per_prefill * max(1, sig.prefill_live)
        )
        prefill_cold = sig.prefill_backlog == 0
        decode_hot = (
            sig.decode_occupancy > self._up_occupancy
            or sig.decode_queue
            > sig.slots_per_replica * max(1, sig.decode_live)
            or (self.target_p95_s > 0
                and sig.p95_s > self.target_p95_s)
        )
        decode_cold = (sig.decode_queue == 0
                       and sig.decode_occupancy < self._down_occupancy)
        return (
            self.prefill_policy.decide(prefill_hot, prefill_cold,
                                       sig.prefill_live),
            self.decode_policy.decide(decode_hot, decode_cold,
                                      sig.decode_live),
        )

    def tick(self) -> None:
        sig = self._signals()
        before = (self.prefill_policy.target, self.decode_policy.target)
        pt, dt = self.decide(sig)
        changed = False
        for name, prev, target, live in (
            ("prefill", before[0], pt, sig.prefill_live),
            ("decode", before[1], dt, sig.decode_live),
        ):
            if prev is not None and target != prev:
                direction = "up" if target > prev else "down"
                _scale_events.labels(direction, name).inc()
                logger.info("gateway %s pool scale %s: %d -> %d",
                            name, direction, prev, target)
                changed = True
            elif live < target:
                _scale_events.labels("restore", name).inc()
                logger.warning("gateway %s pool restore: %d live < "
                               "target %d", name, live, target)
                changed = True
        if not changed and (sig.prefill_live, sig.decode_live) == (pt, dt):
            return
        plan = ScalePlan(
            job_name="gateway",
            replica_resources={"prefill": pt, "decode": dt},
            reason=f"disagg autoscale (prefill backlog="
                   f"{sig.prefill_backlog}, decode occ="
                   f"{sig.decode_occupancy:.2f}, "
                   f"queue={sig.decode_queue})",
        )
        self._prefill_scaler.scale(plan)
        self._decode_scaler.scale(plan)
