"""Gateway front door: admission control, dispatch, stdlib HTTP.

The data plane. A request's life::

    HTTP POST /v1/generate  (or Gateway.submit from Python)
      -> admission: estimated wait vs deadline, 429 + Retry-After past it
      -> seed minting: results are a function of (params, prompt,
         sampling, seed) — never of which replica serves them
      -> router: least-outstanding-slots with prefix-cache affinity
      -> replica decode thread (gateway/pool.py) -> Future resolves

Admission bound derivation: with ``p`` requests pending (queued +
in-flight), EWMA per-request service time ``s`` and ``S`` decode slots
across READY replicas, a new request waits ~``p*s/S`` before its decode
finishes. Admission holds that estimate under ``deadline_s``; the
implied queue bound is ``deadline_s * S / s`` requests, so the bound
tracks capacity (grows when the autoscaler adds replicas, shrinks when
requests get longer) instead of being a magic constant. Rejections
carry ``Retry-After`` sized to when the backlog is expected to fit
again — open-loop clients get backpressure they can obey rather than a
timeout they discover.

A replica kill mid-decode costs latency, not correctness: the pool
hands the dead replica's unfinished work back and the gateway re-routes
it; minted seeds make the re-decode identical to what the dead replica
would have produced.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as FutureTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional, Sequence

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.gateway.pool import ReplicaPool, RequestWork
from dlrover_tpu.gateway.router import Router
from dlrover_tpu.serving import SamplingParams
from dlrover_tpu.telemetry.exposition import CONTENT_TYPE, render
from dlrover_tpu.telemetry.journal import (
    current_trace_id,
    format_ctx,
    get_journal,
    mint_span_id,
    should_sample,
)
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_requests_total = registry().counter(
    "dlrover_tpu_gateway_requests_total",
    "gateway requests by outcome code (200/429/500)",
    label_names=("code",),
)
_request_seconds = registry().histogram(
    "dlrover_tpu_gateway_request_seconds",
    "submit -> completion latency per gateway request",
    label_names=("finish",),
)
_queue_seconds = registry().histogram(
    "dlrover_tpu_gateway_queue_seconds",
    "admission -> replica-dispatch wait per request",
)
_queue_depth = registry().gauge(
    "dlrover_tpu_gateway_queue_depth",
    "requests admitted and not yet completed",
)
_resubmitted_total = registry().counter(
    "dlrover_tpu_gateway_resubmitted_total",
    "requests re-routed after an abrupt replica death",
)
_embedding_lookups_total = registry().counter(
    "dlrover_tpu_gateway_embedding_lookups_total",
    "embedding-route lookups by outcome code (200/400/503)",
    label_names=("code",),
)


class AdmissionError(RuntimeError):
    """Backpressure: retry after ``retry_after_s`` (HTTP 429)."""

    def __init__(self, retry_after_s: float, message: str):
        super().__init__(message)
        self.retry_after_s = retry_after_s


@dataclasses.dataclass
class GatewayResult:
    id: int
    tokens: list[int]
    finish_reason: str
    replica_id: int
    attempts: int
    total_s: float
    queue_s: float
    prefill_s: float
    decode_s: float
    # monotonic arrival stamp per token (the bench derives TTFT and
    # inter-token-latency percentiles from these)
    token_times: list = dataclasses.field(default_factory=list)


class AdmissionController:
    """Deadline-derived bounded queue (see module docstring for the
    bound's derivation)."""

    def __init__(self, deadline_s: float = 30.0,
                 init_request_s: float = 0.5,
                 ewma_alpha: float = 0.2):
        self.deadline_s = deadline_s
        self._alpha = ewma_alpha
        self._ewma_s = init_request_s
        self._pending = 0
        self._lock = threading.Lock()

    @property
    def pending(self) -> int:
        return self._pending

    @property
    def ewma_request_s(self) -> float:
        return self._ewma_s

    def estimated_wait_s(self, slots_total: int) -> float:
        with self._lock:
            return self._pending * self._ewma_s / max(1, slots_total)

    def try_admit(self, slots_total: int) -> None:
        """Admit or raise ``AdmissionError`` with a Retry-After."""
        with self._lock:
            est_wait = (self._pending * self._ewma_s
                        / max(1, slots_total))
            if est_wait > self.deadline_s:
                retry = max(1.0, est_wait - self.deadline_s)
                raise AdmissionError(
                    retry, f"estimated wait {est_wait:.1f}s exceeds "
                           f"deadline {self.deadline_s:.1f}s "
                           f"({self._pending} pending)",
                )
            self._pending += 1
            _queue_depth.set(self._pending)

    def release(self, service_s: float | None = None) -> None:
        with self._lock:
            self._pending = max(0, self._pending - 1)
            _queue_depth.set(self._pending)
            if service_s is not None:
                self._ewma_s += self._alpha * (service_s - self._ewma_s)


class Gateway:
    """Pool + router + admission behind one ``submit``.

    ``engine_factory`` builds one ``serving.InferenceEngine`` per
    replica (runs on the replica's thread); ``prefill_len`` must match
    the engines' chunk size so router affinity keys line up with the
    engines' prefix-cache keys.

    ``prefill_replicas > 0`` disaggregates: a PREFILL pool
    (``serving.PrefillEngine`` replicas) runs prompts and ships
    page-granular KV bundles; the main pool becomes the DECODE pool
    and installs bundles via ``submit_prefilled``. Prefix affinity
    routes to the prefill pool (that's where the prefix caches live);
    decode dispatch is pure least-outstanding. The two pools scale
    independently (``DisaggAutoscaler``) — and because the minted seed,
    chunk program and install path are identical, a request's tokens
    are bit-identical to the unified path.
    """

    def __init__(self, engine_factory, *, replicas: int = 1,
                 prefill_len: int = 64,
                 prefill_replicas: int = 0,
                 prefill_engine_factory=None,
                 admission_deadline_s: float = 30.0,
                 init_request_s: float = 0.5,
                 dispatch_timeout_s: float = 120.0,
                 seed: int = 0,
                 preemption_file: str | None = None,
                 health_interval_s: float = 0.5):
        self.router = Router(prefill_len)
        self.admission = AdmissionController(
            deadline_s=admission_deadline_s,
            init_request_s=init_request_s,
        )
        self.disaggregated = prefill_replicas > 0
        self.pool = ReplicaPool(
            engine_factory, self._on_done, self._resubmit,
            on_error=self._fail,
            health_interval_s=health_interval_s,
            preemption_file=preemption_file,
            name="decode" if self.disaggregated else "serving",
        )
        self.prefill_pool = None
        if self.disaggregated:
            from dlrover_tpu.serving import PrefillEngine

            factory = prefill_engine_factory or (
                lambda: PrefillEngine(engine_factory())
            )
            self.prefill_pool = ReplicaPool(
                factory, self._on_prefilled, self._resubmit,
                on_error=self._fail,
                health_interval_s=health_interval_s,
                preemption_file=preemption_file,
                name="prefill",
            )
        self._seed = seed
        # set by gateway.control.MasterLink when a master is attached
        self.master_link = None
        self._dispatch_timeout_s = dispatch_timeout_s
        self._ids_lock = threading.Lock()
        self._next_id = 0
        self._undispatched: deque[RequestWork] = deque()
        self._undispatched_lock = threading.Lock()
        self._stop = threading.Event()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop, name="gateway-dispatcher",
            daemon=True,
        )
        self._dispatcher.start()
        self.pool.ensure(replicas)
        if self.prefill_pool is not None:
            self.prefill_pool.ensure(prefill_replicas)

    # ----------------------------------------------------------- user API

    def submit(self, prompt: Sequence[int],
               params: SamplingParams | None = None) -> Future:
        """Admit + dispatch; returns a Future[GatewayResult]. Raises
        ``AdmissionError`` (429) past the backpressure bound."""
        params = params or SamplingParams()
        try:
            self.admission.try_admit(self.pool.slots_total())
        except AdmissionError:
            _requests_total.labels("429").inc()
            raise
        with self._ids_lock:
            rid = self._next_id
            self._next_id += 1
        if params.seed is None:
            params = dataclasses.replace(
                params, seed=self._mint_seed(rid)
            )
        work = RequestWork(
            id=rid, prompt=list(prompt), params=params,
            future=Future(), submit_t=time.monotonic(),
        )
        if get_journal().enabled and should_sample(f"req:{rid}"):
            # pre-mint the trace root (§27): the prefill/decode engines
            # attach children under it while the request is in flight;
            # the retroactive gateway_request point reuses this id
            work.span_id = mint_span_id("gateway_request")
            work.sctx = format_ctx(current_trace_id(), work.span_id)
        if not self._try_dispatch(work):
            with self._undispatched_lock:
                self._undispatched.append(work)
        return work.future

    def generate(self, prompt: Sequence[int],
                 params: SamplingParams | None = None,
                 timeout: float | None = None) -> GatewayResult:
        return self.submit(prompt, params).result(timeout)

    def stats(self) -> dict:
        states = [r.state.value for r in self.pool.replicas()]
        # §29: pool-wide observatory aggregate (health-tick product) +
        # the prefix-cache hit rate across every pool this gateway runs
        obs = dict(self.pool.observatory or {})
        hits = obs.get("prefix_cache_hits", 0)
        queries = obs.get("prefix_cache_queries", 0)
        if self.prefill_pool is not None:
            pf_obs = self.prefill_pool.observatory or {}
            hits += pf_obs.get("prefix_cache_hits", 0)
            queries += pf_obs.get("prefix_cache_queries", 0)
        hit_rate = round(hits / queries, 4) if queries else 0.0
        if self.prefill_pool is not None:
            pf = self.prefill_pool
            return {
                "prefix_cache_hit_rate": hit_rate,
                "serving_observatory": obs,
                "degraded": bool(self.master_link is not None
                                 and self.master_link.degraded),
                "disaggregated": True,
                "replicas": {s: states.count(s) for s in set(states)},
                "ready": len(self.pool.ready_replicas()),
                "prefill_ready": len(pf.ready_replicas()),
                "prefill_backlog": pf.outstanding_total(),
                "slots_total": self.pool.slots_total(),
                "slot_occupancy": round(self.pool.occupancy(), 4),
                "queue_depth": self.admission.pending,
                "ewma_request_s": round(
                    self.admission.ewma_request_s, 4),
                "estimated_wait_s": round(
                    self.admission.estimated_wait_s(
                        self.pool.slots_total()
                    ), 4,
                ),
            }
        return {
            "degraded": bool(self.master_link is not None
                             and self.master_link.degraded),
            "prefix_cache_hit_rate": hit_rate,
            "serving_observatory": obs,
            "replicas": {s: states.count(s) for s in set(states)},
            "ready": len(self.pool.ready_replicas()),
            "slots_total": self.pool.slots_total(),
            "slot_occupancy": round(self.pool.occupancy(), 4),
            "queue_depth": self.admission.pending,
            "ewma_request_s": round(self.admission.ewma_request_s, 4),
            "estimated_wait_s": round(
                self.admission.estimated_wait_s(
                    self.pool.slots_total()
                ), 4,
            ),
        }

    def undispatched_counts(self) -> tuple[int, int]:
        """(awaiting-prefill, awaiting-decode) requests no replica has
        accepted yet — the disaggregated autoscaler's backlog split."""
        with self._undispatched_lock:
            pre = sum(1 for w in self._undispatched
                      if w.bundle is None)
            return pre, len(self._undispatched) - pre

    def request_hist_snapshot(self) -> tuple[tuple[float, ...], list[int],
                                             int, float]:
        """(bounds, per-bucket counts incl +Inf, count, sum) of the
        request-latency histogram, merged over finish labels — the
        autoscaler's p95 source."""
        bounds = _request_seconds.buckets
        merged = [0] * (len(bounds) + 1)
        count, total = 0, 0.0
        for sample in _request_seconds.samples():
            for i, n in enumerate(sample["buckets"]):
                merged[i] += n
            count += sample["count"]
            total += sample["sum"]
        return bounds, merged, count, total

    def stop(self) -> None:
        self._stop.set()
        if self.prefill_pool is not None:
            self.prefill_pool.stop()
        self.pool.stop()
        with self._undispatched_lock:
            pending, self._undispatched = list(self._undispatched), deque()
        for work in pending:
            self._fail(work, RuntimeError("gateway stopped"))

    # ----------------------------------------------------------- dispatch

    def _mint_seed(self, rid: int) -> int:
        # a request's continuation must not depend on which replica
        # serves it (or re-serves it after a kill): derive the sampling
        # seed from (gateway seed, request id) so every engine decodes
        # the identical stream
        digest = hashlib.blake2s(
            f"{self._seed}:{rid}".encode(), digest_size=4
        ).digest()
        return int.from_bytes(digest, "big")

    def _try_dispatch(self, work: RequestWork) -> bool:
        if self.prefill_pool is not None and work.bundle is None:
            # disaggregated: prefix affinity targets the PREFILL pool
            # (its engines own the prefix caches the affinity exists
            # for); the bundle comes back through _on_prefilled
            replica = self.router.route(
                work.prompt, self.prefill_pool.ready_replicas()
            )
            if replica is None or not replica.submit(work):
                return False
            self.router.record(work.prompt, replica.id)
            return True
        if self.prefill_pool is not None:
            # decode dispatch: the KV arrives with the bundle, so pure
            # least-outstanding beats any affinity
            replicas = self.pool.ready_replicas()
            if not replicas:
                return False
            replica = min(replicas,
                          key=lambda r: (r.outstanding, r.id))
            return replica.submit(work)
        replica = self.router.route(
            work.prompt, self.pool.ready_replicas()
        )
        if replica is None or not replica.submit(work):
            return False
        self.router.record(work.prompt, replica.id)
        return True

    def _on_prefilled(self, work: RequestWork, res: Any) -> None:
        """Prefill-pool completion hook: attach the KV bundle and hand
        the request to the decode pool."""
        work.prefill_done_t = time.monotonic()
        work.bundle = res.bundle
        if not self._try_dispatch(work):
            with self._undispatched_lock:
                self._undispatched.append(work)

    def _dispatch_loop(self) -> None:
        # retries work that found no READY replica (all starting, or a
        # kill emptied the pool until the autoscaler restores it)
        while not self._stop.wait(0.05):
            with self._undispatched_lock:
                pending = list(self._undispatched)
                self._undispatched.clear()
            for work in pending:
                if self._stop.is_set():
                    break
                age = time.monotonic() - work.submit_t
                if age > self._dispatch_timeout_s:
                    self._fail(work, RuntimeError(
                        f"request {work.id} undispatchable for "
                        f"{age:.0f}s (no serving replica)"
                    ))
                elif not self._try_dispatch(work):
                    with self._undispatched_lock:
                        self._undispatched.append(work)

    def _resubmit(self, orphans: list[RequestWork]) -> None:
        """Pool hook: a replica died abruptly with this work unfinished."""
        _resubmitted_total.inc(len(orphans))
        for work in orphans:
            self.router.forget(work.replica_id)
            work.attempts += 1
            work.first_token_t = 0.0
            work.token_times = []
            work.decode_dispatch_t = 0.0
            if work.bundle is None:
                work.prefill_done_t = 0.0
            with self._undispatched_lock:
                self._undispatched.append(work)

    # -------------------------------------------------------- completion

    def _on_done(self, work: RequestWork, res: Any) -> None:
        done_t = time.monotonic()
        total = done_t - work.submit_t
        queue_s = max(0.0, work.dispatch_t - work.submit_t)
        first = work.first_token_t or done_t
        prefill_s = max(0.0, first - work.dispatch_t)
        decode_s = max(0.0, done_t - first)
        self.admission.release(done_t - work.dispatch_t)
        _requests_total.labels("200").inc()
        _request_seconds.labels(res.finish_reason).observe(total)
        _queue_seconds.observe(queue_s)
        self._journal_request(work, res, done_t)
        if not work.future.done():
            work.future.set_result(GatewayResult(
                id=work.id, tokens=list(res.tokens),
                finish_reason=res.finish_reason,
                replica_id=work.replica_id, attempts=work.attempts,
                total_s=total, queue_s=queue_s, prefill_s=prefill_s,
                decode_s=decode_s,
                token_times=list(work.token_times),
            ))

    def _journal_request(self, work: RequestWork, res: Any,
                         done_t: float) -> None:
        """Retroactive causal tree of one finished request (§27): the
        pre-minted ``gateway_request`` root plus phase children placed
        at their true wall times, so the phase durations exactly tile
        [submit, done] and ``telemetry/trace.py`` can decompose TTFT.
        Skipped entirely when the request was head-sampled out."""
        journal = get_journal()
        if not journal.enabled or not work.span_id:
            return
        now_wall = time.time()

        def wall(mono: float) -> float:
            # monotonic stamp -> the wall time the same instant had
            return round(now_wall - (done_t - mono), 6)

        total = done_t - work.submit_t
        first = work.first_token_t or done_t
        parent = journal.emit(
            "gateway_request", dur=total, rid=work.id,
            replica=work.replica_id, attempts=work.attempts,
            finish=res.finish_reason, tokens=len(res.tokens),
            span_id=work.span_id, disagg=work.bundle is not None,
        )
        journal.emit("gateway_queue", parent=parent,
                     dur=max(0.0, work.dispatch_t - work.submit_t),
                     t=wall(work.dispatch_t))
        journal.emit("gateway_route", parent=parent, dur=0.0,
                     replica=work.replica_id, t=wall(work.dispatch_t))
        if work.bundle is not None and work.prefill_done_t:
            # disaggregated TTFT: prefill chunks, bundle handoff +
            # decode-pool queue, then install-to-first-token
            decode_disp = work.decode_dispatch_t or work.prefill_done_t
            journal.emit(
                "gateway_prefill", parent=parent,
                dur=max(0.0, work.prefill_done_t - work.dispatch_t),
                t=wall(work.prefill_done_t))
            journal.emit(
                "gateway_handoff", parent=parent,
                dur=max(0.0, decode_disp - work.prefill_done_t),
                t=wall(decode_disp))
            journal.emit(
                "gateway_decode_first", parent=parent,
                dur=max(0.0, first - decode_disp), t=wall(first))
        else:
            journal.emit(
                "gateway_prefill", parent=parent,
                dur=max(0.0, first - work.dispatch_t), t=wall(first))
        journal.emit("gateway_decode", parent=parent,
                     dur=max(0.0, done_t - first), t=wall(done_t))

    def _fail(self, work: RequestWork, exc: Exception) -> None:
        self.admission.release()
        _requests_total.labels("500").inc()
        if not work.future.done():
            work.future.set_exception(exc)


class GatewayHTTPServer:
    """JSON-over-HTTP front door on ``ThreadingHTTPServer``.

    - ``POST /v1/generate``: ``{"prompt": [ids], "max_new_tokens"?,
      "temperature"?, "top_k"?, "top_p"?, "eos_id"?, "seed"?}`` ->
      ``{"id", "tokens", "finish_reason", "replica", "attempts"}``;
      429 + ``Retry-After`` under backpressure.
    - ``POST /v1/embedding/lookup`` (with ``embedding_client``):
      ``{"ids": [[...]]}`` -> ``{"values", "version",
      "applied_version", "staleness"}`` — rows served from the LIVE
      training ring through a read-only, version-pinned fabric client
      (DESIGN.md §25); missing ids score as zero vectors, never
      materialize rows. 503 while the ring is unreachable.
    - ``GET /healthz``: replica/queue summary; 503 with no READY replica.
    - ``GET /metrics``: Prometheus text (``dlrover_tpu_gateway_*`` et al).

    ``gateway`` may be None for an embedding-only front door (the
    recsys serving example): the generate route then answers 503.
    """

    def __init__(self, gateway: Optional[Gateway], *,
                 host: str = "0.0.0.0", port: int = 0,
                 request_timeout_s: float = 300.0,
                 embedding_client=None):
        outer = self
        self.gateway = gateway
        self.embedding_client = embedding_client
        self._request_timeout_s = request_timeout_s

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:  # no per-request spam
                pass

            def _json(self, code: int, payload: dict,
                      headers: dict | None = None) -> None:
                body = json.dumps(payload).encode("utf-8")
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _embedding_lookup(self) -> None:
                client = outer.embedding_client
                if client is None:
                    _embedding_lookups_total.labels("503").inc()
                    self._json(503, {"error": "no embedding ring "
                               "attached to this gateway"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length))
                    ids = req["ids"]
                    if not isinstance(ids, list) or not ids:
                        raise ValueError("ids must be a non-empty list")
                except (KeyError, TypeError, ValueError,
                        json.JSONDecodeError) as e:
                    _embedding_lookups_total.labels("400").inc()
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                try:
                    import numpy as np

                    values, info = client.lookup_with_info(
                        np.asarray(ids, dtype=np.int64),
                        init_missing=False,
                    )
                except Exception as e:  # noqa: BLE001 - report to client
                    _embedding_lookups_total.labels("503").inc()
                    self._json(503, {
                        "error": f"{type(e).__name__}: {e}",
                    })
                    return
                _embedding_lookups_total.labels("200").inc()
                self._json(200, {
                    "values": values.tolist(),
                    "version": info["version"],
                    "applied_version": info["applied_version"],
                    "staleness": info["staleness"],
                })

            def do_GET(self) -> None:  # noqa: N802 - stdlib API
                path = self.path.split("?")[0]
                if path == "/healthz":
                    if outer.gateway is None:
                        ok = outer.embedding_client is not None
                        self._json(200 if ok else 503, {
                            "ready": ok,
                            "status": "embedding_only" if ok
                            else "no_backends",
                        })
                        return
                    stats = outer.gateway.stats()
                    code = 200 if stats["ready"] else 503
                    stats["status"] = "ok" if stats["ready"] else "no_replicas"
                    self._json(code, stats)
                elif path == "/metrics":
                    body = render().encode("utf-8")
                    self.send_response(200)
                    self.send_header("Content-Type", CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def do_POST(self) -> None:  # noqa: N802 - stdlib API
                path = self.path.split("?")[0]
                if path == "/v1/embedding/lookup":
                    self._embedding_lookup()
                    return
                if path not in ("/v1/generate", "/generate"):
                    self.send_error(404)
                    return
                if outer.gateway is None:
                    self._json(503, {"error": "no decode backend "
                               "(embedding-only gateway)"})
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(length))
                    prompt = [int(t) for t in req["prompt"]]
                    if not prompt:
                        raise ValueError("empty prompt")
                    params = SamplingParams(
                        temperature=float(req.get("temperature", 1.0)),
                        top_k=int(req.get("top_k", 0)),
                        top_p=float(req.get("top_p", 1.0)),
                        max_new_tokens=int(req.get("max_new_tokens", 64)),
                        eos_id=(int(req["eos_id"])
                                if req.get("eos_id") is not None else None),
                        seed=(int(req["seed"])
                              if req.get("seed") is not None else None),
                    )
                except (KeyError, TypeError, ValueError,
                        json.JSONDecodeError) as e:
                    self._json(400, {"error": f"bad request: {e}"})
                    return
                try:
                    result = outer.gateway.generate(
                        prompt, params, timeout=outer._request_timeout_s
                    )
                except AdmissionError as e:
                    self._json(429, {
                        "error": str(e),
                        "retry_after_s": round(e.retry_after_s, 1),
                    }, headers={
                        "Retry-After": str(int(e.retry_after_s + 0.999)),
                    })
                    return
                except (FutureTimeout, TimeoutError):
                    self._json(504, {"error": "generation timed out"})
                    return
                except Exception as e:  # noqa: BLE001 - report to client
                    self._json(500, {"error": f"{type(e).__name__}: {e}"})
                    return
                self._json(200, {
                    "id": result.id,
                    "tokens": result.tokens,
                    "finish_reason": result.finish_reason,
                    "replica": result.replica_id,
                    "attempts": result.attempts,
                })

        class _Server(ThreadingHTTPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, port), _Handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> "GatewayHTTPServer":
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="gateway-http",
            daemon=True,
        )
        self._thread.start()
        logger.info("gateway HTTP front door on port %d", self.port)
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
