"""Gateway <-> master control-plane link with graceful degradation.

The gateway's data plane (admission, routing, decode) is deliberately
self-contained; this link is the OPTIONAL control-plane coupling — it
pushes the gateway's metrics snapshot to the job master on a heartbeat
cadence (so the master's one-scrape ``/metrics`` covers serving too)
and pulls a desired replica target from the master KV store, applying
it through the same ``ScalePlan`` path the autoscaler uses.

Degradation contract: when the master becomes unreachable the gateway
KEEPS SERVING with its last-known replica pool and last-applied target —
control-plane loss must never fail data-plane requests. The transition
is observable: a ``degraded_mode`` journal instant on enter/exit and
the ``dlrover_tpu_gateway_degraded`` gauge (1 while degraded) for
alerting. Control actions simply resume when the master returns.

Since §26 the enter/exit/re-dial machinery is the shared
``agent/master_link.py`` core (this was its prototype); the gateway
keeps its documented unlabeled gauge and its kv-target tick.
"""

from __future__ import annotations

import threading

from dlrover_tpu.agent.master_link import MasterLink as _DegradedLink
from dlrover_tpu.cluster.crd import ScalePlan
from dlrover_tpu.cluster.scaler import Scaler
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_degraded_gauge = registry().gauge(
    "dlrover_tpu_gateway_degraded",
    "1 while the gateway serves without a reachable master",
)


class MasterLink(_DegradedLink):
    """Heartbeat loop binding a ``Gateway`` to a job master.

    ``client`` is an ``agent.master_client.MasterClient`` (or anything
    with ``report_metrics``/``kv_get``); ``scaler`` (optional) receives
    a ScalePlan when the master's ``kv_key`` names a new replica
    target. The loop never raises: every master error flips the link
    into degraded mode and the next successful tick flips it back.
    """

    def __init__(self, gateway, client, *, scaler: Scaler | None = None,
                 interval_s: float = 5.0,
                 kv_key: str = "gateway/replica_target",
                 group: str = "serving"):
        super().__init__(client, component="gateway",
                         gauge=_degraded_gauge)
        self._gateway = gateway
        self._scaler = scaler
        self._interval_s = interval_s
        self._kv_key = kv_key
        self._group = group
        self._last_target: int | None = None
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        if gateway is not None:
            gateway.master_link = self

    # ----------------------------------------------------------- lifecycle

    def start(self) -> "MasterLink":
        self._thread = threading.Thread(
            target=self._loop, name="gateway-master-link", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stopped.set()

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval_s):
            self.tick()

    # ---------------------------------------------------------------- tick

    def tick(self) -> None:
        try:
            self._client.report_metrics(registry().snapshot(),
                                        role="gateway")
            raw = self._client.kv_get(self._kv_key)
        except (ConnectionError, RuntimeError, OSError) as e:
            self.failed(e)
            if self.stale():
                # mirrored scale target is past the staleness bound
                # (§30): forget it, so a post-recovery target is always
                # re-read from the master and re-applied fresh rather
                # than deduplicated against pre-outage state
                self._last_target = None
            return
        self.ok()
        if not raw:
            return
        try:
            target = int(raw.decode("utf-8").strip())
        except (ValueError, UnicodeDecodeError):
            logger.warning("ignoring malformed %s value %r",
                           self._kv_key, raw[:64])
            return
        if self._scaler is not None and target != self._last_target:
            self._last_target = target
            self._scaler.scale(ScalePlan(
                job_name="gateway",
                replica_resources={self._group: target},
                reason=f"master kv target ({self._kv_key})",
            ))
