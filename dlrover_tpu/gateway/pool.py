"""Serving replica pool: the gateway's control plane.

Reference analog: Podracer/vLLM actor pools — a thin coordinator over
many single-accelerator engines — crossed with DLRover's node manager:
replicas are health-checked, drained on preemption notice, and resized
through the SAME ``ScalePlan`` verb training uses (``PoolScaler`` is
the serving twin of ``cluster/scaler.py``'s node scalers).

Each ``EngineReplica`` owns one ``serving.InferenceEngine`` on a
dedicated decode thread (the engine is strictly single-threaded; the
replica thread is the only thread that ever touches it). Lifecycle::

    STARTING --engine built--> READY --drain()--> DRAINING --empty--> DEAD
                                 |---kill()/thread death------------> DEAD

- ``drain()`` (graceful: preemption notice, scale-down) stops accepting
  new work but finishes every in-flight decode before detaching — the
  preemption contract from ``agent/preemption.py``: the platform
  announces the kill, so the notice window is spent finishing, not
  failing.
- ``kill()`` (abrupt: test/bench injection, or a decode thread dying)
  returns the queued + in-flight work so the gateway can resubmit it to
  surviving replicas; per-request seeds (minted by the gateway) make
  the re-decode bit-identical, so a mid-load replica loss costs latency
  only, never a failed or divergent request.

The pool's health loop detaches dead replicas, hands their orphans to
the gateway's resubmit hook, and keeps the ``dlrover_tpu_gateway_*``
replica/occupancy gauges fresh; the autoscaler reads those and drives
``PoolScaler.scale`` to restore or resize.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from enum import Enum
from typing import Any, Callable

from dlrover_tpu.agent.preemption import PreemptionWatcher
from dlrover_tpu.cluster.crd import ScalePlan
from dlrover_tpu.cluster.scaler import Scaler
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_replicas_gauge = registry().gauge(
    "dlrover_tpu_gateway_replicas",
    "replica count by lifecycle state and pool "
    "(serving, or prefill/decode when disaggregated)",
    label_names=("state", "pool"),
)
_slot_occupancy = registry().gauge(
    "dlrover_tpu_gateway_slot_occupancy",
    "busy fraction of decode slots across READY replicas, per pool",
    label_names=("pool",),
)
_drained_total = registry().counter(
    "dlrover_tpu_gateway_drained_total",
    "replicas drained, by cause and pool",
    label_names=("cause", "pool"),
)
# serving-observatory aggregates (DESIGN.md §29): the health tick rolls
# every ready replica's last kv_pool sample into one scrape surface per
# pool, like the master metrics path — scrapers never fan out to
# replicas
_kv_free_gauge = registry().gauge(
    "dlrover_tpu_gateway_kv_pages_free",
    "KV pool pages free across READY replicas, per pool",
    label_names=("pool",),
)
_kv_used_gauge = registry().gauge(
    "dlrover_tpu_gateway_kv_pages_used",
    "KV pool pages leased across READY replicas, per pool",
    label_names=("pool",),
)
_kv_occupancy_gauge = registry().gauge(
    "dlrover_tpu_gateway_kv_occupancy",
    "leased fraction of the pool-wide KV page pool",
    label_names=("pool",),
)
_shareable_frac_gauge = registry().gauge(
    "dlrover_tpu_gateway_pages_shareable_frac",
    "fraction of live full pages shareable across slots (copy-on-write "
    "headroom), pool-wide",
    label_names=("pool",),
)
_accept_rate_gauge = registry().gauge(
    "dlrover_tpu_gateway_draft_accept_rate",
    "shadow-predictor acceptance rate across READY replicas "
    "(speculative-decoding headroom), per pool",
    label_names=("pool",),
)
_prefix_hit_rate_gauge = registry().gauge(
    "dlrover_tpu_gateway_prefix_cache_hit_rate",
    "prefix-cache hit fraction across READY replicas, per pool",
    label_names=("pool",),
)
_cow_saved_frac_gauge = registry().gauge(
    "dlrover_tpu_gateway_cow_pages_saved_frac",
    "fraction of the pool-wide live logical KV pages served by "
    "copy-on-write sharing instead of a fresh lease (§31 realized, "
    "vs the §29-predicted shareable headroom)",
    label_names=("pool",),
)
_spec_rate_live_gauge = registry().gauge(
    "dlrover_tpu_gateway_spec_accept_rate_live",
    "live speculative-decode draft acceptance across READY replicas, "
    "per pool (§31 realized, vs the §29 shadow prior)",
    label_names=("pool",),
)


class ReplicaState(str, Enum):
    STARTING = "starting"
    READY = "ready"
    DRAINING = "draining"
    DEAD = "dead"


@dataclasses.dataclass
class RequestWork:
    """One gateway request as it moves through (possibly several)
    replicas; the future resolves exactly once, with a
    ``server.GatewayResult``."""

    id: int
    prompt: list[int]
    params: Any                  # serving.SamplingParams, seed minted
    future: Future
    submit_t: float
    dispatch_t: float = 0.0
    first_token_t: float = 0.0
    replica_id: int = -1
    attempts: int = 0
    # disaggregated serving: the prefill pool's KV handoff product;
    # None routes to the prefill pool (or straight to a unified
    # replica), non-None routes to the decode pool
    bundle: Any = None
    # per-token arrival stamps (the bench's inter-token-latency p95
    # source); reset with first_token_t on resubmission
    token_times: list = dataclasses.field(default_factory=list)
    # causal trace (§27): pre-minted root span id + trace:span context
    # ("" = head-sampled out); the engines this request touches attach
    # their admit/handoff/prefill spans under the root before the
    # gateway writes its retroactive gateway_request point
    span_id: str = ""
    sctx: str = ""
    # disaggregated TTFT decomposition stamps (monotonic clock)
    prefill_done_t: float = 0.0
    decode_dispatch_t: float = 0.0


class EngineReplica:
    """One InferenceEngine behind an inbox, on its own decode thread.

    ``engine_factory`` runs ON the replica thread (engine construction
    compiles the prefill/install/step programs; doing it off the caller
    keeps pool scale-up non-blocking), after which the replica turns
    READY and starts draining its inbox through ``engine.step()``.
    """

    def __init__(self, replica_id: int,
                 engine_factory: Callable[[], Any],
                 on_done: Callable[[RequestWork, Any], None],
                 *, on_error: Callable[[RequestWork, Exception],
                                       None] | None = None,
                 heartbeat_timeout_s: float = 60.0):
        self.id = replica_id
        self._engine_factory = engine_factory
        self._on_done = on_done
        self._on_error = on_error or (
            lambda work, exc: work.future.set_exception(exc)
        )
        self._heartbeat_timeout_s = heartbeat_timeout_s
        self._lock = threading.Lock()
        self._wake = threading.Condition(self._lock)
        self._state = ReplicaState.STARTING
        self._inbox: list[RequestWork] = []
        self._inflight: dict[int, RequestWork] = {}  # engine rid -> work
        self._draining = False
        self._killed = False
        self._last_beat = time.monotonic()
        self.engine: Any = None
        self.slots = 0
        self._thread = threading.Thread(
            target=self._run, name=f"gateway-replica-{replica_id}",
            daemon=True,
        )
        self._thread.start()

    # ------------------------------------------------------------- state

    @property
    def state(self) -> ReplicaState:
        return self._state

    @property
    def outstanding(self) -> int:
        with self._lock:
            return len(self._inbox) + len(self._inflight)

    def healthy(self) -> bool:
        """Thread alive and stepping recently; False is the health
        loop's signal to detach and resubmit."""
        if self._state is ReplicaState.DEAD:
            return False
        if not self._thread.is_alive():
            return False
        return (time.monotonic() - self._last_beat
                < self._heartbeat_timeout_s)

    # -------------------------------------------------------------- verbs

    def submit(self, work: RequestWork) -> bool:
        """Accept work unless draining/dead; False tells the router to
        pick someone else."""
        with self._lock:
            if (self._killed or self._draining
                    or self._state is ReplicaState.DEAD):
                return False
            self._inbox.append(work)
            self._wake.notify()
        return True

    def drain(self) -> None:
        """Graceful: no new work, finish in-flight, then DEAD."""
        with self._lock:
            if self._state is ReplicaState.DEAD:
                return
            self._draining = True
            if self._state is ReplicaState.READY:
                self._state = ReplicaState.DRAINING
            self._wake.notify()

    def kill(self) -> list[RequestWork]:
        """Abrupt death (injection / simulated preempt-without-notice):
        stop stepping now, hand back everything unfinished."""
        with self._lock:
            self._killed = True
            self._state = ReplicaState.DEAD
            orphans = self._inbox + list(self._inflight.values())
            self._inbox = []
            self._inflight = {}
            self._wake.notify()
        return orphans

    def take_orphans(self) -> list[RequestWork]:
        """Reclaim unfinished work from a replica whose thread died on
        its own (health-loop path; ``kill()`` covers the injected one)."""
        with self._lock:
            self._state = ReplicaState.DEAD
            orphans = self._inbox + list(self._inflight.values())
            self._inbox = []
            self._inflight = {}
        return orphans

    def join(self, timeout: float | None = None) -> None:
        self._thread.join(timeout)

    # --------------------------------------------------------- decode loop

    def _run(self) -> None:
        try:
            engine = self._engine_factory()
        except Exception:  # noqa: BLE001 - a failed build is a dead replica
            logger.exception("replica %d engine build failed", self.id)
            with self._lock:
                self._state = ReplicaState.DEAD
            return
        aot = self._warm_engine(engine)
        with self._lock:
            if self._killed:
                return
            self.engine = engine
            self.slots = engine.slots
            self._state = ReplicaState.READY
        # the compile_cache evidence of this replica's cold start: a
        # later replica of the same (model, slots, max_len) reads
        # hit=True here — its cold compile became an executable load
        get_journal().emit(
            "gateway_replica_ready", replica=self.id,
            aot=aot is not None,
            aot_hit=bool(aot.cache_hit) if aot else False,
            aot_source=aot.source if aot else "",
            aot_seconds=aot.seconds if aot else 0.0,
        )
        logger.info("replica %d ready (%d slots)", self.id, self.slots)
        while True:
            with self._lock:
                while (not self._inbox and not self._inflight
                       and not self._killed and not self._draining):
                    self._last_beat = time.monotonic()
                    self._wake.wait(0.2)
                if self._killed:
                    return
                if (self._draining and not self._inbox
                        and not self._inflight):
                    self._state = ReplicaState.DEAD
                    logger.info("replica %d drained", self.id)
                    return
                newly, self._inbox = self._inbox, []
            for work in newly:
                if not work.dispatch_t:
                    # first dispatch only: for disaggregated requests
                    # the prefill dispatch starts the service clock and
                    # the decode dispatch must not reset it
                    work.dispatch_t = time.monotonic()
                if work.bundle is not None and not work.decode_dispatch_t:
                    work.decode_dispatch_t = time.monotonic()
                work.replica_id = self.id
                # sctx only when sampled: fake/minimal engines in tests
                # that predate the kwarg stay callable
                extra = {"sctx": work.sctx} if work.sctx else {}
                try:
                    if work.bundle is not None:
                        rid = engine.submit_prefilled(
                            work.prompt, work.params,
                            bundle=work.bundle,
                            on_token=self._token_cb(work), **extra,
                        )
                    else:
                        rid = engine.submit(
                            work.prompt, work.params,
                            on_token=self._token_cb(work), **extra,
                        )
                except Exception as e:  # noqa: BLE001 - a bad request
                    # (prompt too long etc.) fails ITS future only
                    self._on_error(work, e)
                    continue
                with self._lock:
                    self._inflight[rid] = work
            engine.step()
            self._last_beat = time.monotonic()
            for res in engine.poll_results():
                with self._lock:
                    work = self._inflight.pop(res.id, None)
                if work is None:
                    # killed mid-step: this result's work was orphaned
                    # and resolves via resubmission elsewhere
                    continue
                try:
                    self._on_done(work, res)
                except Exception:  # noqa: BLE001 - a completion-hook bug
                    logger.exception(  # must not kill the decode loop
                        "on_done failed (request %d)", work.id
                    )

    @staticmethod
    def _token_cb(work: RequestWork):
        def cb(_rid: int, _tok: int) -> None:
            now = time.monotonic()
            if not work.first_token_t:
                work.first_token_t = now
            work.token_times.append(now)
        return cb

    def _warm_engine(self, engine: Any):
        """Route the replica cold start through the elastic compile
        cache (``parallel/compile_cache.load_or_compile``): the decode
        step — the program every request pays for — is loaded from any
        earlier replica's publish instead of cold-compiled. Off with
        ``DLROVER_TPU_AOT_CACHE=0`` or for engines without the hook."""
        from dlrover_tpu.common import envspec
        from dlrover_tpu.common.constants import EnvKey

        warm = getattr(engine, "warm_aot_step", None)
        if warm is None or not envspec.get_bool(EnvKey.AOT_CACHE):
            return None
        try:
            out = warm()
        except Exception:  # noqa: BLE001 - warming is best-effort
            logger.exception("replica %d AOT warmup failed", self.id)
            return None
        # spec-enabled engines also pre-arm the per-depth verify ladder
        # (§31) — same cache, same best-effort contract
        warm_v = getattr(engine, "warm_aot_verify", None)
        if warm_v is not None:
            try:
                warm_v()
            except Exception:  # noqa: BLE001 - warming is best-effort
                logger.exception(
                    "replica %d verify AOT warmup failed", self.id)
        return out


class ReplicaPool:
    """Replica set + health loop + preemption watchers.

    ``on_orphans`` (the gateway's resubmit hook) receives the
    unfinished work of any replica that dies abruptly; drained replicas
    never orphan anything by construction.
    """

    def __init__(self, engine_factory: Callable[[], Any],
                 on_done: Callable[[RequestWork, Any], None],
                 on_orphans: Callable[[list[RequestWork]], None],
                 *, on_error: Callable[[RequestWork, Exception],
                                       None] | None = None,
                 health_interval_s: float = 0.5,
                 preemption_file: str | None = None,
                 heartbeat_timeout_s: float = 60.0,
                 name: str = "serving"):
        # the metrics `pool` label: "serving" for a unified pool,
        # "prefill"/"decode" for the disaggregated pair
        self.name = name
        self._engine_factory = engine_factory
        self._on_done = on_done
        self._on_orphans = on_orphans
        self._on_error = on_error
        self._preemption_file = preemption_file
        self._heartbeat_timeout_s = heartbeat_timeout_s
        self._lock = threading.Lock()
        # serializes ensure()/scale reconciles (autoscaler tick vs a
        # direct PoolScaler call must not both spawn for the same gap)
        self._reconcile_lock = threading.Lock()
        self._replicas: dict[int, EngineReplica] = {}
        self._watchers: dict[int, PreemptionWatcher] = {}
        self._next_id = 0
        # pool-wide §29 observatory aggregate, refreshed by the health
        # tick; the gateway's stats()/healthz payload reads it
        self.observatory: dict = {}
        self._stop = threading.Event()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="gateway-pool-health",
            daemon=True,
        )
        self._health_interval_s = health_interval_s
        self._health_thread.start()

    # ----------------------------------------------------------- queries

    def replicas(self) -> list[EngineReplica]:
        with self._lock:
            return list(self._replicas.values())

    def ready_replicas(self) -> list[EngineReplica]:
        return [r for r in self.replicas()
                if r.state is ReplicaState.READY]

    def live_count(self) -> int:
        """Replicas counting toward the scale target (STARTING+READY);
        DRAINING ones are already on their way out."""
        return sum(
            r.state in (ReplicaState.STARTING, ReplicaState.READY)
            for r in self.replicas()
        )

    def slots_total(self) -> int:
        return sum(r.slots for r in self.ready_replicas())

    def occupancy(self) -> float:
        busy = total = 0
        for r in self.ready_replicas():
            total += r.slots
            busy += min(r.outstanding, r.slots)
        return busy / total if total else 0.0

    def outstanding_total(self) -> int:
        """Queued + in-flight work across live replicas (the
        disaggregated autoscaler's prefill-backlog signal)."""
        return sum(r.outstanding for r in self.replicas()
                   if r.state is not ReplicaState.DEAD)

    # ------------------------------------------------------------- verbs

    def ensure(self, target: int) -> None:
        """Reconcile live replica count toward ``target`` (grow by
        spawning, shrink by draining the newest)."""
        target = max(0, int(target))
        with self._reconcile_lock:
            with self._lock:
                live = [
                    r for r in self._replicas.values()
                    if r.state in (ReplicaState.STARTING,
                                   ReplicaState.READY)
                ]
            while len(live) < target:
                live.append(self._add_replica())
            for replica in sorted(live, key=lambda r: r.id,
                                  reverse=True)[: len(live) - target]:
                self.drain_replica(replica.id, cause="scale_down")

    def _add_replica(self) -> EngineReplica:
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            replica = EngineReplica(
                rid, self._engine_factory, self._on_done,
                on_error=self._on_error,
                heartbeat_timeout_s=self._heartbeat_timeout_s,
            )
            self._replicas[rid] = replica
            # arm the preemption notice for THIS replica: {node_id} in
            # the configured file template becomes the replica id, the
            # same substitution the agent watcher does per node
            watcher = PreemptionWatcher(
                lambda rid=rid: self.drain_replica(
                    rid, cause="preemption"
                ),
                node_id=rid, poll_interval_s=0.1,
                notice_file=self._preemption_file,
            ).start()
            if watcher.enabled:
                self._watchers[rid] = watcher
        get_journal().emit("gateway_replica_add", replica=rid)
        return replica

    def drain_replica(self, replica_id: int, cause: str = "drain") -> None:
        with self._lock:
            replica = self._replicas.get(replica_id)
        if replica is None or replica.state is ReplicaState.DEAD:
            return
        logger.warning("draining replica %d (%s)", replica_id, cause)
        _drained_total.labels(cause, self.name).inc()
        get_journal().emit("gateway_replica_drain", replica=replica_id,
                           cause=cause)
        replica.drain()

    def kill_replica(self, replica_id: int) -> int:
        """Abrupt-death injection (tests/bench): detach now, resubmit
        the orphans; returns how many requests were orphaned."""
        with self._lock:
            replica = self._replicas.pop(replica_id, None)
            watcher = self._watchers.pop(replica_id, None)
        if replica is None:
            return 0
        if watcher is not None:
            watcher.stop()
        orphans = replica.kill()
        logger.warning("replica %d killed with %d in-flight requests",
                       replica_id, len(orphans))
        get_journal().emit("gateway_replica_kill", replica=replica_id,
                           orphans=len(orphans))
        if orphans:
            self._on_orphans(orphans)
        return len(orphans)

    def relaunch_replica(self, replica_id: int) -> None:
        """ScalePlan relaunch verb: drain the named replica and bring
        up a replacement (fresh id — replica ids are engine
        incarnations, never reused)."""
        self.drain_replica(replica_id, cause="relaunch")
        self._add_replica()

    def stop(self) -> None:
        self._stop.set()
        with self._lock:
            replicas = list(self._replicas.values())
            watchers = list(self._watchers.values())
            self._replicas.clear()
            self._watchers.clear()
        for watcher in watchers:
            watcher.stop()
        for replica in replicas:
            orphans = replica.kill()
            if orphans:
                # hand unfinished work back so the gateway can fail the
                # futures explicitly — a silent kill would leave callers
                # blocked on results that can never arrive
                self._on_orphans(orphans)

    # -------------------------------------------------------- health loop

    def _health_loop(self) -> None:
        while not self._stop.wait(self._health_interval_s):
            try:
                self._health_tick()
            except Exception:  # noqa: BLE001 - health must keep running
                logger.exception("pool health tick failed")

    def _health_tick(self) -> None:
        with self._lock:
            replicas = list(self._replicas.items())
        for rid, replica in replicas:
            if replica.state is ReplicaState.DEAD or not replica.healthy():
                with self._lock:
                    self._replicas.pop(rid, None)
                    watcher = self._watchers.pop(rid, None)
                if watcher is not None:
                    watcher.stop()
                orphans = replica.take_orphans()
                if orphans:
                    logger.warning(
                        "replica %d died with %d unfinished requests; "
                        "resubmitting", rid, len(orphans),
                    )
                    self._on_orphans(orphans)
        counts = dict.fromkeys(ReplicaState, 0)
        for replica in self.replicas():
            counts[replica.state] += 1
        for state, n in counts.items():
            _replicas_gauge.labels(state.value, self.name).set(n)
        _slot_occupancy.labels(self.name).set(self.occupancy())
        self.observatory = self._observatory_tick()

    def _observatory_tick(self) -> dict:
        """Roll every READY replica's last observatory sample (plus its
        prefix-cache counters) into the pool-wide §29 aggregate and
        refresh the gateway gauges. Ratios are weighted by each
        replica's denominators, never averaged over averages."""
        hits = queries = 0
        free = used = total = high_water = 0
        sh_pages = sh_total = 0
        accepted = scored = 0
        run_p95 = run_p50 = 0
        sampled = 0
        cow_saved = cow_shared = cow_breaks = 0
        spec_acc = spec_scored = spec_extra = spec_steps = 0
        for replica in self.ready_replicas():
            eng = replica.engine
            hits += int(getattr(eng, "prefix_cache_hits", 0) or 0)
            queries += int(getattr(eng, "prefix_cache_queries", 0) or 0)
            # §31 live counters come straight off the replica surface —
            # they must aggregate even between observatory samples
            cow_saved += int(getattr(eng, "cow_pages_saved", 0) or 0)
            cow_shared += int(
                getattr(eng, "cow_pages_shared_total", 0) or 0)
            cow_breaks += int(getattr(eng, "cow_breaks_total", 0) or 0)
            spec_acc += int(getattr(eng, "spec_drafts_accepted", 0) or 0)
            spec_scored += int(
                getattr(eng, "spec_drafts_scored", 0) or 0)
            spec_extra += int(
                getattr(eng, "spec_extra_tokens_total", 0) or 0)
            spec_steps += int(getattr(eng, "spec_steps_total", 0) or 0)
            snap_fn = getattr(eng, "observatory_snapshot", None)
            snap = snap_fn() if snap_fn is not None else None
            if not snap:
                continue
            sampled += 1
            free += snap.get("free", 0)
            used += snap.get("used", 0)
            total += snap.get("total", 0)
            high_water += snap.get("high_water", 0)
            sh_pages += snap.get("shareable_pages", 0)
            sh_total += snap.get("total_pages", 0)
            accepted += snap.get("accepted", 0)
            scored += snap.get("scored", 0)
            run_p50 = max(run_p50, snap.get("accept_run_p50", 0))
            run_p95 = max(run_p95, snap.get("accept_run_p95", 0))
        agg = {
            "replicas_sampled": sampled,
            "kv_pages_free": free,
            "kv_pages_used": used,
            "kv_pages_total": total,
            "kv_pages_high_water": high_water,
            "kv_occupancy": round(used / total, 4) if total else 0.0,
            "pages_shareable_frac": (
                round(sh_pages / sh_total, 4) if sh_total else 0.0),
            "draft_accept_rate": (
                round(accepted / scored, 4) if scored else 0.0),
            "draft_tokens_scored": scored,
            "accept_run_p50": run_p50,
            "accept_run_p95": run_p95,
            "prefix_cache_hits": hits,
            "prefix_cache_queries": queries,
            "prefix_cache_hit_rate": (
                round(hits / queries, 4) if queries else 0.0),
            # §31 realized COW/spec facts (0 when the levers are off)
            "cow_pages_saved": cow_saved,
            "cow_pages_saved_frac": (
                round(cow_saved / (used + cow_saved), 4)
                if used + cow_saved else 0.0),
            "cow_pages_shared_total": cow_shared,
            "cow_breaks_total": cow_breaks,
            "spec_accept_rate_live": (
                round(spec_acc / spec_scored, 4) if spec_scored
                else 0.0),
            "spec_drafts_scored": spec_scored,
            "spec_extra_tokens_total": spec_extra,
            "spec_verify_steps_total": spec_steps,
        }
        _kv_free_gauge.labels(self.name).set(free)
        _kv_used_gauge.labels(self.name).set(used)
        _kv_occupancy_gauge.labels(self.name).set(agg["kv_occupancy"])
        _shareable_frac_gauge.labels(self.name).set(
            agg["pages_shareable_frac"])
        _accept_rate_gauge.labels(self.name).set(
            agg["draft_accept_rate"])
        _prefix_hit_rate_gauge.labels(self.name).set(
            agg["prefix_cache_hit_rate"])
        _cow_saved_frac_gauge.labels(self.name).set(
            agg["cow_pages_saved_frac"])
        _spec_rate_live_gauge.labels(self.name).set(
            agg["spec_accept_rate_live"])
        return agg


class PoolScaler(Scaler):
    """Execute ScalePlans against a ReplicaPool.

    The serving twin of ``cluster/scaler.py``'s node scalers: the
    gateway autoscaler (and any operator emitting ScalePlan CRs) drives
    replica count through this one verb, so serving elasticity rides
    the exact control-plane path training elasticity does.
    """

    def __init__(self, pool: ReplicaPool, group: str = "serving"):
        self._pool = pool
        self._group = group

    def scale(self, plan: ScalePlan) -> None:
        for rid in plan.remove_nodes:
            self._pool.drain_replica(rid, cause="scale_down")
        for rid in plan.relaunch_nodes:
            self._pool.relaunch_replica(rid)
        target = plan.replica_resources.get(self._group)
        if target is not None:
            logger.info("scaling %s replicas to %d (%s)", self._group,
                        target, plan.reason or "plan")
            self._pool.ensure(int(target))
