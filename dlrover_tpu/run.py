"""``python -m dlrover_tpu.run`` — the elastic launch CLI.

Reference analog: the ``dlrover-run`` console script
(dlrover/trainer/torch/elastic_run.py:124 parse_args, :230
_launch_dlrover_local_master, :322 run): a torchrun-superset launcher that
optionally spawns a local master (``--standalone``), then runs the elastic
agent supervising the training script. TPU differences: one training process
per host (JAX owns all local chips), and the rendezvous yields a JAX
coordination-service address instead of a TCPStore.

Usage:
    python -m dlrover_tpu.run --standalone --max-restarts 3 \
        train.py --my-flag ...
    python -m dlrover_tpu.run --master-addr 10.0.0.2:5001 --node-id 3 \
        --nnodes 4:8 train.py ...
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import time

from dlrover_tpu.agent.elastic_agent import AgentConfig, RunResult, launch_agent
from dlrover_tpu.common.constants import Defaults, EnvKey
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


def parse_args(argv: list[str] | None = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        "dlrover-tpu run", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument(
        "--standalone", action="store_true",
        help="spawn a local job master (single-host dev mode)",
    )
    p.add_argument("--master-addr", default="",
                   help="job master host:port (cluster mode)")
    p.add_argument("--job-name", default="local")
    p.add_argument("--node-id", type=int,
                   default=int(os.environ.get(EnvKey.NODE_ID, "0")))
    p.add_argument(
        "--nnodes", default="1",
        help="N or MIN:MAX node range for the elastic rendezvous",
    )
    p.add_argument("--node-unit", type=int, default=1,
                   help="world size must be a multiple of this")
    p.add_argument("--max-restarts", type=int, default=Defaults.MAX_RESTARTS)
    p.add_argument("--rdzv-timeout", type=float,
                   default=Defaults.RDZV_WAIT_TIMEOUT_S)
    p.add_argument("--monitor-interval", type=float,
                   default=Defaults.MONITOR_INTERVAL_S)
    p.add_argument("--heartbeat-interval", type=float,
                   default=Defaults.HEARTBEAT_INTERVAL_S,
                   help="agent->master heartbeat (and master-action "
                        "delivery) cadence")
    p.add_argument("--auto-config", action="store_true",
                   help="derive devices/network-check/comm timeouts from "
                        "the environment (reference: --auto-config)")
    p.add_argument("--network-check", action="store_true",
                   help="run a collective probe before training")
    p.add_argument("--exclude-straggler", action="store_true",
                   help="with --network-check: also exclude slow nodes")
    p.add_argument("--no-save-on-failure", action="store_true",
                   help="skip the breakpoint checkpoint persist on restart")
    p.add_argument("--hang-timeout", type=float, default=0.0,
                   help="restart the trainer when its step stops "
                        "advancing for this many seconds (0 disables)")
    p.add_argument("--hang-startup-grace", type=float, default=600.0,
                   help="per-spawn grace before hang detection arms "
                        "(covers XLA compilation)")
    p.add_argument("--host-ip", default="127.0.0.1")
    p.add_argument("--topology-key", default="",
                   help="rank-sorting key (TPU slice/host position)")
    p.add_argument("training_script", help="script (or module via -m inside)")
    p.add_argument("training_args", nargs=argparse.REMAINDER)
    return p.parse_args(argv)


def parse_nnodes(spec: str) -> tuple[int, int]:
    if ":" in spec:
        lo, hi = spec.split(":", 1)
        return int(lo), int(hi)
    n = int(spec)
    return n, n


def launch_local_master(args, min_nodes: int, max_nodes: int
                        ) -> tuple[subprocess.Popen, str, str]:
    """Spawn the standalone master; return (proc, addr, port_file)."""
    port_file = os.path.join(
        tempfile.mkdtemp(prefix="dlrover_tpu_master_"), "port"
    )
    cmd = [
        sys.executable, "-m", "dlrover_tpu.master.job_master",
        "--job-name", args.job_name,
        "--min-nodes", str(min_nodes),
        "--max-nodes", str(max_nodes),
        "--node-unit", str(args.node_unit),
        "--rdzv-timeout", str(args.rdzv_timeout),
        "--heartbeat-interval", str(args.heartbeat_interval),
        "--port-file", port_file,
    ]
    # span-id namespace (§27): the master shares the agent's env (no
    # NODE_ID) — without a namespace the two would mint identical
    # deterministic span-id streams under DLROVER_TPU_TRACE_SEED
    env = dict(os.environ)
    env[EnvKey.SPAN_NS] = "master"
    proc = subprocess.Popen(cmd, start_new_session=True, env=env)
    deadline = time.time() + 30
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"standalone master exited early with {proc.returncode}"
            )
        if os.path.exists(port_file):
            with open(port_file) as f:
                text = f.read().strip()
            if text:
                return proc, f"127.0.0.1:{text}", port_file
        time.sleep(0.05)
    proc.kill()
    raise TimeoutError("standalone master did not report its port in 30s")


def auto_configure(
    args,
    dev_root: str = "/dev",
    sys_pci_root: str = "/sys/bus/pci/devices",
) -> None:
    """Fill node identity/count/devices/timeouts from the environment
    when the CLI left them at defaults.

    Reference analog: ElasticLaunchConfig.auto_configure_params
    (dlrover/python/elastic_agent/torch/training.py:143-157) — torchrun-
    style env-driven configuration so a pod template needs no per-node
    CLI edits: the scaler/operator injects DLROVER_TPU_NODE_NUM and
    DLROVER_TPU_NODE_ID and every replica runs the same command line.
    The node-count promotion always applies; the rest is gated on
    ``--auto-config`` exactly as the reference gates on
    ``self.auto_config``. The derivations, TPU-shaped:

    - node count from env (reference :152);
    - local device count — the nproc-per-node analog (:155) — sniffed
      from kernel device nodes and exported for the agent and the
      network-check payload, WITHOUT initializing JAX (libtpu is
      exclusive-access; see common/accelerator.py);
    - accelerator kind exported (:146's get_device_name branch);
    - auto network-check at >=4 nodes (:157), plus the comm-timeout
      derivation: the coordination-service join timeout scales with the
      fleet size (a 512-host restart storm cannot all join in the
      300 s jax default).
    """
    env_nnodes = os.environ.get(EnvKey.NODE_NUM, "")
    if args.nnodes == "1" and env_nnodes:
        args.nnodes = env_nnodes
        logger.info("auto-config: nnodes=%s from %s", env_nnodes,
                    EnvKey.NODE_NUM)
    if not args.auto_config:
        return

    from dlrover_tpu.common.accelerator import sniff_accelerator

    kind, count = sniff_accelerator(dev_root, sys_pci_root)
    os.environ.setdefault(EnvKey.ACCELERATOR, kind)
    if kind == "tpu":
        # the agent reads this instead of importing jax (which would
        # steal the chips from the trainer it spawns)
        if EnvKey.DEVICE_COUNT_OVERRIDE not in os.environ:
            os.environ[EnvKey.DEVICE_COUNT_OVERRIDE] = str(count)
            logger.info("auto-config: %d local tpu device(s)", count)
        else:
            logger.info(
                "auto-config: keeping %s=%s (sniffed %d)",
                EnvKey.DEVICE_COUNT_OVERRIDE,
                os.environ[EnvKey.DEVICE_COUNT_OVERRIDE], count,
            )

    _, max_nodes = parse_nnodes(args.nnodes)
    if max_nodes >= 4 and not args.network_check:
        args.network_check = True
        logger.info("auto-config: network check on (%d nodes >= 4)",
                    max_nodes)
    if EnvKey.INIT_TIMEOUT not in os.environ:
        # 300 s jax default, +1 s/node headroom past 64 hosts
        timeout = max(300, 300 + (max_nodes - 64))
        os.environ[EnvKey.INIT_TIMEOUT] = str(timeout)


def main(argv: list[str] | None = None) -> int:
    args = parse_args(argv)
    auto_configure(args)
    min_nodes, max_nodes = parse_nnodes(args.nnodes)

    master_proc = None
    if args.standalone:
        master_proc, master_addr, port_file = launch_local_master(
            args, min_nodes, max_nodes
        )
        logger.info("standalone master at %s", master_addr)
        # a restarted master binds a fresh port and republishes it in
        # the atomic port file: exporting the path lets the agent (and
        # its trainer children) re-resolve the address instead of
        # retrying a dead socket forever (DESIGN.md §26)
        os.environ.setdefault(EnvKey.MASTER_PORT_FILE, port_file)
    else:
        master_addr = args.master_addr or os.environ.get(
            EnvKey.MASTER_ADDR, ""
        )
        if not master_addr:
            print(
                "error: provide --master-addr (or --standalone)",
                file=sys.stderr,
            )
            return 2

    script = args.training_script
    train_args = list(args.training_args)
    if train_args and train_args[0] == "--":
        train_args = train_args[1:]
    entrypoint = [sys.executable, script, *train_args]

    # children must resolve dlrover_tpu from this checkout even when the
    # package is not pip-installed
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    existing = os.environ.get("PYTHONPATH", "")
    if pkg_root not in existing.split(os.pathsep):
        os.environ["PYTHONPATH"] = (
            f"{pkg_root}{os.pathsep}{existing}" if existing else pkg_root
        )

    config = AgentConfig(
        job_name=args.job_name,
        master_addr=master_addr,
        node_id=args.node_id,
        entrypoint=entrypoint,
        max_restarts=args.max_restarts,
        monitor_interval_s=args.monitor_interval,
        heartbeat_interval_s=args.heartbeat_interval,
        rdzv_timeout_s=args.rdzv_timeout,
        network_check=args.network_check,
        exclude_straggler=args.exclude_straggler,
        host_ip=args.host_ip,
        topology_key=args.topology_key,
        save_on_failure=not args.no_save_on_failure,
        hang_timeout_s=args.hang_timeout,
        hang_startup_grace_s=args.hang_startup_grace,
    )
    try:
        result = launch_agent(config)
    finally:
        if master_proc is not None:
            try:
                deadline = time.time() + 10
                while time.time() < deadline and master_proc.poll() is None:
                    time.sleep(0.1)
                if master_proc.poll() is None:
                    os.killpg(master_proc.pid, signal.SIGTERM)
                    try:
                        master_proc.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        # a wedged master must not outlive the run: its
                        # port/IPC names would break later standalone runs
                        os.killpg(master_proc.pid, signal.SIGKILL)
                        master_proc.wait(timeout=10)
            except (ProcessLookupError, subprocess.TimeoutExpired):
                pass
    if result == RunResult.SUCCEEDED:
        return 0
    if result == RunResult.NODE_RELAUNCH:
        return 3  # operator/scaler contract: replace this host, same job
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
