"""Python binding for the native KvVariable embedding runtime.

Reference analog: the KvVariable python layer
(tfplus/tfplus/kv_variable/python/ops/kv_variable_ops.py + embedding_ops.py)
over the C++ kernels (kv_variable/kernels/kv_variable.h:89,
kernels/training_ops.cc). TPU-native shape: the unbounded id->row table
lives host-side (XLA needs static shapes); ``lookup`` gathers the batch's
rows into a dense [n, dim] block that ships to the device, and
``apply_adam`` applies the sparse optimizer update host-side to exactly the
touched rows (GroupAdam family: Adam + optional L2 + group-lasso row
shrinkage, reference group_adam.py:272).

The binding is ctypes over ``native/libdlrover_tpu_native.so`` (built by
``make -C native``; auto-built on first import when the toolchain is
available).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdlrover_tpu_native.so")
_lib = None
_lib_lock = threading.Lock()

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")


def _load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        # run make unconditionally: it's a no-op when the .so is current,
        # and an edited kv_variable.cc must never load stale. Tolerate a
        # missing toolchain when a prebuilt .so exists.
        proc = subprocess.run(
            ["make", "-C", _NATIVE_DIR], capture_output=True, text=True
        )
        if proc.returncode != 0 and not os.path.exists(_LIB_PATH):
            raise RuntimeError(
                f"native build failed:\n{proc.stderr[-4000:]}"
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.kv_create.restype = ctypes.c_void_p
        lib.kv_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_float,
        ]
        lib.kv_free.argtypes = [ctypes.c_void_p]
        lib.kv_size.restype = ctypes.c_int64
        lib.kv_size.argtypes = [ctypes.c_void_p]
        lib.kv_lookup.argtypes = [
            ctypes.c_void_p, _i64p, ctypes.c_int64, _f32p, ctypes.c_int,
        ]
        lib.kv_apply_adam.argtypes = [
            ctypes.c_void_p, _i64p, _f32p, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_int64, ctypes.c_float, ctypes.c_float,
        ]
        lib.kv_apply_adagrad.restype = ctypes.c_int
        lib.kv_apply_adagrad.argtypes = [
            ctypes.c_void_p, _i64p, _f32p, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
        ]
        lib.kv_apply_ftrl.restype = ctypes.c_int
        lib.kv_apply_ftrl.argtypes = [
            ctypes.c_void_p, _i64p, _f32p, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_float,
        ]
        lib.kv_apply_radam.restype = ctypes.c_int
        lib.kv_apply_radam.argtypes = [
            ctypes.c_void_p, _i64p, _f32p, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_int64, ctypes.c_float,
        ]
        lib.kv_export.restype = ctypes.c_int64
        lib.kv_export.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
        ]
        lib.kv_import.argtypes = [
            ctypes.c_void_p, _i64p, _f32p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.kv_remove.restype = ctypes.c_int64
        lib.kv_remove.argtypes = [ctypes.c_void_p, _i64p, ctypes.c_int64]
        lib.kv_delta_export.restype = ctypes.c_int64
        lib.kv_delta_export.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64, _i64p, ctypes.c_int,
        ]
        lib.kv_delta_overflowed.restype = ctypes.c_int
        lib.kv_delta_overflowed.argtypes = [ctypes.c_void_p]
        lib.kv_overflow_gen.restype = ctypes.c_int64
        lib.kv_overflow_gen.argtypes = [ctypes.c_void_p]
        lib.kv_ack_overflow.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.kv_io_errors.restype = ctypes.c_int64
        lib.kv_io_errors.argtypes = [ctypes.c_void_p]
        lib.kv_clear_deltas.argtypes = [ctypes.c_void_p]
        lib.kv_mark_dirty.argtypes = [ctypes.c_void_p, _i64p, ctypes.c_int64]
        lib.kv_enable_spill.restype = ctypes.c_int
        lib.kv_enable_spill.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.kv_evict.restype = ctypes.c_int64
        lib.kv_evict.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32, ctypes.c_int64,
        ]
        lib.kv_disk_rows.restype = ctypes.c_int64
        lib.kv_disk_rows.argtypes = [ctypes.c_void_p]
        _lib = lib
        return lib


class KvEmbeddingTable:
    """Unbounded sparse-id embedding table with a sparse Adam optimizer.

    ``num_slots=2`` reserves Adam's (m, v) per row; set 0 for a frozen /
    SGD-updated table.
    """

    def __init__(self, dim: int, num_slots: int = 2, seed: int = 0,
                 init_scale: float = 0.05):
        self._lib = _load_lib()
        self.dim = dim
        self.num_slots = num_slots
        self._handle = self._lib.kv_create(
            dim, num_slots, seed, init_scale
        )
        self._step = 0

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.kv_free(handle)
            self._handle = None

    def __len__(self) -> int:
        return int(self._lib.kv_size(self._handle))

    # ------------------------------------------------------------------- ops

    def lookup(self, ids: np.ndarray, init_missing: bool = True
               ) -> np.ndarray:
        """Gather rows for ``ids`` (any shape) -> [*ids.shape, dim] f32."""
        flat = np.ascontiguousarray(ids, np.int64).reshape(-1)
        out = np.empty((flat.size, self.dim), np.float32)
        self._lib.kv_lookup(
            self._handle, flat, flat.size, out, int(init_missing)
        )
        return out.reshape(*np.shape(ids), self.dim)

    def apply_adam(self, ids: np.ndarray, grads: np.ndarray,
                   lr: float = 1e-3, beta1: float = 0.9,
                   beta2: float = 0.999, eps: float = 1e-8,
                   l2: float = 0.0, group_lasso: float = 0.0,
                   step: int | None = None) -> None:
        """Sparse (Group)Adam on the rows of ``ids`` with ``grads``.

        Duplicate ids apply sequentially. ``group_lasso`` adds the
        proximal row-shrinkage step of the reference's GroupAdam.
        """
        flat, g = self._check_grads(ids, grads, 2, "apply_adam")
        if step is None:
            self._step += 1
            step = self._step
        self._lib.kv_apply_adam(
            self._handle, flat, g, flat.size,
            lr, beta1, beta2, eps, step, l2, group_lasso,
        )

    def _check_grads(self, ids: np.ndarray, grads: np.ndarray,
                     need_slots: int, what: str
                     ) -> tuple[np.ndarray, np.ndarray]:
        flat = np.ascontiguousarray(ids, np.int64).reshape(-1)
        g = np.ascontiguousarray(grads, np.float32).reshape(-1, self.dim)
        if g.shape[0] != flat.size:
            raise ValueError(
                f"{flat.size} ids but {g.shape[0]} gradient rows"
            )
        if self.num_slots < need_slots:
            raise ValueError(
                f"{what} needs num_slots >= {need_slots}, "
                f"table has {self.num_slots}"
            )
        return flat, g

    def apply_adagrad(self, ids: np.ndarray, grads: np.ndarray,
                      lr: float = 0.1, eps: float = 1e-8,
                      l2: float = 0.0, group_lasso: float = 0.0) -> None:
        """Sparse (Group)Adagrad: slot 0 is the squared-grad accumulator;
        ``group_lasso`` adds the reference GroupAdagrad's proximal row
        shrinkage (tfplus training_ops.cc Adagrad family)."""
        flat, g = self._check_grads(ids, grads, 1, "apply_adagrad")
        rc = self._lib.kv_apply_adagrad(
            self._handle, flat, g, flat.size, lr, eps, l2, group_lasso,
        )
        if rc != 0:
            raise RuntimeError(f"kv_apply_adagrad failed ({rc})")

    def apply_ftrl(self, ids: np.ndarray, grads: np.ndarray,
                   lr: float = 0.1, l1: float = 0.0, l2: float = 0.0,
                   beta: float = 1.0, group_lasso: float = 0.0) -> None:
        """Sparse (Group)FTRL-proximal: slots are (z, n). L1 drives
        per-coordinate sparsity; ``group_lasso`` prunes whole rows
        (reference SparseGroupFtrl)."""
        flat, g = self._check_grads(ids, grads, 2, "apply_ftrl")
        rc = self._lib.kv_apply_ftrl(
            self._handle, flat, g, flat.size, lr, l1, l2, beta,
            group_lasso,
        )
        if rc != 0:
            raise RuntimeError(f"kv_apply_ftrl failed ({rc})")

    def apply_radam(self, ids: np.ndarray, grads: np.ndarray,
                    lr: float = 1e-3, beta1: float = 0.9,
                    beta2: float = 0.999, eps: float = 1e-8,
                    l2: float = 0.0, step: int | None = None) -> None:
        """Sparse Rectified Adam (variance-rectified warmup-free Adam;
        reference tfplus rectified_adam.py). Slots are (m, v)."""
        flat, g = self._check_grads(ids, grads, 2, "apply_radam")
        if step is None:
            self._step += 1
            step = self._step
        rc = self._lib.kv_apply_radam(
            self._handle, flat, g, flat.size, lr, beta1, beta2, eps,
            step, l2,
        )
        if rc != 0:
            raise RuntimeError(f"kv_apply_radam failed ({rc})")

    def apply(self, optimizer: str, ids: np.ndarray, grads: np.ndarray,
              **kwargs) -> None:
        """Name-dispatched sparse update — what config-driven trainers
        (the recsys example) call. Optimizers: adam, group_adam,
        adagrad, group_adagrad, ftrl, group_ftrl, radam."""
        known = {"adam", "group_adam", "adagrad", "group_adagrad",
                 "ftrl", "group_ftrl", "radam"}
        if optimizer not in known:
            raise ValueError(f"unknown sparse optimizer {optimizer!r}")
        base = optimizer.removeprefix("group_")
        if optimizer.startswith("group_") and "group_lasso" not in kwargs:
            kwargs["group_lasso"] = 1e-3
        fn = {
            "adam": self.apply_adam,
            "adagrad": self.apply_adagrad,
            "ftrl": self.apply_ftrl,
            "radam": self.apply_radam,
        }[base]
        fn(ids, grads, **kwargs)

    def remove(self, ids: np.ndarray) -> int:
        flat = np.ascontiguousarray(ids, np.int64).reshape(-1)
        return int(self._lib.kv_remove(self._handle, flat, flat.size))

    # ---------------------------------------------- hybrid (tiered) storage

    def enable_spill(self, path: str) -> None:
        """Attach a disk spill tier (reference: hybrid_embedding's
        mem + storage tables). Cold rows move there via ``evict`` and
        fault back in on access; export/checkpoint sees both tiers."""
        rc = int(self._lib.kv_enable_spill(
            self._handle, os.fsencode(path)
        ))
        if rc == -2:
            raise RuntimeError(
                "spill tier already enabled; re-pointing it would orphan "
                "the spilled rows"
            )
        if rc != 0:
            raise OSError(f"cannot open spill file {path!r}")

    def evict(self, max_freq: int = 1, max_rows: int = 0) -> int:
        """Spill rows with frequency <= ``max_freq`` to disk (at most
        ``max_rows``; 0 = unlimited), freeing their host memory. Returns
        the number spilled."""
        return int(self._lib.kv_evict(self._handle, max_freq, max_rows))

    @property
    def disk_rows(self) -> int:
        return int(self._lib.kv_disk_rows(self._handle))

    # ------------------------------------------------------------ checkpoint

    def export(self, min_freq: int = 0, with_slots: bool = True
               ) -> dict[str, np.ndarray]:
        """Snapshot rows with frequency >= ``min_freq`` (the reference's
        under-threshold feature filtering)."""
        n = int(self._lib.kv_export(self._handle, min_freq, None, None,
                                    None, None, 0, None))
        keys = np.empty(n, np.int64)
        values = np.empty((n, self.dim), np.float32)
        slots = np.empty((n, self.num_slots * self.dim), np.float32)
        freq = np.empty(n, np.uint32)
        errs = np.zeros(1, np.int64)
        written = 0
        if n:
            # the fill pass is capacity-bounded: the table may mutate
            # between the count and fill calls (shard-level locking only)
            written = int(self._lib.kv_export(
                self._handle, min_freq,
                keys.ctypes.data_as(ctypes.c_void_p),
                values.ctypes.data_as(ctypes.c_void_p),
                slots.ctypes.data_as(ctypes.c_void_p)
                if with_slots and self.num_slots else None,
                freq.ctypes.data_as(ctypes.c_void_p),
                n,
                errs.ctypes.data_as(ctypes.c_void_p),
            ))
        if written < n:
            keys, values = keys[:written], values[:written]
            slots, freq = slots[:written], freq[:written]
        if int(errs[0]):
            # scoped to THIS call (the global io_errors counter also
            # counts unrelated lookup-path failures)
            raise OSError(
                f"{int(errs[0])} spill-tier read failures during "
                "export: the snapshot would silently omit rows"
            )
        out = {
            "keys": keys, "values": values, "freq": freq,
            "step": np.asarray(self._step, np.int64),
        }
        if with_slots and self.num_slots:
            out["slots"] = slots
        return out

    def import_(self, snapshot: dict[str, np.ndarray]) -> None:
        keys = np.ascontiguousarray(snapshot["keys"], np.int64)
        values = np.ascontiguousarray(snapshot["values"], np.float32)
        slots = snapshot.get("slots")
        freq = snapshot.get("freq")
        if values.shape != (keys.size, self.dim):
            raise ValueError(
                f"snapshot values shape {values.shape} != "
                f"({keys.size}, {self.dim}) — saved with a different dim?"
            )
        if slots is not None and np.shape(slots) != (
            keys.size, self.num_slots * self.dim
        ):
            raise ValueError(
                f"snapshot slots shape {np.shape(slots)} != "
                f"({keys.size}, {self.num_slots * self.dim}) — saved with "
                "different num_slots?"
            )
        if freq is not None and np.shape(freq) != (keys.size,):
            raise ValueError(f"snapshot freq shape {np.shape(freq)}")
        self._lib.kv_import(
            self._handle, keys, values,
            np.ascontiguousarray(slots, np.float32).ctypes.data_as(
                ctypes.c_void_p
            ) if slots is not None else None,
            np.ascontiguousarray(freq, np.uint32).ctypes.data_as(
                ctypes.c_void_p
            ) if freq is not None else None,
            keys.size,
        )
        if "step" in snapshot:
            self._step = int(snapshot["step"])

    # ----------------------------------------------------- incremental ckpt

    def _delta_drain_once(self, with_slots: bool, clear: bool
                          ) -> tuple[dict[str, np.ndarray], bool]:
        """One native drain pass; returns (chunk, complete). The chunk's
        ``read_errors`` counts spilled rows whose disk read failed — they
        keep their dirty marks and surface in the next drain."""
        counts = np.zeros(3, np.int64)
        self._lib.kv_delta_export(
            self._handle, None, None, None, None, 0, None, 0, counts, 0
        )
        # slack: the table may grow between count and fill; an early-stop
        # just means the remainder drains on the next pass
        n = int(counts[0]) + 256
        m = int(counts[1]) + 256
        keys = np.empty(n, np.int64)
        values = np.empty((n, self.dim), np.float32)
        slots = np.empty((n, self.num_slots * self.dim), np.float32)
        freq = np.empty(n, np.uint32)
        removed = np.empty(m, np.int64)
        complete = int(self._lib.kv_delta_export(
            self._handle,
            keys.ctypes.data_as(ctypes.c_void_p),
            values.ctypes.data_as(ctypes.c_void_p),
            slots.ctypes.data_as(ctypes.c_void_p)
            if with_slots and self.num_slots else None,
            freq.ctypes.data_as(ctypes.c_void_p),
            n,
            removed.ctypes.data_as(ctypes.c_void_p),
            m, counts, int(clear),
        ))
        r, d = int(counts[0]), int(counts[1])
        chunk = {
            "keys": keys[:r], "values": values[:r], "freq": freq[:r],
            "removed": removed[:d],
            "step": np.asarray(self._step, np.int64),
            "read_errors": np.asarray(int(counts[2]), np.int64),
        }
        if with_slots and self.num_slots:
            chunk["slots"] = slots[:r]
        return chunk, bool(complete)

    def delta_export(self, with_slots: bool = True, clear: bool = True
                     ) -> dict[str, np.ndarray]:
        """Rows whose values changed since the last clearing delta export
        (the reference's delta export for incremental checkpoints /
        serving sync). Includes ``removed``: keys deleted since then —
        restore replays removals before upserts. ``clear=True`` resets the
        tracking so the next delta is relative to this one.

        Each native pass drains whole shards atomically (a key's value
        export and its removal never interleave within a pass); passes
        are folded with ``merge_deltas`` so later events win. Lookup-only
        frequency bumps do not mark rows dirty, so restored frequencies
        can lag the live table's — value data is exact.
        """
        if clear:
            out, complete = self._delta_drain_once(with_slots, True)
            tries = 0
            while not complete and tries < 8:
                chunk, complete = self._delta_drain_once(with_slots, True)
                out = merge_deltas(out, chunk)
                tries += 1
            # early stops and spill-read failures are both LOSSLESS here:
            # an undrained shard keeps its marks/logs, and a failed-read
            # row keeps its dirty mark — the change surfaces in the next
            # delta. ``read_errors`` in the result tells checkpointing
            # callers this delta is not yet a complete cut.
        else:
            # clear=False passes drain nothing, so chunks can't be
            # merged (they'd duplicate); retry whole passes with freshly
            # counted buffers until one completes
            for _ in range(8):
                out, complete = self._delta_drain_once(with_slots, False)
                if complete:
                    break
            else:
                raise RuntimeError(
                    "delta_export(clear=False) could not complete: the "
                    "table is mutating faster than the drain"
                )
            if int(out["read_errors"]):
                # nothing was drained/cleared, so raising loses nothing —
                # and a peek consumer must not mistake this for complete
                raise OSError(
                    f"{int(out['read_errors'])} spill-tier read failures "
                    "during delta export"
                )
        return out

    def delta_overflowed(self) -> bool:
        """True when removals were dropped (bounded removed-log overflow)
        and no covering base has been acked: the delta chain is broken
        and the next save must be a full export."""
        return bool(self._lib.kv_delta_overflowed(self._handle))

    def overflow_gen(self) -> int:
        """Monotonic overflow generation (see the manager's ack cycle)."""
        return int(self._lib.kv_overflow_gen(self._handle))

    def ack_overflow(self, gen: int) -> None:
        """Mark overflows up to ``gen`` as covered by a durable base."""
        self._lib.kv_ack_overflow(self._handle, gen)

    @property
    def io_errors(self) -> int:
        """Cumulative spill-tier read failures."""
        return int(self._lib.kv_io_errors(self._handle))

    def clear_deltas(self) -> None:
        """Reset delta tracking (call after a full/base export)."""
        self._lib.kv_clear_deltas(self._handle)

    def mark_dirty(self, ids: np.ndarray) -> None:
        """Re-mark rows dirty (failed-checkpoint recovery: the export
        cleared their marks but the file never became durable)."""
        flat = np.ascontiguousarray(ids, np.int64).reshape(-1)
        if flat.size:
            self._lib.kv_mark_dirty(self._handle, flat, flat.size)

    def apply_delta(self, delta: dict[str, np.ndarray]) -> None:
        """Replay one delta: removals first, then row upserts."""
        removed = delta.get("removed")
        if removed is not None and np.size(removed):
            self.remove(np.asarray(removed))
        if np.size(delta["keys"]):
            self.import_({
                k: v for k, v in delta.items()
                if k not in ("removed", "read_errors")
            })


def merge_deltas(older: dict | None, newer: dict) -> dict:
    """Fold an older delta under a newer one (one replayable delta out).

    Replay applies removals before upserts, so an older row whose key was
    since removed must be dropped — keeping it would resurrect the stale
    value. For duplicate keys the newer row wins (import applies rows
    sequentially; newer rows are concatenated after older ones).
    """
    if older is None:
        return newer
    keep = ~np.isin(older["keys"], newer["removed"])
    out = dict(newer)
    out["keys"] = np.concatenate([older["keys"][keep], newer["keys"]])
    out["values"] = np.concatenate(
        [older["values"][keep], newer["values"]]
    )
    out["freq"] = np.concatenate([older["freq"][keep], newer["freq"]])
    if "slots" in newer and "slots" in older:
        out["slots"] = np.concatenate(
            [older["slots"][keep], newer["slots"]]
        )
    out["removed"] = np.concatenate([older["removed"], newer["removed"]])
    out["read_errors"] = np.asarray(
        int(older.get("read_errors", 0)) + int(newer.get("read_errors", 0)),
        np.int64,
    )
    return out


class IncrementalCheckpointManager:
    """Base + delta checkpoints for a KvEmbeddingTable.

    Reference analog: the incremental checkpoint manager
    (tfplus/tfplus/kv_variable/python/training/checkpoint_manager.py) —
    periodic full saves with cheap deltas between them, so a 100M-row
    table checkpoints at the cost of the rows that actually changed.

    Layout under ``directory``: ``base-N.npz`` (full export at version N)
    and ``delta-N.npz`` (changes from version N-1 to N); ``restore()``
    loads the newest base then replays every later delta in order.
    """

    def __init__(self, table: KvEmbeddingTable, directory: str,
                 base_interval: int = 10):
        self.table = table
        self.directory = directory
        self.base_interval = base_interval
        self._version = 0
        # changes drained from the table's delta tracking but not yet
        # durably written (carried across a failed save so nothing is
        # ever lost from the chain)
        self._pending: dict[str, np.ndarray] | None = None
        os.makedirs(directory, exist_ok=True)

    def _write(self, path: str, snap: dict) -> None:
        # save()'s contract is "tracking only advances once the file is
        # durable" — so durable must mean fsynced, not just in page cache
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **snap)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        dfd = os.open(self.directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def save(self) -> str:
        """Write the next checkpoint (base every ``base_interval``-th
        save, delta otherwise); returns the path written.

        Tracking state only advances when the file is durable: a failed
        write parks the drained changes in ``_pending`` (folded into the
        next attempt) and does not consume the version, so the chain
        stays gapless and lossless. A removed-log overflow (bounded
        native log) forces a base — the delta chain is broken there.
        """
        v = self._version + 1
        # the overflow generation observed BEFORE draining is what a
        # durable base can ack; an overflow racing the save keeps the
        # flag up and forces the next save to be a base as well
        overflow_gen = self.table.overflow_gen()
        force_base = self.table.delta_overflowed()
        if force_base or (v - 1) % self.base_interval == 0:
            # drain tracking FIRST, then snapshot: the full export is a
            # superset of the drained delta, so a durable base supersedes
            # it (and any older pending) — rows dirtied between drain and
            # export keep their marks and land in the next delta
            pend = self.table.delta_export()
            path = os.path.join(self.directory, f"base-{v}.npz")
            try:
                self._write(path, self.table.export())
            except BaseException:
                self._pending = merge_deltas(self._pending, pend)
                raise
            self._pending = None
            self.table.ack_overflow(overflow_gen)
        else:
            path = os.path.join(self.directory, f"delta-{v}.npz")
            snap = merge_deltas(self._pending, self.table.delta_export())
            if int(snap.get("read_errors", 0)):
                # some spilled rows could not be read: a delta written now
                # would be a valid-but-stale cut (those rows revert on a
                # restore taken before the next delta). Park everything
                # drained and surface the failure; the next save retries.
                self._pending = snap
                raise OSError(
                    f"{int(snap['read_errors'])} spill-tier read "
                    "failures while draining the delta; checkpoint "
                    "postponed (no data lost)"
                )
            snap = {k: v_ for k, v_ in snap.items() if k != "read_errors"}
            try:
                self._write(path, snap)
            except BaseException:
                self._pending = snap
                raise
            self._pending = None
        self._version = v
        return path

    def restore(self) -> int:
        """Load newest base + later deltas; returns the version restored
        (0 when the directory holds no base). Raises when delta files
        exist beyond a gap in the chain (a replay would silently skip
        them — the directory is corrupt or from a foreign run)."""
        names = os.listdir(self.directory)
        bases = sorted(
            int(f[len("base-"):-len(".npz")])
            for f in names
            if f.startswith("base-") and f.endswith(".npz")
        )
        if not bases:
            return 0
        base_v = bases[-1]
        deltas = {
            int(f[len("delta-"):-len(".npz")])
            for f in names
            if f.startswith("delta-") and f.endswith(".npz")
        }
        # validate the chain BEFORE touching the table: raising after a
        # partial replay would leave the caller's table half-mutated
        v = base_v
        while (v + 1) in deltas:
            v += 1
        orphans = sorted(d for d in deltas if d > v)
        if orphans:
            raise ValueError(
                f"delta chain ends at version {v} but later files exist "
                f"(delta-{orphans}): refusing a restore that would drop "
                "them"
            )
        with np.load(os.path.join(self.directory, f"base-{base_v}.npz")) as z:
            self.table.import_(dict(z))
        for d in range(base_v + 1, v + 1):
            with np.load(os.path.join(self.directory, f"delta-{d}.npz")) as z:
                self.table.apply_delta(dict(z))
        # restore itself dirties every imported row; the next delta
        # should be relative to this restored state
        self.table.clear_deltas()
        self._version = v
        return v
