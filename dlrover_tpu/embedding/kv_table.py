"""Python binding for the native KvVariable embedding runtime.

Reference analog: the KvVariable python layer
(tfplus/tfplus/kv_variable/python/ops/kv_variable_ops.py + embedding_ops.py)
over the C++ kernels (kv_variable/kernels/kv_variable.h:89,
kernels/training_ops.cc). TPU-native shape: the unbounded id->row table
lives host-side (XLA needs static shapes); ``lookup`` gathers the batch's
rows into a dense [n, dim] block that ships to the device, and
``apply_adam`` applies the sparse optimizer update host-side to exactly the
touched rows (GroupAdam family: Adam + optional L2 + group-lasso row
shrinkage, reference group_adam.py:272).

The binding is ctypes over ``native/libdlrover_tpu_native.so`` (built by
``make -C native``; auto-built on first import when the toolchain is
available).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_LIB_PATH = os.path.join(_NATIVE_DIR, "libdlrover_tpu_native.so")
_lib = None
_lib_lock = threading.Lock()

_i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
_f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
_u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")


def _load_lib():
    global _lib
    with _lib_lock:
        if _lib is not None:
            return _lib
        # run make unconditionally: it's a no-op when the .so is current,
        # and an edited kv_variable.cc must never load stale. Tolerate a
        # missing toolchain when a prebuilt .so exists.
        proc = subprocess.run(
            ["make", "-C", _NATIVE_DIR], capture_output=True, text=True
        )
        if proc.returncode != 0 and not os.path.exists(_LIB_PATH):
            raise RuntimeError(
                f"native build failed:\n{proc.stderr[-4000:]}"
            )
        lib = ctypes.CDLL(_LIB_PATH)
        lib.kv_create.restype = ctypes.c_void_p
        lib.kv_create.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_float,
        ]
        lib.kv_free.argtypes = [ctypes.c_void_p]
        lib.kv_size.restype = ctypes.c_int64
        lib.kv_size.argtypes = [ctypes.c_void_p]
        lib.kv_lookup.argtypes = [
            ctypes.c_void_p, _i64p, ctypes.c_int64, _f32p, ctypes.c_int,
        ]
        lib.kv_apply_adam.argtypes = [
            ctypes.c_void_p, _i64p, _f32p, ctypes.c_int64,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            ctypes.c_int64, ctypes.c_float, ctypes.c_float,
        ]
        lib.kv_export.restype = ctypes.c_int64
        lib.kv_export.argtypes = [
            ctypes.c_void_p, ctypes.c_uint32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.kv_import.argtypes = [
            ctypes.c_void_p, _i64p, _f32p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ]
        lib.kv_remove.restype = ctypes.c_int64
        lib.kv_remove.argtypes = [ctypes.c_void_p, _i64p, ctypes.c_int64]
        _lib = lib
        return lib


class KvEmbeddingTable:
    """Unbounded sparse-id embedding table with a sparse Adam optimizer.

    ``num_slots=2`` reserves Adam's (m, v) per row; set 0 for a frozen /
    SGD-updated table.
    """

    def __init__(self, dim: int, num_slots: int = 2, seed: int = 0,
                 init_scale: float = 0.05):
        self._lib = _load_lib()
        self.dim = dim
        self.num_slots = num_slots
        self._handle = self._lib.kv_create(
            dim, num_slots, seed, init_scale
        )
        self._step = 0

    def __del__(self):
        handle = getattr(self, "_handle", None)
        if handle:
            self._lib.kv_free(handle)
            self._handle = None

    def __len__(self) -> int:
        return int(self._lib.kv_size(self._handle))

    # ------------------------------------------------------------------- ops

    def lookup(self, ids: np.ndarray, init_missing: bool = True
               ) -> np.ndarray:
        """Gather rows for ``ids`` (any shape) -> [*ids.shape, dim] f32."""
        flat = np.ascontiguousarray(ids, np.int64).reshape(-1)
        out = np.empty((flat.size, self.dim), np.float32)
        self._lib.kv_lookup(
            self._handle, flat, flat.size, out, int(init_missing)
        )
        return out.reshape(*np.shape(ids), self.dim)

    def apply_adam(self, ids: np.ndarray, grads: np.ndarray,
                   lr: float = 1e-3, beta1: float = 0.9,
                   beta2: float = 0.999, eps: float = 1e-8,
                   l2: float = 0.0, group_lasso: float = 0.0,
                   step: int | None = None) -> None:
        """Sparse (Group)Adam on the rows of ``ids`` with ``grads``.

        Duplicate ids apply sequentially. ``group_lasso`` adds the
        proximal row-shrinkage step of the reference's GroupAdam.
        """
        flat = np.ascontiguousarray(ids, np.int64).reshape(-1)
        g = np.ascontiguousarray(grads, np.float32).reshape(-1, self.dim)
        if g.shape[0] != flat.size:
            raise ValueError(
                f"{flat.size} ids but {g.shape[0]} gradient rows"
            )
        if self.num_slots < 2:
            raise ValueError("apply_adam needs num_slots >= 2 (m, v)")
        if step is None:
            self._step += 1
            step = self._step
        self._lib.kv_apply_adam(
            self._handle, flat, g, flat.size,
            lr, beta1, beta2, eps, step, l2, group_lasso,
        )

    def remove(self, ids: np.ndarray) -> int:
        flat = np.ascontiguousarray(ids, np.int64).reshape(-1)
        return int(self._lib.kv_remove(self._handle, flat, flat.size))

    # ------------------------------------------------------------ checkpoint

    def export(self, min_freq: int = 0, with_slots: bool = True
               ) -> dict[str, np.ndarray]:
        """Snapshot rows with frequency >= ``min_freq`` (the reference's
        under-threshold feature filtering)."""
        n = int(self._lib.kv_export(self._handle, min_freq, None, None,
                                    None, None, 0))
        keys = np.empty(n, np.int64)
        values = np.empty((n, self.dim), np.float32)
        slots = np.empty((n, self.num_slots * self.dim), np.float32)
        freq = np.empty(n, np.uint32)
        written = 0
        if n:
            # the fill pass is capacity-bounded: the table may mutate
            # between the count and fill calls (shard-level locking only)
            written = int(self._lib.kv_export(
                self._handle, min_freq,
                keys.ctypes.data_as(ctypes.c_void_p),
                values.ctypes.data_as(ctypes.c_void_p),
                slots.ctypes.data_as(ctypes.c_void_p)
                if with_slots and self.num_slots else None,
                freq.ctypes.data_as(ctypes.c_void_p),
                n,
            ))
        if written < n:
            keys, values = keys[:written], values[:written]
            slots, freq = slots[:written], freq[:written]
        out = {
            "keys": keys, "values": values, "freq": freq,
            "step": np.asarray(self._step, np.int64),
        }
        if with_slots and self.num_slots:
            out["slots"] = slots
        return out

    def import_(self, snapshot: dict[str, np.ndarray]) -> None:
        keys = np.ascontiguousarray(snapshot["keys"], np.int64)
        values = np.ascontiguousarray(snapshot["values"], np.float32)
        slots = snapshot.get("slots")
        freq = snapshot.get("freq")
        if values.shape != (keys.size, self.dim):
            raise ValueError(
                f"snapshot values shape {values.shape} != "
                f"({keys.size}, {self.dim}) — saved with a different dim?"
            )
        if slots is not None and np.shape(slots) != (
            keys.size, self.num_slots * self.dim
        ):
            raise ValueError(
                f"snapshot slots shape {np.shape(slots)} != "
                f"({keys.size}, {self.num_slots * self.dim}) — saved with "
                "different num_slots?"
            )
        if freq is not None and np.shape(freq) != (keys.size,):
            raise ValueError(f"snapshot freq shape {np.shape(freq)}")
        self._lib.kv_import(
            self._handle, keys, values,
            np.ascontiguousarray(slots, np.float32).ctypes.data_as(
                ctypes.c_void_p
            ) if slots is not None else None,
            np.ascontiguousarray(freq, np.uint32).ctypes.data_as(
                ctypes.c_void_p
            ) if freq is not None else None,
            keys.size,
        )
        if "step" in snapshot:
            self._step = int(snapshot["step"])
