"""Elastic KV embedding fabric: one hash table, trained and served.

The promotion of ``embedding/service.py``'s PS-style sharded table into
the ROADMAP-3 subsystem (DESIGN.md §25). Four changes over the PS tier:

1. **Consistent-hash ownership.** Row ownership is a vnode hash ring
   (``common/hashring`` — the same blake2s/64-vnode construction as the
   gateway's ``ShardRing``) over stable member ids, not
   ``splitmix64(id) % N``: a scale event N→N±1 migrates ~1/N of the
   rows instead of reshuffling nearly everything. Every scale journals
   ``embedding_scale`` with moved-row counts.
2. **Async gradient streaming.** ``FabricClient.apply`` enqueues the
   sparse update into a bounded send queue and returns; a background
   flusher streams batches to the shard servers. Staleness — the
   newest enqueued apply version minus the newest flushed one, in
   steps — is a live gauge (``dlrover_tpu_embedding_staleness_steps``)
   with a hard bound (``DLROVER_TPU_EMBEDDING_MAX_STALENESS``) that
   back-pressures the training step, and ``drain()`` is the barrier
   every checkpoint snapshot takes so saved state is update-complete.
3. **Verified shard checkpoints.** Shard exports are deterministic
   packed blocks (rows sorted by key, optimizer slots + frequency
   included) written through ``CheckpointStorage.write_parallel`` with
   per-piece CRC32s; with ``DLROVER_TPU_EMBEDDING_REPLICAS=2`` each
   block also lands in its ring successor's file, so restore runs the
   §20 ``resolve_restore_plan`` quorum semantics and rolls a corrupt
   shard back to its replica twin instead of losing the step. The
   ``commit_w<W>`` manifest carries hash-shard identity (members,
   table geometry, applied version), and restore reassembles any saved
   ring size onto the current one (N→M→N row-exact).
4. **Train + serve from one table.** A ``mode="serve"`` client is
   read-only (lookups never materialize rows), version-pinned (every
   request carries the routing version; a scale event answers with a
   structured error and the client re-routes), and stamps the applied
   training version on each response — the gateway's embedding lookup
   route (``gateway/server.py``) serves the *live* training ring.

Wire framing, chunked row pushes and the two-phase scale protocol are
the hardened r04/r05 designs from ``embedding/service.py``; the fabric
reuses its ``_call`` (which is also the ``embedding_msg`` chaos
injection point) and error type so transport fixes land once.
"""

from __future__ import annotations

import dataclasses
import json
import os
import socket
import threading
import time
import zlib
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable

import numpy as np

from dlrover_tpu.checkpoint import integrity
from dlrover_tpu.common import envspec
from dlrover_tpu.common.array_wire import decode_msg, encode_msg
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.hashring import HashRing, id_points
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.msg_server import ArrayMsgServer
from dlrover_tpu.common.storage import PosixDiskStorage
from dlrover_tpu.embedding.kv_table import KvEmbeddingTable
from dlrover_tpu.embedding.service import ShardError, _call
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_staleness_steps = registry().gauge(
    "dlrover_tpu_embedding_staleness_steps",
    "async-apply staleness: newest enqueued apply version minus newest "
    "flushed one, in training steps",
)
_apply_lag_seconds = registry().histogram(
    "dlrover_tpu_embedding_apply_lag_seconds",
    "enqueue -> flushed-to-shards latency of one async apply batch",
    buckets=(0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
             1.0, 2.5, 5.0),
)
_flush_queue_depth = registry().gauge(
    "dlrover_tpu_embedding_flush_queue_depth",
    "apply batches enqueued and not yet flushed to the shard servers",
)
_backpressure_total = registry().counter(
    "dlrover_tpu_embedding_backpressure_total",
    "apply() calls that blocked on the staleness bound or a full queue",
)
_scale_moved_rows_total = registry().counter(
    "dlrover_tpu_embedding_scale_moved_rows_total",
    "rows migrated between shard servers by fabric ring scale events",
)
_lookups_total = registry().counter(
    "dlrover_tpu_embedding_lookups_total",
    "fabric lookup batches by client mode",
    label_names=("mode",),
)

# rows per migration/import push: bounded so one frame stays well under
# rpc.MAX_FRAME even for wide tables with optimizer slots
_PUSH_CHUNK_BYTES = 8 << 20


# --------------------------------------------------------------- ring route


@dataclasses.dataclass
class RingRoute:
    """One immutable routing epoch: (version, ring members, addresses).

    Members are STABLE ids (``emb-0`` …), decoupled from addresses: the
    ring hashes member ids, so row placement — and therefore the moved
    fraction of a scale event — is deterministic across runs even
    though listen ports are ephemeral."""

    version: int
    members: list[str]
    addrs: dict[str, str]
    replicas: int = 1
    vnodes: int = 64

    def __post_init__(self):
        self.members = list(self.members)
        self.addrs = dict(self.addrs)
        ring = HashRing(self.members, vnodes=self.vnodes)
        self._points, self._owners = ring.snapshot(self.members)

    def owner_indices(self, ids: np.ndarray) -> np.ndarray:
        """Index into ``members`` of each id's owning shard server."""
        return HashRing.owner_indices(
            self._points, self._owners, id_points(ids)
        )

    def twin(self, member: str) -> str:
        """The ring-successor replica twin that also persists
        ``member``'s block when ``replicas >= 2``."""
        i = self.members.index(member)
        return self.members[(i + 1) % len(self.members)]

    def to_meta(self) -> dict:
        return {"version": self.version, "members": self.members,
                "addrs": self.addrs, "replicas": self.replicas,
                "vnodes": self.vnodes}

    @classmethod
    def from_meta(cls, meta: dict) -> "RingRoute":
        return cls(version=int(meta["version"]),
                   members=list(meta["members"]),
                   addrs=dict(meta["addrs"]),
                   replicas=int(meta.get("replicas", 1)),
                   vnodes=int(meta.get("vnodes", 64)))


# ------------------------------------------------------------ block packing


def pack_block(member: str, snap: dict, applied_version: int) -> bytes:
    """Deterministically serialize one shard's row set: rows sorted by
    key, values + optimizer slots + frequency all included, framed with
    ``array_wire``. Determinism is what makes the replica twin's copy
    byte-identical to the owner's — the quorum restore's coverage
    algebra (§20) matches pieces by content CRC."""
    keys = np.asarray(snap["keys"], np.int64)
    order = np.argsort(keys, kind="stable")
    arrays = {"keys": keys[order]}
    for name in ("values", "slots", "freq"):
        if name in snap:
            arrays[name] = np.ascontiguousarray(
                np.asarray(snap[name])[order]
            )
    return encode_msg("emb_block", {
        "member": member, "rows": int(keys.size),
        "applied_version": int(applied_version),
        "step": int(snap.get("step", 0)),
    }, arrays)


def unpack_block(blob: bytes) -> tuple[dict, dict]:
    op, meta, arrays = decode_msg(blob)
    if op != "emb_block":
        raise ValueError(f"not an embedding block: op={op!r}")
    return meta, arrays


def _push_rows(addr: str, rows: dict, dim: int, num_slots: int,
               meta: dict | None = None, timeout: float = 30.0) -> None:
    """Chunked ``import_rows`` push to one shard server (bounded frame
    sizes for wide tables with slots)."""
    host, _, port = addr.rpartition(":")
    row_bytes = dim * 4 * (1 + num_slots) + 8 + 4
    chunk = max(1, _PUSH_CHUNK_BYTES // row_bytes)
    with socket.create_connection(
        (host or "127.0.0.1", int(port)), timeout=timeout
    ) as conn:
        conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        n = int(rows["keys"].size)
        for i in range(0, n, chunk):
            sl = slice(i, i + chunk)
            payload = {
                k: rows[k][sl]
                for k in ("keys", "values", "slots", "freq")
                if rows.get(k) is not None
            }
            _call(conn, "import_rows", meta or {}, payload)


# ------------------------------------------------------------- shard server


class FabricShardServer(ArrayMsgServer):
    """One fabric shard: a native KvEmbeddingTable owning the rows the
    hash ring maps to this member at the current routing version.

    Beyond the PS-tier server this one tracks ``applied_version`` (the
    newest async-apply version it has folded in — stamped on every
    lookup response so serving clients know how fresh their rows are)
    and owns the verified-persist surface: ``persist_prepare`` packs
    the deterministic block, ``hold_block`` parks a peer's block for
    twin redundancy, ``persist_write`` lands this writer's file through
    ``CheckpointStorage.write_parallel`` with per-piece CRCs and
    returns the manifest/ack entry."""

    error_cls = ShardError

    def __init__(self, dim: int, num_slots: int = 2, *, member: str,
                 seed: int = 0, host: str = "0.0.0.0", port: int = 0,
                 storage=None):
        super().__init__(host=host, port=port,
                         name=f"emb-fabric-{member}")
        self.dim = dim
        self.num_slots = num_slots
        self.member = member
        # member-derived seed: deterministic distinct init per shard,
        # stable across respawns of the same member id
        self.table = KvEmbeddingTable(
            dim=dim, num_slots=num_slots,
            seed=seed + (zlib.crc32(member.encode()) & 0xFFFF),
        )
        self.storage = storage or PosixDiskStorage()
        self.route: RingRoute | None = None
        self.applied_version = 0
        self._lock = threading.Lock()
        self._migrating = False
        self._migrating_since = 0.0
        self.migrate_ttl_s = 1800.0
        self._prepared: dict[int, bytes] = {}       # step -> own block
        self._held: dict[tuple[int, str], bytes] = {}  # (step, owner)

    def start(self) -> "FabricShardServer":
        super().start()
        logger.info("fabric shard %s serving on port %d", self.member,
                    self.port)
        return self

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    # ------------------------------------------------------------- dispatch

    def _check_epoch(self, meta: dict) -> None:
        if self._migrating:
            if (self._migrating_since
                    and time.monotonic() - self._migrating_since
                    > self.migrate_ttl_s):
                logger.warning(
                    "migration armed > %.0fs with no commit/abort "
                    "(dead coordinator?); self-aborting to restore "
                    "service", self.migrate_ttl_s,
                )
                self.abort_migration()
            else:
                raise ShardError("migrating",
                                 "shard is re-partitioning",
                                 {"retry_ms": 100})
        v = meta.get("v")
        if self.route is not None and v is not None \
                and v != self.route.version:
            raise ShardError(
                "version",
                f"client routing v{v} != shard v{self.route.version}",
                {"current": self.route.version},
            )

    def _handle(self, op: str, meta: dict, arrays: dict) -> bytes:
        if op == "ping":
            route = self.route
            return encode_msg("ok", {
                "member": self.member, "rows": len(self.table),
                "version": route.version if route else -1,
                "applied_version": self.applied_version,
            })
        if op == "lookup":
            self._check_epoch(meta)
            with self._lock:
                values = self.table.lookup(
                    arrays["ids"], init_missing=meta.get("init", True)
                )
                applied = self.applied_version
            return encode_msg("ok", {"applied_version": applied},
                              arrays={"values": values})
        if op == "apply":
            self._check_epoch(meta)
            with self._lock:
                self.table.apply(
                    meta["optimizer"], arrays["ids"], arrays["grads"],
                    **meta.get("kwargs", {}),
                )
                version = int(meta.get("version", 0))
                if version > self.applied_version:
                    self.applied_version = version
            return encode_msg("ok", {"rows": len(self.table)})
        if op == "import_rows":
            # migration/restore push: no epoch check — the pusher runs
            # ahead of the version bump by design
            with self._lock:
                self.table.import_(dict(arrays))
                version = int(meta.get("applied_version", 0))
                if version > self.applied_version:
                    self.applied_version = version
            return encode_msg("ok", {"rows": len(self.table)})
        if op == "export":
            with self._lock:
                snap = self.table.export(
                    min_freq=meta.get("min_freq", 0)
                )
            return encode_msg("ok", {"rows": int(snap["keys"].size)},
                              arrays=snap)
        if op == "rows":
            return encode_msg("ok", {"rows": len(self.table)})
        if op == "set_route":
            with self._lock:
                self.route = RingRoute.from_meta(meta["route"])
            return encode_msg("ok", {"version": self.route.version})
        if op == "set_applied":
            with self._lock:
                self.applied_version = int(meta["version"])
            return encode_msg("ok", {})
        if op == "migrate":
            moved = self.migrate_to(RingRoute.from_meta(meta["route"]))
            return encode_msg("ok", {
                "moved": moved, "rows": len(self.table),
            })
        if op == "commit_migration":
            pruned = self.commit_migration(
                RingRoute.from_meta(meta["route"])
            )
            return encode_msg("ok", {
                "pruned": pruned, "rows": len(self.table),
            })
        if op == "abort_migration":
            return encode_msg("ok", {"pruned": self.abort_migration()})
        if op == "prune_all":
            # rollback path for pure-new destinations of an aborted
            # scale: they sit outside the old ring, so every row they
            # received is a stray
            with self._lock:
                keys = self.table.export(with_slots=False)["keys"]
                if keys.size:
                    self.table.remove(keys)
            return encode_msg("ok", {"pruned": int(keys.size)})
        if op == "persist_prepare":
            return encode_msg("ok", self.persist_prepare(
                int(meta["step"])
            ))
        if op == "send_block":
            self.send_block(int(meta["step"]), meta["dest_addr"])
            return encode_msg("ok", {})
        if op == "hold_block":
            with self._lock:
                self._held[(int(meta["step"]), meta["owner"])] = \
                    arrays["blob"].tobytes()
            return encode_msg("ok", {})
        if op == "persist_write":
            entry = self.persist_write(
                int(meta["step"]), meta["dir"],
                int(meta["num_shards"]),
            )
            return encode_msg("ok", {"entry": entry})
        raise ShardError("bad_op", f"unknown op {op!r}")

    # ------------------------------------------------------------ migration

    def migrate_to(self, new_route: RingRoute) -> int:
        """Phase 1 of the two-phase scale: COPY every row whose owner
        under ``new_route``'s ring differs from this member to its new
        owner. Nothing is deleted and the epoch is not adopted — the
        same zero-loss protocol as the PS tier (service.py), with the
        splitmix-mod partition swapped for ring ownership. The
        ``_migrating`` gate stays armed until commit/abort; its TTL
        clock starts when the copy finishes."""
        self._migrating = True
        self._migrating_since = 0.0
        try:
            with self._lock:
                snap = self.table.export()
                keys = snap["keys"]
                moved = 0
                if keys.size:
                    owners = new_route.owner_indices(keys)
                    for dest_idx, dest in enumerate(new_route.members):
                        if dest == self.member:
                            continue
                        sel = owners == dest_idx
                        if not np.any(sel):
                            continue
                        moved += int(sel.sum())
                        _push_rows(
                            new_route.addrs[dest], {
                                "keys": keys[sel],
                                "values": snap["values"][sel],
                                "slots": snap["slots"][sel]
                                if "slots" in snap else None,
                                "freq": snap["freq"][sel],
                            }, self.dim, self.num_slots,
                            # the destination adopts the source's
                            # freshness: migrated rows must not read
                            # as applied_version 0 on serve lookups
                            meta={"applied_version":
                                  self.applied_version},
                        )
                self._migrating_since = time.monotonic()
                return moved
        except BaseException:
            self._migrating = False
            self._migrating_since = 0.0
            raise

    def commit_migration(self, new_route: RingRoute) -> int:
        """Phase 2: adopt the new epoch and PRUNE every row this member
        does not own under the new ring (idempotent, self-healing —
        also clears dormant strays of an earlier aborted scale). A
        member absent from the new ring is departing and prunes
        everything."""
        with self._lock:
            if not self._migrating:
                raise ShardError(
                    "not_migrating",
                    "no armed migration (self-aborted past TTL?); "
                    "re-run the scale",
                )
            keys = self.table.export(with_slots=False)["keys"]
            if self.member not in new_route.members:
                prune = keys
            elif keys.size:
                mine = new_route.members.index(self.member)
                prune = keys[new_route.owner_indices(keys) != mine]
            else:
                prune = keys
            if prune.size:
                self.table.remove(prune)
            self.route = new_route
            self._migrating = False
            self._migrating_since = 0.0
            return int(prune.size)

    def abort_migration(self) -> int:
        """Roll back phase 1: stay at the current epoch, prune the
        strays this member holds beyond its current-ring ownership
        (copies it received from an aborted peer push)."""
        with self._lock:
            keys = self.table.export(with_slots=False)["keys"]
            route = self.route
            if keys.size and route is not None \
                    and self.member in route.members:
                mine = route.members.index(self.member)
                strays = keys[route.owner_indices(keys) != mine]
                if strays.size:
                    self.table.remove(strays)
            else:
                strays = keys[:0]
            self._migrating = False
            self._migrating_since = 0.0
            return int(strays.size)

    # ----------------------------------------------------------- persistence

    def persist_prepare(self, step: int) -> dict:
        """Pack this member's full row set into the deterministic block
        for ``step``; parked until ``persist_write`` consumes it."""
        with self._lock:
            blob = pack_block(
                self.member, self.table.export(), self.applied_version
            )
            self._prepared[step] = blob
            return {
                "rows": len(self.table),
                "crc32": zlib.crc32(blob) & 0xFFFFFFFF,
                "bytes": len(blob),
                "applied_version": self.applied_version,
            }

    def send_block(self, step: int, dest_addr: str) -> None:
        """Push the prepared block to the ring-successor twin — the
        BYTES travel verbatim, so owner and twin write byte-identical
        copies and the manifest's per-piece CRCs agree."""
        with self._lock:
            blob = self._prepared.get(step)
        if blob is None:
            raise ShardError("not_prepared",
                             f"no prepared block for step {step}")
        host, _, port = dest_addr.rpartition(":")
        with socket.create_connection(
            (host or "127.0.0.1", int(port)), timeout=30.0
        ) as conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _call(conn, "hold_block",
                  {"step": step, "owner": self.member},
                  {"blob": np.frombuffer(blob, np.uint8)})

    def persist_write(self, step: int, ckpt_dir: str,
                      num_shards: int) -> dict:
        """Write this writer's shard file (own block + any held twin
        blocks, deterministically ordered) through
        ``CheckpointStorage.write_parallel``, plus the piece-offset
        meta the §20 ranged re-verification reads. Returns the
        manifest/ack entry."""
        with self._lock:
            own = self._prepared.pop(step, None)
            if own is None:
                raise ShardError("not_prepared",
                                 f"no prepared block for step {step}")
            blocks = [(self.member, own)]
            for (s, owner), blob in list(self._held.items()):
                if s == step:
                    blocks.append((owner, blob))
                    del self._held[(s, owner)]
                elif s < step:          # stale leftovers of a failed save
                    del self._held[(s, owner)]
            blocks.sort(key=lambda kv: kv[0])
            metas: dict[str, dict] = {}
            pieces: dict[str, dict] = {}
            off = 0
            for owner, blob in blocks:
                crc = zlib.crc32(blob) & 0xFFFFFFFF
                key = f"emb/{owner}"
                metas[key] = {"crc32": crc, "offset": off,
                              "nbytes": len(blob)}
                pieces[key] = {
                    "path": key, "index": [], "crc32": crc,
                    "replica": 0 if owner == self.member else 1,
                }
                off += len(blob)
            bin_bytes = b"".join(blob for _, blob in blocks)
            sdir = os.path.join(ckpt_dir, f"step-{step}")
            self.storage.makedirs(sdir)
            self.storage.write_parallel(
                bin_bytes, os.path.join(sdir, f"node_{self.member}.bin")
            )
            self.storage.write(
                json.dumps({"metas": metas}),
                os.path.join(sdir, f"node_{self.member}.meta.json"),
            )
            return {
                "crc32": zlib.crc32(bin_bytes) & 0xFFFFFFFF,
                "bytes": len(bin_bytes),
                "pieces": pieces,
            }


# -------------------------------------------------------------- coordinator


class FabricCoordinator(ArrayMsgServer):
    """Routing authority + scale/persist/restore driver for the ring.

    The PS tier's version-bumped coordinator, upgraded to ring
    ownership and the verified-persist protocol: ``scale`` runs the
    two-phase migration and journals ``embedding_scale`` with moved-row
    counts; ``persist`` collects prepared blocks, places twin copies,
    has every shard server write + ack, and commits the rank-0
    ``commit_w<W>`` manifest (through the master's persist-ack ledger
    under ``group="embedding"`` when a master client is attached);
    ``restore`` resolves the newest verified plan and reassembles any
    saved ring size onto the current one."""

    error_cls = ShardError

    def __init__(self, members: dict[str, str], *, dim: int,
                 num_slots: int = 2, replicas: int | None = None,
                 ckpt_dir: str = "", storage=None, master_client=None,
                 host: str = "0.0.0.0", port: int = 0):
        super().__init__(host=host, port=port, name="emb-fabric-coord")
        self.dim = dim
        self.num_slots = num_slots
        if replicas is None:
            replicas = envspec.get_int(EnvKey.EMBEDDING_REPLICAS)
        self.ckpt_dir = ckpt_dir
        self.storage = storage or PosixDiskStorage()
        self.master_client = master_client
        self.route = RingRoute(version=0, members=list(members),
                               addrs=dict(members), replicas=replicas)
        # _lock guards the route snapshot (instant holds); _scale_lock
        # serializes scale/persist/restore, which legitimately run for
        # minutes on big tables (the r04 starvation lesson)
        self._lock = threading.Lock()
        self._scale_lock = threading.Lock()
        self._link = None  # lazy agent/master_link.py degraded link

    def _master_link(self):
        """Degraded-mode link for the master-ledger coupling (§26):
        created lazily so coordinators without a master client never
        register it."""
        if self._link is None:
            from dlrover_tpu.agent.master_link import MasterLink

            self._link = MasterLink(self.master_client,
                                    component="embedding")
        return self._link

    def start(self) -> "FabricCoordinator":
        self._push_route(self.route)
        super().start()
        logger.info("fabric coordinator on port %d (%d shards, "
                    "replicas=%d)", self.port,
                    len(self.route.members), self.route.replicas)
        return self

    @property
    def addr(self) -> str:
        return f"127.0.0.1:{self.port}"

    def _handle(self, op: str, meta: dict, arrays: dict) -> bytes:
        if op == "route":
            with self._lock:
                return encode_msg("ok", {"route": self.route.to_meta()})
        if op == "scale":
            try:
                self.scale(dict(meta["members"]))
            except Exception as e:  # noqa: BLE001 - report to caller
                raise ShardError(
                    "scale_failed", f"{type(e).__name__}: {e}"
                ) from e
            with self._lock:
                return encode_msg("ok", {"route": self.route.to_meta()})
        if op == "persist":
            try:
                info = self.persist(int(meta["step"]),
                                    meta.get("dir") or None)
            except Exception as e:  # noqa: BLE001 - report to caller
                raise ShardError(
                    "persist_failed", f"{type(e).__name__}: {e}"
                ) from e
            return encode_msg("ok", info)
        if op == "restore":
            try:
                info = self.restore(meta.get("dir") or None)
            except Exception as e:  # noqa: BLE001 - report to caller
                raise ShardError(
                    "restore_failed", f"{type(e).__name__}: {e}"
                ) from e
            return encode_msg("ok", {"restored": info})
        if op == "repair":
            try:
                info = self.repair(meta["member"], meta["addr"])
            except Exception as e:  # noqa: BLE001 - report to caller
                raise ShardError(
                    "repair_failed", f"{type(e).__name__}: {e}"
                ) from e
            return encode_msg("ok", info)
        raise ShardError("bad_op", f"unknown op {op!r}")

    # ------------------------------------------------------------- plumbing

    def _shard_call(self, addr: str, op: str, meta: dict | None = None,
                    arrays: dict | None = None,
                    timeout: float | None = 60.0):
        host, _, port = addr.rpartition(":")
        with socket.create_connection(
            (host or "127.0.0.1", int(port)), timeout=timeout
        ) as conn:
            return _call(conn, op, meta, arrays)

    def _retry_shard_call(self, addr: str, op: str, meta: dict,
                          retries: int = 3, backoff_s: float = 0.5,
                          timeout: float | None = 60.0) -> dict:
        last: Exception | None = None
        for attempt in range(max(1, retries)):
            try:
                rmeta, _ = self._shard_call(addr, op, meta,
                                            timeout=timeout)
                return rmeta
            except (ShardError, ConnectionError, OSError) as e:
                last = e
                logger.warning("%s to %s failed (attempt %d/%d): %s",
                               op, addr, attempt + 1, retries, e)
                time.sleep(backoff_s * (attempt + 1))
        raise RuntimeError(f"{op} to {addr} failed after "
                           f"{retries} attempts: {last}")

    def _push_route(self, route: RingRoute) -> None:
        for member in route.members:
            self._shard_call(route.addrs[member], "set_route",
                             {"route": route.to_meta()})

    def total_rows(self) -> int:
        with self._lock:
            route = self.route
        return sum(
            self._shard_call(route.addrs[m], "rows")[0]["rows"]
            for m in route.members
        )

    # ----------------------------------------------------------------- scale

    def scale(self, new_members: dict[str, str],
              migrate_retries: int = 3) -> RingRoute:
        """Re-partition the ring onto ``new_members`` (member id ->
        addr), failure-atomically: COPY (zero-loss, rolled back on
        failure), then COMMIT (rolled forward). Journals
        ``embedding_scale`` with the moved-row count — the number the
        ~1/N migration bound is asserted against."""
        with self._scale_lock:
            with self._lock:
                old = self.route
            new_route = RingRoute(
                version=old.version + 1,
                members=list(new_members), addrs=dict(new_members),
                replicas=old.replicas, vnodes=old.vnodes,
            )
            t0 = time.monotonic()
            total_before = sum(
                self._shard_call(old.addrs[m], "rows")[0]["rows"]
                for m in old.members
            )
            moved = 0
            try:
                for member in old.members:
                    rmeta = self._retry_shard_call(
                        old.addrs[member], "migrate",
                        {"route": new_route.to_meta()},
                        migrate_retries, timeout=None,
                    )
                    moved += int(rmeta["moved"])
                    logger.info("fabric shard %s copied %d rows",
                                member, rmeta["moved"])
                # pure-new members adopt first: they only gained rows,
                # so a failure here still rolls back cleanly
                for member in new_route.members:
                    if member not in old.members:
                        self._retry_shard_call(
                            new_route.addrs[member], "set_route",
                            {"route": new_route.to_meta()},
                            migrate_retries,
                        )
            except Exception:
                self._rollback(old, new_route)
                get_journal().emit(
                    "embedding_scale", from_n=len(old.members),
                    to_n=len(new_route.members), moved=moved,
                    version=new_route.version, ok=False,
                )
                raise
            # commit the old members — from here failures roll FORWARD
            for member in old.members:
                self._retry_shard_call(
                    old.addrs[member], "commit_migration",
                    {"route": new_route.to_meta()}, migrate_retries,
                )
            with self._lock:
                self.route = new_route
            _scale_moved_rows_total.inc(moved)
            get_journal().emit(
                "embedding_scale", from_n=len(old.members),
                to_n=len(new_route.members), moved=moved,
                total_rows=total_before, version=new_route.version,
                ok=True, dur=time.monotonic() - t0,
            )
            return new_route

    def _rollback(self, old: RingRoute, new_route: RingRoute) -> None:
        for member in old.members:
            try:
                self._shard_call(old.addrs[member], "abort_migration")
            except Exception:  # noqa: BLE001 - best effort
                logger.warning("abort_migration to %s failed", member)
        for member in new_route.members:
            if member in old.members:
                continue
            try:
                self._shard_call(new_route.addrs[member], "prune_all")
            except Exception:  # noqa: BLE001 - best effort
                logger.warning("prune_all to %s failed", member)

    # --------------------------------------------------------------- persist

    def persist(self, step: int, ckpt_dir: str | None = None) -> dict:
        """Verified shard checkpoint of the whole ring at ``step``.

        The caller owns the drain barrier (``FabricClient.drain()`` /
        ``persist_fabric``): the fabric cannot see un-flushed client
        queues, so snapshotting without draining would save
        update-incomplete state."""
        ckpt_dir = ckpt_dir or self.ckpt_dir
        if not ckpt_dir:
            raise ValueError("no checkpoint directory configured")
        with self._scale_lock:
            with self._lock:
                route = self.route
            W = len(route.members)
            t0 = time.monotonic()
            prepared: dict[str, dict] = {}
            for member in route.members:
                rmeta, _ = self._shard_call(
                    route.addrs[member], "persist_prepare",
                    {"step": step},
                )
                prepared[member] = rmeta
            if route.replicas >= 2 and W >= 2:
                for member in route.members:
                    self._shard_call(
                        route.addrs[member], "send_block",
                        {"step": step,
                         "dest_addr": route.addrs[route.twin(member)]},
                    )
            shards: dict[str, dict] = {}
            for member in route.members:
                rmeta, _ = self._shard_call(
                    route.addrs[member], "persist_write",
                    {"step": step, "dir": ckpt_dir, "num_shards": W},
                    timeout=None,
                )
                shards[member] = dict(rmeta["entry"])
            applied = max(
                int(p.get("applied_version", 0))
                for p in prepared.values()
            )
            rows = sum(int(p.get("rows", 0)) for p in prepared.values())
            # every shard server acks the master's persist ledger (the
            # §20 commit path, namespaced group="embedding"); the
            # commit manifest is then assembled from the ledger so a
            # writer that died before acking keeps the step invisible.
            # A master OUTAGE must not fail the persist (§26): the
            # coordinator collected every writer's entry synchronously
            # above — its local map is ground truth — so it commits
            # from that, journals degraded mode, and the queued acks
            # replay when the master returns.
            if self.master_client is not None:
                try:
                    for member, entry in shards.items():
                        self.master_client.report_persist_ack(
                            step, W, entry, writer_id=member,
                            group="embedding",
                        )
                    status = self.master_client.persist_status(
                        step, W, group="embedding"
                    )
                    if status.complete:
                        shards = {m: dict(e)
                                  for m, e in status.shards.items()}
                        self._master_link().ok()
                    else:
                        # acks were queued for redelivery (outage) or
                        # the restarted master's ledger is catching up:
                        # the local map stands
                        logger.warning(
                            "persist ledger incomplete (%d/%d acks for "
                            "step %d); committing from the "
                            "coordinator's local manifest",
                            status.acked, W, step,
                        )
                except (ConnectionError, TimeoutError, OSError,
                        RuntimeError) as e:
                    self._master_link().failed(e)
            sdir = os.path.join(ckpt_dir, f"step-{step}")
            integrity.write_commit(
                self.storage, sdir, step, W, shards,
                group="embedding",
                extra={
                    "kind": "embedding", "dim": self.dim,
                    "num_slots": self.num_slots,
                    "members": list(route.members),
                    "replicas": route.replicas,
                    "applied_version": applied,
                },
            )
            self.storage.write(
                json.dumps({"step": step, "num_shards": W}),
                os.path.join(ckpt_dir, "latest"),
            )
            info = {"step": step, "num_shards": W, "rows": rows,
                    "applied_version": applied}
            get_journal().emit("embedding_persist", step=step,
                               num_shards=W, rows=rows,
                               dur=time.monotonic() - t0)
            return info

    # --------------------------------------------------------------- restore

    def restore(self, ckpt_dir: str | None = None) -> dict | None:
        """Restore the newest VERIFIED fabric checkpoint onto the
        CURRENT ring (any saved ring size; optimizer slots + frequency
        row-exact). Runs §20 quorum semantics: a corrupt shard file
        whose block verifies in its ring-successor twin's file restores
        from the twin (``ckpt_shard_rollback``); a step with an
        uncovered block rolls back whole-step to the newest verified
        one. Returns None when nothing restorable exists."""
        ckpt_dir = ckpt_dir or self.ckpt_dir
        if not ckpt_dir:
            raise ValueError("no checkpoint directory configured")
        with self._scale_lock:
            loaded = self._load_checkpoint(ckpt_dir)
            if loaded is None:
                return None
            plan, manifest, keys, rows = loaded
            with self._lock:
                route = self.route
            owners = route.owner_indices(keys)
            applied = int(manifest.get("applied_version", 0))
            for idx, member in enumerate(route.members):
                sel = owners == idx
                if not np.any(sel):
                    continue
                _push_rows(
                    route.addrs[member],
                    {"keys": keys[sel],
                     **{k: v[sel] for k, v in rows.items()}},
                    self.dim, self.num_slots,
                    meta={"applied_version": applied},
                )
            for member in route.members:
                self._shard_call(route.addrs[member], "set_applied",
                                 {"version": applied})
            info = {"step": plan.step, "rows": int(keys.size),
                    "applied_version": applied,
                    "saved_members": list(manifest.get("members", [])),
                    "num_shards": plan.num_shards}
            get_journal().emit(
                "embedding_restore", step=plan.step,
                rows=int(keys.size), from_w=plan.num_shards,
                to_w=len(route.members),
            )
            return info

    def _load_checkpoint(self, ckpt_dir: str):
        """(plan, manifest, keys, row arrays) of the newest VERIFIED
        embedding checkpoint, or None with nothing restorable."""
        plan = integrity.resolve_restore_plan(self.storage, ckpt_dir)
        if plan is None:
            return None
        sdir = os.path.join(ckpt_dir, f"step-{plan.step}")
        manifest = json.loads(self.storage.read_text(os.path.join(
            sdir, integrity.commit_marker(plan.num_shards)
        )))
        if manifest.get("kind") != "embedding":
            raise ValueError(
                f"step {plan.step} is not an embedding checkpoint"
            )
        blocks = self._read_blocks(sdir, manifest, plan)
        keys = np.concatenate([b["keys"] for b in blocks])
        rows = {
            name: np.concatenate([b[name] for b in blocks])
            for name in ("values", "slots", "freq")
            if all(name in b for b in blocks)
        }
        return plan, manifest, keys, rows

    # ---------------------------------------------------------------- repair

    def repair(self, member: str, new_addr: str,
               ckpt_dir: str | None = None) -> dict:
        """Replace a DEAD shard server: same ring membership (ownership
        does not move), ``member`` re-homed to ``new_addr`` under a
        bumped route version (every client re-dials), and ONLY the dead
        member's rows refilled from the newest verified checkpoint —
        the surviving shards keep their live (possibly newer) rows, so
        the blast radius of a shard-server loss is one shard's
        since-last-checkpoint updates, not the ring."""
        ckpt_dir = ckpt_dir or self.ckpt_dir
        with self._scale_lock:
            with self._lock:
                old = self.route
            if member not in old.members:
                raise ValueError(f"{member!r} is not a ring member")
            addrs = dict(old.addrs)
            addrs[member] = new_addr
            new_route = RingRoute(
                version=old.version + 1, members=list(old.members),
                addrs=addrs, replicas=old.replicas, vnodes=old.vnodes,
            )
            t0 = time.monotonic()
            self._push_route(new_route)
            with self._lock:
                self.route = new_route
            restored_rows = 0
            step = None
            if ckpt_dir:
                loaded = self._load_checkpoint(ckpt_dir)
                if loaded is not None:
                    plan, manifest, keys, rows = loaded
                    applied = int(manifest.get("applied_version", 0))
                    mine = new_route.members.index(member)
                    sel = new_route.owner_indices(keys) == mine
                    if np.any(sel):
                        _push_rows(
                            new_addr,
                            {"keys": keys[sel],
                             **{k: v[sel] for k, v in rows.items()}},
                            self.dim, self.num_slots,
                            meta={"applied_version": applied},
                        )
                    self._shard_call(new_addr, "set_applied",
                                     {"version": applied})
                    restored_rows = int(sel.sum())
                    step = plan.step
            get_journal().emit(
                "embedding_repair", member=member, step=step,
                rows=restored_rows, version=new_route.version,
                dur=time.monotonic() - t0,
            )
            return {"member": member, "rows": restored_rows,
                    "step": step, "version": new_route.version}

    def _read_blocks(self, sdir: str, manifest: dict, plan) -> list[dict]:
        """One verified block per saved member, preferring the primary
        writer and falling back to any writer whose copy of the piece
        the restore plan did not condemn."""
        shards: dict[str, dict] = dict(manifest.get("shards", {}))
        out: list[dict] = []
        for member in manifest.get("members", []):
            key = f"emb/{member}"
            block = None
            # primary writer first, then every twin holder
            writers = sorted(
                (w for w, e in shards.items()
                 if key in (e or {}).get("pieces", {})),
                key=lambda w: (w != member, w),
            )
            for writer in writers:
                bad = plan.bad_pieces.get(writer, set())
                if bad is None or (bad and key in bad):
                    continue
                try:
                    block = self._read_piece(sdir, writer, key)
                    break
                except (OSError, ValueError) as e:
                    logger.warning(
                        "block %s unreadable from writer %s: %s",
                        key, writer, e,
                    )
            if block is None:
                raise OSError(
                    f"no verified copy of block {key} in {sdir}"
                )
            meta, arrays = unpack_block(block)
            out.append(arrays)
        return out

    def _read_piece(self, sdir: str, writer: str, key: str) -> bytes:
        header = json.loads(self.storage.read_text(
            os.path.join(sdir, f"node_{writer}.meta.json")
        ))
        info = header["metas"][key]
        blob = self.storage.read_range(
            os.path.join(sdir, f"node_{writer}.bin"),
            int(info["offset"]), int(info["nbytes"]),
        )
        if len(blob) != int(info["nbytes"]) \
                or zlib.crc32(blob) & 0xFFFFFFFF != int(info["crc32"]):
            raise ValueError(f"piece {key} of writer {writer} corrupt")
        return blob


# ------------------------------------------------------------------- client


@dataclasses.dataclass
class _ApplyItem:
    version: int
    optimizer: str
    ids: np.ndarray
    grads: np.ndarray
    kwargs: dict
    t_enqueue: float


class FabricClient:
    """Ring-routed table client: the KvEmbeddingTable surface over the
    fabric, with async gradient streaming in ``mode="train"`` and a
    read-only, version-pinned view in ``mode="serve"``.

    Train mode: ``apply`` enqueues and returns; the flusher thread
    streams batches shard-ward in order. ``drain()`` is the checkpoint
    barrier. The staleness bound back-pressures ``apply`` (the step
    blocks) once the flusher falls more than
    ``DLROVER_TPU_EMBEDDING_MAX_STALENESS`` versions behind.

    Serve mode: lookups never materialize missing rows and each call
    stamps the applied training version of the touched shards
    (``last_lookup_info``) so responses carry their freshness.
    """

    def __init__(self, coordinator_addr: str | None = None,
                 route: RingRoute | None = None, dim: int = 0, *,
                 mode: str = "train", async_apply: bool | None = None,
                 max_staleness: int | None = None,
                 flush_ms: float | None = None,
                 queue_batches: int | None = None,
                 timeout: float = 30.0, retry_window_s: float = 600.0):
        if not coordinator_addr and route is None:
            raise ValueError("need coordinator_addr or route")
        if mode not in ("train", "serve"):
            raise ValueError(f"unknown mode {mode!r}")
        self.dim = dim
        self.mode = mode
        self._timeout = timeout
        self.retry_window_s = retry_window_s
        self._coord_addr = coordinator_addr
        self._route = route
        self._tls = threading.local()
        self._sock_gen = 0
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="emb-fabric-client"
        )
        self._step = 0
        self._applied = 0
        self._queue: deque[_ApplyItem] = deque()
        self._flush_error: Exception | None = None
        self._closed = False
        self.last_lookup_info: dict = {}
        if max_staleness is None:
            max_staleness = envspec.get_int(
                EnvKey.EMBEDDING_MAX_STALENESS
            )
        self.max_staleness = max(1, int(max_staleness))
        if flush_ms is None:
            flush_ms = envspec.get_float(EnvKey.EMBEDDING_FLUSH_MS)
        self._flush_s = max(0.0005, float(flush_ms) / 1000.0)
        if queue_batches is None:
            queue_batches = envspec.get_int(EnvKey.EMBEDDING_QUEUE)
        self.queue_batches = max(1, int(queue_batches))
        if coordinator_addr:
            self.refresh_route()
        self._async = (mode == "train"
                       and (async_apply is None or async_apply))
        self._flusher: threading.Thread | None = None
        if self._async:
            self._flusher = threading.Thread(
                target=self._flush_loop, daemon=True,
                name="emb-fabric-flusher",
            )
            self._flusher.start()

    # ------------------------------------------------------------- plumbing

    def refresh_route(self) -> None:
        host, _, port = self._coord_addr.rpartition(":")
        with socket.create_connection(
            (host or "127.0.0.1", int(port)), timeout=self._timeout
        ) as conn:
            meta, _ = _call(conn, "route")
        with self._lock:
            self._route = RingRoute.from_meta(meta["route"])
            # bump the socket generation: every worker thread re-dials
            # lazily, so stale sockets to drained servers die here too
            self._sock_gen += 1

    @property
    def route(self) -> RingRoute:
        with self._lock:
            return self._route

    @property
    def version(self) -> int:
        return self.route.version

    def _sock_for(self, addr: str) -> socket.socket:
        # per-worker-thread connection maps: lookups (caller thread
        # pool) and the flusher fan out concurrently, and two frames
        # interleaved on one socket would corrupt the protocol
        tls = self._tls
        with self._lock:
            gen = self._sock_gen
        if getattr(tls, "gen", None) != gen:
            for s in getattr(tls, "socks", {}).values():
                try:
                    s.close()
                except OSError:
                    pass
            tls.socks = {}
            tls.gen = gen
        s = tls.socks.get(addr)
        if s is None:
            host, _, port = addr.rpartition(":")
            s = socket.create_connection(
                (host or "127.0.0.1", int(port)), timeout=self._timeout
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            tls.socks[addr] = s
        return s

    def _evict_sock(self, addr: str) -> None:
        s = getattr(self._tls, "socks", {}).pop(addr, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _shard_call(self, addr: str, op: str, meta: dict,
                    arrays: dict) -> tuple[dict, dict]:
        try:
            return _call(self._sock_for(addr), op, meta, arrays)
        except (ConnectionError, OSError):
            # evict + one immediate re-dial (dead/drained server); a
            # second failure evicts again so the retry loop dials fresh
            self._evict_sock(addr)
            try:
                return _call(self._sock_for(addr), op, meta, arrays)
            except (ConnectionError, OSError):
                self._evict_sock(addr)
                raise

    def _fanout(self, op: str, ids: np.ndarray,
                per_shard_arrays: Callable,
                meta_extra: dict | None = None):
        """Ring-owner fan-out with per-id retry completion (the §25
        twin of the PS tier's ``_fanout``): version errors and
        migrating gates re-route under a refreshed route; only the ids
        whose shard call failed are re-sent."""
        flat = np.ascontiguousarray(ids, np.int64).reshape(-1)
        pending = np.ones(flat.size, dtype=bool)
        results: list[tuple[np.ndarray, dict, dict]] = []
        last: Exception | None = None
        deadline = time.monotonic() + self.retry_window_s
        backoff = 0.25
        while True:
            route = self.route
            idxs = np.nonzero(pending)[0]
            owners = route.owner_indices(flat[idxs])
            futures = []
            for s, member in enumerate(route.members):
                sel = idxs[owners == s]
                if sel.size == 0:
                    continue
                meta = {"v": route.version, **(meta_extra or {})}
                arrays = per_shard_arrays(flat[sel], sel)
                futures.append((sel, self._pool.submit(
                    self._shard_call, route.addrs[member], op, meta,
                    arrays,
                )))
            for sel, fut in futures:
                try:
                    rmeta, rarrays = fut.result()
                    results.append((sel, rmeta, rarrays))
                    pending[sel] = False
                except ShardError as e:
                    last = e
                    if e.code not in ("version", "migrating"):
                        raise
                except (ConnectionError, OSError) as e:
                    last = e
            if not pending.any():
                return results, flat
            if time.monotonic() >= deadline:
                break
            time.sleep(backoff)
            backoff = min(backoff * 1.5, 2.0)
            if self._coord_addr:
                try:
                    self.refresh_route()
                except (ShardError, ConnectionError, OSError) as e:
                    last = e  # coordinator busy/unreachable: retry
        raise RuntimeError(
            f"embedding fabric fanout kept failing after "
            f"{self.retry_window_s:.0f}s: {last}"
        )

    # ------------------------------------------------------------- user ops

    def lookup(self, ids: np.ndarray, init_missing: bool = True
               ) -> np.ndarray:
        values, _info = self.lookup_with_info(ids, init_missing)
        return values

    def lookup_with_info(self, ids: np.ndarray,
                         init_missing: bool = True
                         ) -> tuple[np.ndarray, dict]:
        """Gather + freshness info. Serve-mode lookups never create
        rows regardless of ``init_missing`` (a read path must not
        mutate the model); the info dict stamps the routing version and
        the applied training version of the touched shards (min = the
        step every returned row is guaranteed to reflect)."""
        if self.mode == "serve":
            init_missing = False
        _lookups_total.labels(self.mode).inc()
        flat_shape = np.shape(ids)
        parts, flat = self._fanout(
            "lookup", ids,
            lambda shard_ids, sel: {"ids": shard_ids},
            meta_extra={"init": init_missing},
        )
        out = np.empty((flat.size, self.dim), np.float32)
        applied = []
        for sel, rmeta, rarrays in parts:
            out[sel] = rarrays["values"]
            applied.append(int(rmeta.get("applied_version", 0)))
        info = {
            "version": self.version,
            "applied_version": min(applied) if applied else 0,
            "applied_version_max": max(applied) if applied else 0,
        }
        info["staleness"] = (info["applied_version_max"]
                             - info["applied_version"])
        self.last_lookup_info = info
        return out.reshape(*flat_shape, self.dim), info

    def apply(self, optimizer: str, ids: np.ndarray,
              grads: np.ndarray, **kwargs) -> None:
        """Sparse update. Async (default in train mode): enqueue and
        return, back-pressuring once the flusher is more than
        ``max_staleness`` versions behind or the queue is full."""
        if self.mode != "train":
            raise RuntimeError("serve-mode clients are read-only")
        g = np.ascontiguousarray(grads, np.float32).reshape(-1, self.dim)
        flat = np.ascontiguousarray(ids, np.int64).reshape(-1)
        if g.shape[0] != flat.size:
            raise ValueError(
                f"{flat.size} ids but {g.shape[0]} gradient rows"
            )
        with self._cond:
            if self._flush_error is not None:
                raise RuntimeError(
                    "embedding flusher died"
                ) from self._flush_error
            version = self._step + 1
            self._step = version
        if optimizer in ("adam", "group_adam", "radam"):
            kwargs.setdefault("step", version)
        item = _ApplyItem(version, optimizer, flat, g, dict(kwargs),
                          time.monotonic())
        if not self._async:
            self._flush_item(item)
            with self._cond:
                self._applied = version
            return
        blocked = False
        with self._cond:
            while (not self._closed and self._flush_error is None
                   and (version - self._applied > self.max_staleness
                        or len(self._queue) >= self.queue_batches)):
                if not blocked:
                    blocked = True
                    _backpressure_total.inc()
                self._cond.wait(0.05)
            if self._flush_error is not None:
                raise RuntimeError(
                    "embedding flusher died"
                ) from self._flush_error
            if self._closed:
                raise RuntimeError("client is closed")
            self._queue.append(item)
            _flush_queue_depth.set(len(self._queue))
            _staleness_steps.set(self._step - self._applied)
            self._cond.notify_all()

    def _flush_item(self, item: _ApplyItem) -> None:
        self._fanout(
            "apply", item.ids,
            lambda shard_ids, sel: {"ids": shard_ids,
                                    "grads": item.grads[sel]},
            meta_extra={"optimizer": item.optimizer,
                        "kwargs": item.kwargs,
                        "version": item.version},
        )

    def _flush_loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(self._flush_s)
                if self._closed and not self._queue:
                    return
                item = self._queue.popleft()
                _flush_queue_depth.set(len(self._queue))
            try:
                self._flush_item(item)
            except Exception as e:  # noqa: BLE001 - surface to apply/drain
                logger.error("embedding flusher died: %s", e)
                with self._cond:
                    self._flush_error = e
                    self._cond.notify_all()
                return
            with self._cond:
                self._applied = item.version
                _apply_lag_seconds.observe(
                    time.monotonic() - item.t_enqueue
                )
                _staleness_steps.set(
                    max(0, self._step - self._applied)
                )
                self._cond.notify_all()

    def drain(self, timeout: float | None = None) -> bool:
        """The checkpoint barrier: block until every enqueued apply has
        been flushed to the shard servers, so a snapshot taken after a
        successful drain is update-complete. Returns False on timeout;
        raises if the flusher died (those gradients are NOT durable)."""
        deadline = None if timeout is None \
            else time.monotonic() + timeout
        with self._cond:
            while self._queue or self._applied < self._step:
                if self._flush_error is not None:
                    raise RuntimeError(
                        "embedding flusher died with updates queued"
                    ) from self._flush_error
                if deadline is not None \
                        and time.monotonic() >= deadline:
                    return False
                self._cond.wait(0.05)
        return True

    def staleness(self) -> int:
        with self._cond:
            return max(0, self._step - self._applied)

    def resume_from(self, applied_version: int) -> None:
        """Adopt a restored checkpoint's applied version so post-resume
        applies continue the version sequence (Adam step counters and
        staleness accounting stay monotonic)."""
        with self._cond:
            self._step = max(self._step, int(applied_version))
            self._applied = max(self._applied, int(applied_version))

    # --------------------------------------------------- coordinator bridge

    def _coord_call(self, op: str, meta: dict | None = None) -> dict:
        host, _, port = self._coord_addr.rpartition(":")
        with socket.create_connection(
            (host or "127.0.0.1", int(port)), timeout=self._timeout
        ) as conn:
            rmeta, _ = _call(conn, op, meta)
        return rmeta

    def persist(self, step: int, timeout: float | None = None) -> dict:
        """Drain barrier + coordinator-driven verified checkpoint."""
        if not self.drain(timeout):
            raise TimeoutError(
                "drain did not complete before the checkpoint"
            )
        return self._coord_call("persist", {"step": step})

    def row_count(self) -> int:
        route = self.route
        total = 0
        for member in route.members:
            rmeta, _ = self._shard_call(route.addrs[member], "rows",
                                        {}, {})
            total += rmeta["rows"]
        return total

    def __len__(self) -> int:
        return self.row_count()

    def export(self, min_freq: int = 0, with_slots: bool = True
               ) -> dict[str, np.ndarray]:
        """KvEmbeddingTable-compatible full-table snapshot."""
        route = self.route
        snaps = []
        for member in route.members:
            _, arrays = self._shard_call(route.addrs[member], "export",
                                         {"min_freq": min_freq}, {})
            snaps.append(arrays)
        out: dict[str, np.ndarray] = {}
        for k in ("keys", "values", "slots", "freq"):
            if all(k in s for s in snaps):
                out[k] = np.concatenate([s[k] for s in snaps])
        if not with_slots:
            out.pop("slots", None)
        return out

    def close(self) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        if self._flusher is not None:
            self._flusher.join(timeout=5.0)
        self._pool.shutdown(wait=False)
        for s in getattr(self._tls, "socks", {}).values():
            try:
                s.close()
            except OSError:
                pass


# ------------------------------------------------------------- conveniences


def start_local_fabric(n: int, *, dim: int, num_slots: int = 2,
                       seed: int = 0, replicas: int | None = None,
                       ckpt_dir: str = "", master_client=None,
                       host: str = "127.0.0.1"
                       ) -> tuple[FabricCoordinator,
                                  list[FabricShardServer]]:
    """In-process ring of ``n`` shard servers + coordinator (tests,
    bench, the single-host example). Member ids are ``emb-<i>`` —
    stable across runs, so row placement and scale-event moved counts
    are deterministic."""
    servers = [
        FabricShardServer(
            dim=dim, num_slots=num_slots, member=f"emb-{i}",
            seed=seed, host=host,
        ).start()
        for i in range(n)
    ]
    members = {s.member: s.addr for s in servers}
    coord = FabricCoordinator(
        members, dim=dim, num_slots=num_slots, replicas=replicas,
        ckpt_dir=ckpt_dir, master_client=master_client, host=host,
    ).start()
    return coord, servers
