"""Multi-host sharded embedding service: the elastic-PS analog.

Reference analog: DLRover's elastic parameter servers for sparse models —
tables sharded across PS processes with runtime scaling
(dlrover/python/master/elastic_training/elastic_ps.py:82 version-bumped
PS cluster, master/node/job_auto_scaler.py:98 PSTrainingAutoScaler) over
tfplus's hybrid embedding storage
(tfplus/kv_variable/kernels/hybrid_embedding/table_manager.h:1). That is
the one reference capability a single-process KvEmbeddingTable cannot
represent: a table bigger than one host's RAM, or a scale event that
re-partitions rows.

TPU-native shape: the dense tower trains under jit on the chips; the
unbounded sparse rows live in N *embedding shard servers* (each wrapping
the native C++ table, embedding/kv_table.py). The trainer's
``ShardedKvClient`` routes each batch's ids by a stable key hash,
gathers/updates over the repo's no-pickle length-prefixed TCP framing
(common/rpc.py), and presents the same lookup/apply surface as the local
table so the recsys training loop is unchanged.

Elasticity follows the reference's *versioned cluster* design
(elastic_ps.py: workers watch a version and rebuild): every request
carries the routing version; a scale event migrates rows server→server
(each old owner pushes the rows whose new owner differs), then bumps the
version. A client holding stale routing gets a structured version error,
refetches the route from the coordinator, and retries — training blocks
briefly instead of losing updates.

Wire protocol (hot path, so raw arrays rather than JSON floats): one
frame = JSON header (op, meta, array manifest) + concatenated raw array
bytes, inside the common/rpc length-prefixed frame.
"""

from __future__ import annotations

import argparse
import os
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Iterable

import numpy as np

from dlrover_tpu import chaos

# decode_msg is re-exported: tests and tools treat this module as the
# wire-protocol surface for the embedding tier
from dlrover_tpu.common.array_wire import decode_msg, encode_msg  # noqa: F401
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.msg_server import (
    ArrayMsgServer,
    MsgError,
    call_msg,
)
from dlrover_tpu.embedding.kv_table import (
    IncrementalCheckpointManager,
    KvEmbeddingTable,
)

logger = get_logger(__name__)

# rows per migration push: bounded so one frame stays well under
# rpc.MAX_FRAME even for wide tables with optimizer slots
_MIGRATE_CHUNK_BYTES = 8 << 20


def shard_owner(ids: np.ndarray, num_shards: int) -> np.ndarray:
    """Stable owner shard per id: splitmix64 finalizer then mod — raw
    ``id % n`` would put every hot contiguous id range on one server."""
    x = np.asarray(ids, dtype=np.uint64)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    x = x ^ (x >> np.uint64(31))
    return (x % np.uint64(num_shards)).astype(np.int64)


class ShardError(MsgError):
    pass


def _apply_msg_fault(fault, sock: socket.socket) -> None:
    """Injected embedding-transport faults (chaos plan ``embedding_msg``
    point). The embedding tier's raw-array TCP framing bypasses
    ``RpcClient``, so the PR-4 ``rpc_call`` rules never touch it —
    this point closes that blind spot at the one client-side choke
    point every lookup/apply/migration push goes through.

    ``delay`` sleeps before sending (a congested link), ``drop`` loses
    the request before it hits the wire (the server never sees it),
    ``reset`` kills the connection mid-exchange (server death /
    conntrack reset — the socket is poisoned and must be re-dialed),
    ``garble`` poisons the stream with a corrupt frame (the server
    closes it; protocol state is unrecoverable on this socket).
    """
    if fault.action == "delay":
        time.sleep(float(fault.args.get("s", 0.2)))
        return
    if fault.action == "drop":
        raise ConnectionError("chaos: dropped embedding message")
    if fault.action == "reset":
        try:
            sock.close()
        except OSError:
            pass
        raise ConnectionResetError("chaos: embedding connection reset")
    if fault.action == "garble":
        from dlrover_tpu.common.rpc import send_frame

        try:
            send_frame(sock, b"\x00garbled-embedding-frame")
        except OSError:
            pass
        raise ConnectionError("chaos: garbled embedding frame")


def _call(sock: socket.socket, op: str, meta: dict | None = None,
          arrays: dict | None = None) -> tuple[dict, dict]:
    if chaos.ENABLED:
        # the trainer->shard partition site (§30): like the rack tier,
        # the embedding framing bypasses RpcClient, so the link-level
        # net_partition rules need their own hook here
        from dlrover_tpu.chaos import partition as net_partition

        if net_partition.check("trainer", "shard", op=op) is not None:
            raise ConnectionError(
                "chaos: net partition open (trainer->shard)"
            )
        fault = chaos.fire("embedding_msg", op=op)
        if fault is not None:
            _apply_msg_fault(fault, sock)
    return call_msg(sock, op, meta, arrays, error_cls=ShardError)


class EmbeddingShardServer(ArrayMsgServer):
    """One embedding PS shard: a native KvEmbeddingTable behind TCP
    (accept/dispatch scaffolding in common/msg_server.py).

    Owns rows with ``shard_owner(id, num_shards) == index`` at the
    current routing version. ``migrate_to`` re-partitions under a new
    epoch, pushing rows to their new owners (the PS migration analog).
    """

    error_cls = ShardError

    def __init__(self, dim: int, num_slots: int = 2, *, seed: int = 0,
                 host: str = "0.0.0.0", port: int = 0,
                 version: int = 0, num_shards: int = 1, index: int = 0,
                 ckpt_dir: str = "", base_interval: int = 10):
        super().__init__(host=host, port=port,
                         name=f"emb-shard-{index}")
        self.table = KvEmbeddingTable(dim=dim, num_slots=num_slots,
                                      seed=seed + 7919 * index)
        self.dim = dim
        self.num_slots = num_slots
        self.version = version
        self.num_shards = num_shards
        self.index = index
        self._ckpt_dir = ckpt_dir
        self._base_interval = base_interval
        self._ckpt: IncrementalCheckpointManager | None = None
        # one lock serializes table mutations against migration: the
        # native table is internally thread-safe, but a migrate must see
        # a frozen row set while it repartitions
        self._lock = threading.Lock()
        self._migrating = False
        # liveness escape: a coordinator that dies between copy and
        # commit would otherwise leave the gate armed forever. After
        # the TTL the server self-aborts (safe: phase 1 deleted
        # nothing); a commit arriving later is rejected (gate no longer
        # armed) so the coordinator's retry re-runs the whole scale.
        self._migrating_since = 0.0
        self.migrate_ttl_s = 1800.0

    def start(self) -> "EmbeddingShardServer":
        super().start()
        logger.info(
            "embedding shard %d/%d v%d serving on port %d",
            self.index, self.num_shards, self.version, self.port,
        )
        return self

    # ------------------------------------------------------------- dispatch

    def _check_epoch(self, meta: dict) -> None:
        if self._migrating:
            if (self._migrating_since
                    and time.monotonic() - self._migrating_since
                    > self.migrate_ttl_s):
                logger.warning(
                    "migration armed > %.0fs with no commit/abort "
                    "(dead coordinator?); self-aborting to restore "
                    "service", self.migrate_ttl_s,
                )
                self.abort_migration()
            else:
                raise ShardError("migrating",
                                 "shard is re-partitioning",
                                 {"retry_ms": 100})
        v = meta.get("v")
        if v is not None and v != self.version:
            raise ShardError(
                "version",
                f"client routing v{v} != shard v{self.version}",
                {"current": self.version},
            )

    def _handle(self, op: str, meta: dict, arrays: dict) -> bytes:
        if op == "ping":
            return encode_msg("ok", {
                "version": self.version, "num_shards": self.num_shards,
                "index": self.index, "rows": len(self.table),
            })
        if op == "lookup":
            self._check_epoch(meta)
            with self._lock:
                values = self.table.lookup(
                    arrays["ids"], init_missing=meta.get("init", True)
                )
            return encode_msg("ok", arrays={"values": values})
        if op == "apply":
            self._check_epoch(meta)
            with self._lock:
                self.table.apply(
                    meta["optimizer"], arrays["ids"], arrays["grads"],
                    **meta.get("kwargs", {}),
                )
            return encode_msg("ok", {"rows": len(self.table)})
        if op == "import_rows":
            # migration push from a peer (or a bulk load): no epoch check
            # — the pusher is mid-migration ahead of the version bump
            with self._lock:
                self.table.import_(dict(arrays))
            return encode_msg("ok", {"rows": len(self.table)})
        if op == "export":
            with self._lock:
                snap = self.table.export(
                    min_freq=meta.get("min_freq", 0)
                )
            return encode_msg("ok", {"rows": int(snap["keys"].size)},
                              arrays=snap)
        if op == "rows":
            return encode_msg("ok", {"rows": len(self.table)})
        if op == "migrate":
            moved = self.migrate_to(
                meta["addrs"], meta["version"],
                self_index=meta.get("self_index", -1),
            )
            return encode_msg("ok", {
                "moved": moved, "rows": len(self.table),
            })
        if op == "commit_migration":
            pruned = self.commit_migration(
                meta["version"], meta["num_shards"],
                meta.get("index", -1),
            )
            return encode_msg("ok", {
                "pruned": pruned, "rows": len(self.table),
            })
        if op == "prune_unowned":
            # rollback path for DESTINATIONS of an aborted scale: drop
            # every row this server does not own under the GIVEN ring
            # (index < 0 = not in that ring at all -> drop everything
            # it received). No epoch or gate change.
            n_shards = int(meta["num_shards"])
            index = int(meta.get("index", -1))
            with self._lock:
                keys = self.table.export()["keys"]
                if index < 0:
                    prune = keys
                elif keys.size:
                    prune = keys[shard_owner(keys, n_shards) != index]
                else:
                    prune = keys
                if prune.size:
                    self.table.remove(prune)
            return encode_msg("ok", {"pruned": int(prune.size),
                                     "rows": len(self.table)})
        if op == "abort_migration":
            self.abort_migration()
            return encode_msg("ok", {"version": self.version})
        if op == "set_epoch":
            with self._lock:
                self.version = meta["version"]
                self.num_shards = meta["num_shards"]
                self.index = meta["index"]
            return encode_msg("ok", {"version": self.version})
        if op == "ckpt_save":
            return encode_msg("ok", {"path": self.ckpt_save()})
        if op == "ckpt_restore":
            return encode_msg("ok", {"version": self.ckpt_restore()})
        raise ShardError("bad_op", f"unknown op {op!r}")

    # ------------------------------------------------------------ migration

    def migrate_to(self, addrs: list[str], new_version: int,
                   self_index: int = -1) -> int:
        """Phase 1 of the two-phase scale: COPY every row whose new owner
        isn't this server to its destination. Nothing is removed and the
        epoch is not adopted here — this server stays the authoritative
        owner of all its rows until the coordinator's
        ``commit_migration`` lands, so a failed push leaves the ring
        fully intact and a retried scale simply re-pushes (``import_``
        is last-write-wins). That retires the r04 loss window where rows
        were deleted per-destination mid-migration and a later failure
        left them unreachable, with ``lookup(init_missing=True)``
        silently resurrecting fresh rows.

        ``self_index`` is this server's position in the NEW ring,
        computed by the coordinator from the address it knows this
        server by (a port-based self-guess would misfire when multiple
        hosts use the same port); -1 = scale-down, everything moves.
        Rows transfer WITH optimizer slots and frequency, chunked to
        bound frame sizes. The ``_migrating`` gate stays ARMED on
        success (mutations between copy and commit would be lost after
        the flip); ``commit_migration``/``abort_migration`` clears it.
        Returns rows copied."""
        self._migrating = True
        # TTL disarmed (0.0) while the copy is IN FLIGHT: the copy's
        # liveness is proven by its open RPC, and a TTL counted from
        # copy start would self-abort any legitimately long copy (and
        # the aborting request thread would block on _lock behind it).
        # The clock starts when the copy finishes — from then on only a
        # dead coordinator can leave the gate armed.
        self._migrating_since = 0.0
        try:
            with self._lock:
                new_n = len(addrs)
                my_index = self_index if 0 <= self_index < new_n else -1
                snap = self.table.export()
                keys = snap["keys"]
                owners = (shard_owner(keys, new_n) if keys.size
                          else np.zeros(0, np.int64))
                moved = 0
                for dest in range(new_n):
                    if dest == my_index:
                        continue
                    sel = owners == dest
                    if not np.any(sel):
                        continue
                    moved += int(sel.sum())
                    self._push_rows(addrs[dest], {
                        "keys": keys[sel],
                        "values": snap["values"][sel],
                        "slots": snap["slots"][sel]
                        if "slots" in snap else None,
                        "freq": snap["freq"][sel],
                    })
                self._migrating_since = time.monotonic()
                return moved
        except BaseException:
            # a failed copy aborts THIS server's phase; re-open for
            # traffic at the old epoch (the coordinator may retry)
            self._migrating = False
            self._migrating_since = 0.0
            raise

    def commit_migration(self, new_version: int, num_shards: int,
                         index: int) -> int:
        """Phase 2: adopt the new epoch and PRUNE every row this server
        does not own in the new ring. Pruning by ownership (rather than
        a remembered moved-key list) is idempotent and self-healing: it
        also clears dormant copies left by a previously aborted scale.
        ``index`` < 0 = departing server (drained; prunes everything).
        Rejected when the gate is no longer armed (the server
        self-aborted past its TTL): the copies may be stale by now, so
        the coordinator must re-run the whole scale."""
        with self._lock:
            if not self._migrating:
                raise ShardError(
                    "not_migrating",
                    "no armed migration (self-aborted past TTL?); "
                    "re-run the scale",
                )
            snap_keys = self.table.export()["keys"]
            if index < 0:
                prune = snap_keys
            elif snap_keys.size:
                prune = snap_keys[
                    shard_owner(snap_keys, num_shards) != index
                ]
            else:
                prune = snap_keys
            if prune.size:
                self.table.remove(prune)
            self.version = new_version
            self.num_shards = num_shards
            self.index = max(index, 0)
            self._migrating = False
            self._migrating_since = 0.0
            return int(prune.size)

    def abort_migration(self) -> int:
        """Roll back phase 1. Nothing was removed from the authoritative
        owners, so re-opening at the old epoch restores service — but
        rows already COPIED to surviving destinations are strays there
        (un-owned at the old epoch) and would double-count in
        export/checkpoint, where a later restore could replay the stale
        copy over the authoritative row. Prune them by ownership at the
        CURRENT epoch."""
        with self._lock:
            keys = self.table.export()["keys"]
            if keys.size and self.num_shards > 0:
                strays = keys[
                    shard_owner(keys, self.num_shards) != self.index
                ]
                if strays.size:
                    self.table.remove(strays)
            else:
                strays = keys[:0]
            self._migrating = False
            self._migrating_since = 0.0
            return int(strays.size)

    def _push_rows(self, addr: str, rows: dict) -> None:
        host, _, port = addr.rpartition(":")
        row_bytes = self.dim * 4 * (1 + self.num_slots) + 8 + 4
        chunk = max(1, _MIGRATE_CHUNK_BYTES // row_bytes)
        with socket.create_connection(
            (host or "127.0.0.1", int(port)), timeout=30.0
        ) as conn:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            n = rows["keys"].size
            for i in range(0, n, chunk):
                sl = slice(i, i + chunk)
                payload = {
                    "keys": rows["keys"][sl],
                    "values": rows["values"][sl],
                    "freq": rows["freq"][sl],
                }
                if rows.get("slots") is not None:
                    payload["slots"] = rows["slots"][sl]
                _call(conn, "import_rows", arrays=payload)

    # ----------------------------------------------------------- checkpoint

    def ckpt_save(self) -> str:
        if not self._ckpt_dir:
            raise ShardError("no_ckpt_dir", "server started without one")
        with self._lock:
            mgr = self._ckpt_manager()
            return mgr.save()

    def ckpt_restore(self) -> int:
        if not self._ckpt_dir:
            raise ShardError("no_ckpt_dir", "server started without one")
        with self._lock:
            mgr = self._ckpt_manager()
            return mgr.restore()

    def _ckpt_manager(self) -> IncrementalCheckpointManager:
        # per-(shard-count, index) directory: after a reshard the row
        # ownership changed, so the old chain must not be appended to —
        # a fresh manager in a fresh dir starts with a base
        d = os.path.join(self._ckpt_dir,
                         f"n{self.num_shards}-s{self.index}")
        if self._ckpt is None or self._ckpt.directory != d:
            self._ckpt = IncrementalCheckpointManager(
                self.table, d, base_interval=self._base_interval
            )
        return self._ckpt


class EmbeddingCoordinator(ArrayMsgServer):
    """Routing authority: (version, shard addrs) + the scale operation.

    Reference analog: ElasticPsService's version-bumped PS cluster
    (elastic_ps.py:82) driven by the PS auto-scaler. ``scale()`` runs the
    migration: every CURRENT server re-partitions against the new address
    ring (pushing moved rows directly peer-to-peer), then every server in
    the new ring adopts the bumped epoch. Clients that raced the scale
    get a version error from a shard and re-fetch the route here."""

    error_cls = ShardError

    def __init__(self, addrs: Iterable[str], host: str = "0.0.0.0",
                 port: int = 0):
        super().__init__(host=host, port=port, name="emb-coord")
        self.version = 0
        self.addrs = list(addrs)
        # _lock guards the (version, addrs) route snapshot and is held
        # only for instants; _scale_lock serializes scale operations,
        # which legitimately run for minutes — holding _lock across a
        # scale (the r04 design) starved `route` requests past the
        # client timeout and crashed trainers mid-migration
        self._lock = threading.Lock()
        self._scale_lock = threading.Lock()

    def start(self) -> "EmbeddingCoordinator":
        self._push_epochs()
        super().start()
        logger.info("embedding coordinator on port %d (%d shards)",
                    self.port, len(self.addrs))
        return self

    def _handle(self, op: str, meta: dict, arrays: dict) -> bytes:
        if op == "route":
            with self._lock:
                return encode_msg("ok", {
                    "version": self.version, "addrs": self.addrs,
                })
        if op == "scale":
            try:
                self.scale(meta["addrs"])
            except Exception as e:  # noqa: BLE001 - report to caller
                raise ShardError(
                    "scale_failed", f"{type(e).__name__}: {e}"
                ) from e
            with self._lock:
                return encode_msg("ok", {
                    "version": self.version, "addrs": self.addrs,
                })
        raise ShardError("bad_op", f"unknown op {op!r}")

    def _shard_call(self, addr: str, op: str, meta: dict | None = None,
                    timeout: float = 60.0):
        host, _, port = addr.rpartition(":")
        with socket.create_connection(
            (host or "127.0.0.1", int(port)), timeout=timeout
        ) as conn:
            return _call(conn, op, meta)

    def _push_epochs(self) -> None:
        for i, addr in enumerate(self.addrs):
            self._shard_call(addr, "set_epoch", {
                "version": self.version, "num_shards": len(self.addrs),
                "index": i,
            })

    def scale(self, new_addrs: list[str], migrate_retries: int = 3,
              retry_backoff_s: float = 0.5) -> None:
        """Re-partition the table onto ``new_addrs`` (grow or shrink),
        failure-atomically.

        Two phases (reference analog: elastic_ps.py:82's versioned
        cluster, hardened per the r04 verdict):

        1. COPY — every old server pushes the rows whose new owner
           differs (retried per server: ``import_`` is last-write-wins,
           so a re-push after a destination hiccup is idempotent).
           Nothing is deleted; a failure here rolls back by simply
           re-opening every server at the old epoch. Zero loss.
        2. COMMIT — pure-new servers adopt the epoch, then every old
           server prunes the rows it no longer owns and adopts. A
           failure HERE is rolled *forward* (commits retried), because
           a committed server has already pruned — rolling back would
           recreate exactly the loss window phase 1 exists to close.
           If commits keep failing the scale raises and must be
           retried; rows are never lost, only unavailable until the
           retry converges (clients back off on version errors).

        The route flips only after full commit; ``route`` requests are
        served throughout from the short-hold snapshot lock."""
        with self._scale_lock:
            with self._lock:
                old_addrs = list(self.addrs)
                new_version = self.version + 1
            try:
                for addr in old_addrs:
                    # the coordinator knows each server by address, so
                    # IT computes the server's position in the new ring
                    # (a port-based self-guess would misfire when hosts
                    # share ports); no timeout cap — a migrate streams
                    # the shard's whole row set and may legitimately
                    # run for minutes on big tables
                    try:
                        self_index = new_addrs.index(addr)
                    except ValueError:
                        self_index = -1
                    meta = self._retry_shard_call(
                        addr, "migrate", {
                            "addrs": new_addrs, "version": new_version,
                            "self_index": self_index,
                        }, migrate_retries, retry_backoff_s,
                        timeout=None,
                    )
                    logger.info("shard %s copied %d rows", addr,
                                meta["moved"])
            except Exception:
                self._rollback(old_addrs, new_addrs)
                raise
            # phase 2a: epochs for pure-new members first (they only
            # gain rows). Retried, and STILL rollback-safe on failure —
            # no old server has pruned anything yet, so abort is the
            # same clean path as a phase-1 failure (review finding: an
            # unretried, unrolled-back set_epoch here left every old
            # server's migrating gate armed until the TTL).
            try:
                for i, addr in enumerate(new_addrs):
                    if addr not in old_addrs:
                        self._retry_shard_call(
                            addr, "set_epoch", {
                                "version": new_version,
                                "num_shards": len(new_addrs),
                                "index": i,
                            }, migrate_retries, retry_backoff_s,
                        )
            except Exception:
                self._rollback(old_addrs, new_addrs)
                raise
            # phase 2b: commit (prune+adopt) the old members — from
            # here failures roll FORWARD (see docstring)
            for addr in old_addrs:
                try:
                    idx = new_addrs.index(addr)
                except ValueError:
                    idx = -1
                self._retry_shard_call(
                    addr, "commit_migration", {
                        "version": new_version,
                        "num_shards": len(new_addrs), "index": idx,
                    }, migrate_retries, retry_backoff_s,
                )
            with self._lock:
                self.version = new_version
                self.addrs = list(new_addrs)

    def _rollback(self, old_addrs: list[str],
                  new_addrs: list[str]) -> None:
        """Undo an uncommitted scale: nothing was deleted from the
        authoritative owners, so re-opening them at the old epoch is
        the core rollback (abort prunes their own strays). PURE-NEW
        destinations additionally drop every row they received — they
        sit outside the old ring, so a stray copy there would otherwise
        survive until a later scale and could resurrect a row the
        trainer deleted in between (review finding r05)."""
        for addr in old_addrs:
            try:
                self._shard_call(addr, "abort_migration")
            except Exception:  # noqa: BLE001 - best effort
                logger.warning("abort_migration to %s failed", addr)
        for addr in new_addrs:
            if addr in old_addrs:
                continue
            try:
                self._shard_call(addr, "prune_unowned",
                                 {"num_shards": len(old_addrs),
                                  "index": -1})
            except Exception:  # noqa: BLE001 - best effort
                logger.warning("prune_unowned to %s failed", addr)

    def _retry_shard_call(self, addr: str, op: str, meta: dict,
                          retries: int, backoff_s: float,
                          timeout: float | None = 60.0) -> dict:
        last: Exception | None = None
        for attempt in range(max(1, retries)):
            try:
                rmeta, _ = self._shard_call(addr, op, meta,
                                            timeout=timeout)
                return rmeta
            except (ShardError, ConnectionError, OSError) as e:
                last = e
                logger.warning("%s to %s failed (attempt %d/%d): %s",
                               op, addr, attempt + 1, retries, e)
                time.sleep(backoff_s * (attempt + 1))
        raise RuntimeError(f"{op} to {addr} failed after "
                           f"{retries} attempts: {last}")

    def total_rows(self) -> int:
        with self._lock:
            addrs = list(self.addrs)
        return sum(
            self._shard_call(a, "rows")[0]["rows"] for a in addrs
        )


class ShardedKvClient:
    """Trainer-side sharded table: the KvEmbeddingTable surface over N
    shard servers. ``lookup``/``apply`` split each batch by owner shard,
    fan out in parallel, and reassemble — so the recsys training loop is
    identical whether the table is local or sharded."""

    def __init__(self, coordinator_addr: str | None = None,
                 addrs: list[str] | None = None, dim: int = 0,
                 timeout: float = 30.0, retry_window_s: float = 600.0):
        if not coordinator_addr and not addrs:
            raise ValueError("need coordinator_addr or addrs")
        self.dim = dim
        self._timeout = timeout
        self.retry_window_s = retry_window_s
        self._coord_addr = coordinator_addr
        self.version = 0
        self._addrs: list[str] = list(addrs or [])
        self._socks: dict[str, socket.socket] = {}
        self._lock = threading.Lock()
        self._pool = ThreadPoolExecutor(
            max_workers=8, thread_name_prefix="emb-client"
        )
        if coordinator_addr:
            self.refresh_route()
        self._step = 0

    # ------------------------------------------------------------- plumbing

    def refresh_route(self) -> None:
        host, _, port = self._coord_addr.rpartition(":")
        with socket.create_connection(
            (host or "127.0.0.1", int(port)), timeout=self._timeout
        ) as conn:
            meta, _ = _call(conn, "route")
        with self._lock:
            self.version = meta["version"]
            self._addrs = list(meta["addrs"])
            # stale sockets may point at drained servers
            for s in self._socks.values():
                try:
                    s.close()
                except OSError:
                    pass
            self._socks.clear()

    def _sock_for(self, addr: str) -> socket.socket:
        s = self._socks.get(addr)
        if s is None:
            host, _, port = addr.rpartition(":")
            s = socket.create_connection(
                (host or "127.0.0.1", int(port)), timeout=self._timeout
            )
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._socks[addr] = s
        return s

    def _evict_sock(self, addr: str) -> None:
        """Close-and-forget a socket that failed: popping without
        closing (the r05 behavior) leaked one fd per dead server, and
        leaving it cached re-sent the NEXT call into the same dead
        connection — recovery then had to come from the slower
        version-error/route-refresh path instead of a fresh dial."""
        s = self._socks.pop(addr, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def _shard_call(self, idx: int, op: str, meta: dict,
                    arrays: dict) -> tuple[dict, dict]:
        addr = self._addrs[idx]
        try:
            return _call(self._sock_for(addr), op, meta, arrays)
        except (ConnectionError, OSError):
            # evict + one immediate re-dial: the server may have
            # restarted between ops (same addr, new process)
            self._evict_sock(addr)
            try:
                return _call(self._sock_for(addr), op, meta, arrays)
            except (ConnectionError, OSError):
                # still down: evict again so the retry loop's NEXT
                # attempt (after a route refresh) dials fresh instead
                # of reusing a half-dead connection
                self._evict_sock(addr)
                raise

    def _fanout(self, op: str, ids: np.ndarray,
                per_shard_arrays, meta_extra: dict | None = None,
                retry_window_s: float | None = None):
        """Split by owner, call each touched shard, return per-shard
        (selector, response-arrays) pairs.

        Retry semantics: completion is tracked PER ID — a retry after a
        route-level failure (version bump, migration in progress, or a
        dead/drained server) re-sends only the ids whose shard call
        failed, re-routed under the refreshed route. Shards that already
        answered are never re-sent, so a scale event racing an ``apply``
        cannot double-apply gradients to the shards that succeeded.
        (The residual at-least-once window — a shard that applied but
        whose *response* was lost — is inherent to retrying writes and
        matches the sharding-client's at-least-once contract.)

        The retry budget is TIME-based (default ``self.retry_window_s``,
        600 s): a big-table scale legitimately blocks shards behind
        their migrating gate for minutes, and the r04 count-based
        budget (60 x 0.25 s ~ 15 s) crashed training during exactly the
        event the retries exist to ride out. ``refresh_route`` failures
        are themselves retriable — the coordinator answers from a
        short-hold snapshot lock now, but a momentarily unreachable
        coordinator must not kill the trainer either."""
        flat = np.ascontiguousarray(ids, np.int64).reshape(-1)
        pending = np.ones(flat.size, dtype=bool)
        results: list[tuple[np.ndarray, dict]] = []
        last: Exception | None = None
        deadline = time.monotonic() + (
            retry_window_s if retry_window_s is not None
            else self.retry_window_s
        )
        backoff = 0.25
        while True:
            n = max(1, len(self._addrs))
            idxs = np.nonzero(pending)[0]
            owners = shard_owner(flat[idxs], n)
            futures = []
            for s in range(n):
                sel = idxs[owners == s]
                if sel.size == 0:
                    continue
                meta = {"v": self.version, **(meta_extra or {})}
                arrays = per_shard_arrays(flat[sel], sel)
                futures.append((sel, self._pool.submit(
                    self._shard_call, s, op, meta, arrays
                )))
            for sel, fut in futures:
                try:
                    _, rarrays = fut.result()
                    results.append((sel, rarrays))
                    pending[sel] = False
                except ShardError as e:
                    last = e
                    if e.code not in ("version", "migrating"):
                        raise
                except (ConnectionError, OSError) as e:
                    # a drained server may already be gone after a
                    # scale-down: re-route instead of crashing training
                    last = e
            # success is checked AFTER collecting: an iteration that
            # completes past the deadline keeps its own result instead
            # of discarding applied gradients as a spurious failure
            if not pending.any():
                return results, flat
            if time.monotonic() >= deadline:
                break
            time.sleep(backoff)
            backoff = min(backoff * 1.5, 2.0)
            if self._coord_addr:
                try:
                    self.refresh_route()
                except (ShardError, ConnectionError, OSError) as e:
                    last = e  # coordinator busy/unreachable: retry
        raise RuntimeError(
            f"embedding fanout kept failing after "
            f"{retry_window_s or self.retry_window_s:.0f}s: {last}"
        )

    # ------------------------------------------------------------- user ops

    def lookup(self, ids: np.ndarray, init_missing: bool = True
               ) -> np.ndarray:
        flat_shape = np.shape(ids)
        parts, flat = self._fanout(
            "lookup", ids,
            lambda shard_ids, sel: {"ids": shard_ids},
            meta_extra={"init": init_missing},
        )
        out = np.empty((flat.size, self.dim), np.float32)
        for sel, rarrays in parts:
            out[sel] = rarrays["values"]
        return out.reshape(*flat_shape, self.dim)

    def apply(self, optimizer: str, ids: np.ndarray, grads: np.ndarray,
              **kwargs) -> None:
        g = np.ascontiguousarray(grads, np.float32).reshape(-1, self.dim)
        self._step += 1
        if optimizer in ("adam", "group_adam", "radam"):
            kwargs.setdefault("step", self._step)
        self._fanout(
            "apply", ids,
            lambda shard_ids, sel: {"ids": shard_ids, "grads": g[sel]},
            meta_extra={"optimizer": optimizer, "kwargs": kwargs},
        )

    def apply_adam(self, ids: np.ndarray, grads: np.ndarray,
                   **kwargs) -> None:
        self.apply("adam", ids, grads, **kwargs)

    def row_count(self) -> int:
        total = 0
        for i in range(len(self._addrs)):
            meta, _ = self._shard_call(i, "rows", {}, {})
            total += meta["rows"]
        return total

    def __len__(self) -> int:
        return self.row_count()

    def export(self, min_freq: int = 0, with_slots: bool = True
               ) -> dict[str, np.ndarray]:
        """KvEmbeddingTable-compatible snapshot alias (full table)."""
        snap = self.export_all()
        if not with_slots:
            snap.pop("slots", None)
        return snap

    def export_all(self) -> dict[str, np.ndarray]:
        """Full-table snapshot across shards (tests/verification)."""
        snaps = []
        for i in range(len(self._addrs)):
            _, arrays = self._shard_call(i, "export", {}, {})
            snaps.append(arrays)
        out: dict[str, np.ndarray] = {}
        for k in ("keys", "values", "slots", "freq"):
            if all(k in s for s in snaps):
                out[k] = np.concatenate([s[k] for s in snaps])
        return out

    def ckpt_save(self) -> list[str]:
        return [self._shard_call(i, "ckpt_save", {}, {})[0]["path"]
                for i in range(len(self._addrs))]

    def ckpt_restore(self) -> list[int]:
        return [self._shard_call(i, "ckpt_restore", {}, {})[0]["version"]
                for i in range(len(self._addrs))]

    def close(self) -> None:
        self._pool.shutdown(wait=False)
        for s in self._socks.values():
            try:
                s.close()
            except OSError:
                pass
        self._socks.clear()


class EmbeddingServerScaler:
    """Scaler-contract adapter for the table tier: the
    PSTrainingAutoScaler analog (reference
    dlrover/python/master/node/job_auto_scaler.py:98 resizes parameter
    servers through the pod scaler + elastic-PS version bump).

    A ScalePlan whose ``replica_resources`` carries the
    ``"table_server"`` group is executed as: spawn/stop local shard
    server processes toward the target count, then
    ``EmbeddingCoordinator.scale`` migrates rows onto the new ring and
    bumps the routing version. Plugs directly into
    ``master.auto_scaler.JobAutoScaler`` as its scaler (or alongside a
    worker scaler via a dispatching wrapper). Pod-based deployments do
    the same with the operator spawning server pods and an addr-watch
    feeding ``coordinator.scale``.
    """

    GROUP = "table_server"

    def __init__(self, dim: int, *, coordinator: EmbeddingCoordinator,
                 spawn=None, num_slots: int = 2, seed: int = 0,
                 ckpt_dir: str = "", host: str = "127.0.0.1",
                 spawn_timeout_s: float = 60.0):
        self.dim = dim
        self.num_slots = num_slots
        self.seed = seed
        self.ckpt_dir = ckpt_dir
        self.host = host
        self.spawn_timeout_s = spawn_timeout_s
        self._coord = coordinator
        self._procs: dict[str, object] = {}  # addr -> Popen/server
        # _lock guards _procs ONLY (short holds, so stop_all can always
        # proceed); _scale_lock serializes scale operations, whose
        # migrate leg is legitimately unbounded on big tables
        self._lock = threading.Lock()
        self._scale_lock = threading.Lock()
        self._stopped = False
        self._spawn = spawn or self._default_spawn

    def _default_spawn(self, index: int) -> tuple[str, object]:
        """Spawn a shard-server subprocess carrying the TIER'S table
        configuration — a new server with a different num_slots/seed
        would reject migrated rows (import_ shape check) or break the
        deterministic-init contract mid-ring."""
        import subprocess
        import sys

        cmd = [sys.executable, "-m", "dlrover_tpu.embedding.service",
               "--dim", str(self.dim),
               "--num-slots", str(self.num_slots),
               "--seed", str(self.seed),
               "--host", self.host, "--index", str(index)]
        if self.ckpt_dir:
            cmd += ["--ckpt-dir", self.ckpt_dir]
        proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 EnvKey.PLATFORM: "cpu"},
        )
        # bounded readiness wait: a wedged child must not park scale()
        # (and with it the auto-scaler tick + stop_all) on readline
        # forever
        line_box: list[str] = []

        def read():
            line_box.append(proc.stdout.readline().strip())

        t = threading.Thread(target=read, daemon=True)
        t.start()
        t.join(self.spawn_timeout_s)
        line = line_box[0] if line_box else ""
        if not line.startswith("PORT "):
            self._terminate(proc)
            raise RuntimeError(
                f"table server not ready within {self.spawn_timeout_s}s"
                f" (got {line!r})"
            )
        # the pipe has served its one purpose; keeping it open leaks an
        # fd per spawn and would wedge a child that ever filled it
        try:
            proc.stdout.close()
        except OSError:
            pass
        return f"{self.host}:{line.split()[1]}", proc

    def scale(self, plan) -> None:
        target = plan.replica_resources.get(self.GROUP)
        if target is None:
            return
        if target < 1:
            # an empty ring has nowhere to migrate rows TO — executing
            # it would strand every row and then kill their holders
            raise ValueError(
                f"table_server target {target}: the tier cannot scale "
                "below 1 (rows need an owner)"
            )
        with self._scale_lock:
            if self._stopped:
                raise RuntimeError("table tier is shut down")
            addrs = list(self._coord.addrs)
            spawned: list[str] = []
            try:
                while len(addrs) + len(spawned) < target:
                    # re-check per spawn: a stop_all() racing this scale
                    # must not have servers registered AFTER its clear
                    if self._stopped:
                        raise RuntimeError("table tier is shut down")
                    addr, proc = self._spawn(len(addrs) + len(spawned))
                    with self._lock:
                        self._procs[addr] = proc
                    spawned.append(addr)
                new_addrs = (addrs + spawned)[:target]
                retired = [a for a in addrs if a not in new_addrs]
                if spawned or retired:
                    logger.info(
                        "table tier %d -> %d servers (%s)", len(addrs),
                        target, plan.reason or "scale plan",
                    )
                    self._coord.scale(new_addrs)  # migrates, bumps ver
            except BaseException:
                # a failed spawn OR migration must not leak the servers
                # just spawned for this plan: they are not in the route,
                # and a retried plan would spawn a fresh set on top
                for addr in spawned:
                    with self._lock:
                        proc = self._procs.pop(addr, None)
                    self._terminate(proc)
                raise
            for addr in retired:  # drained by the migrate; now stop
                with self._lock:
                    proc = self._procs.pop(addr, None)
                self._terminate(proc)

    @staticmethod
    def _terminate(proc) -> None:
        """terminate -> wait -> kill for subprocesses (no zombies, no
        SIGTERM-ignoring stragglers); in-process servers (tests,
        co-located tiers) expose stop()."""
        import subprocess

        if proc is None:
            return
        if hasattr(proc, "terminate") and hasattr(proc, "wait"):
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10)
        elif hasattr(proc, "stop"):
            proc.stop()

    def stop_all(self) -> None:
        # flag first so an in-flight/next scale() refuses to spawn more;
        # terminate OUTSIDE the lock (a straggler's wait must not block
        # the registrations scale() does under short lock holds)
        self._stopped = True
        with self._lock:
            procs = list(self._procs.values())
            self._procs.clear()
        for proc in procs:
            self._terminate(proc)


def main(argv=None) -> int:
    """CLI shard-server entry: prints ``PORT <n>`` once listening (the
    spawner's readiness/port-discovery contract, like data_worker.py)."""
    p = argparse.ArgumentParser("embedding shard server")
    p.add_argument("--dim", type=int, required=True)
    p.add_argument("--num-slots", type=int, default=2)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--index", type=int, default=0)
    p.add_argument("--num-shards", type=int, default=1)
    p.add_argument("--ckpt-dir", default="")
    p.add_argument("--spill-dir", default="",
                   help="hybrid tier: spill file for cold rows")
    args = p.parse_args(argv)
    server = EmbeddingShardServer(
        dim=args.dim, num_slots=args.num_slots, seed=args.seed,
        host=args.host, port=args.port, index=args.index,
        num_shards=args.num_shards, ckpt_dir=args.ckpt_dir,
    )
    if args.spill_dir:
        os.makedirs(args.spill_dir, exist_ok=True)
        server.table.enable_spill(os.path.join(
            args.spill_dir, f"shard-{args.index}.spill"
        ))
    server.start()
    print(f"PORT {server.port}", flush=True)
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
