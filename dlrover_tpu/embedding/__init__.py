from dlrover_tpu.embedding.kv_table import KvEmbeddingTable  # noqa: F401

# the elastic embedding fabric (DESIGN.md §25) is imported lazily by
# its users (examples, gateway, bench) — importing it here would drag
# checkpoint/telemetry into every `from dlrover_tpu.embedding import
# KvEmbeddingTable`
