from dlrover_tpu.embedding.kv_table import KvEmbeddingTable  # noqa: F401
