"""In-graph sparse embedding ops: XLA FFI custom calls over the native
KvVariable runtime.

Reference analog: tfplus's KvVariable gather/apply are GRAPH ops
(tfplus/kv_variable/ops/kv_variable_ops.cc:37, kernels/
training_ops.cc) — the sparse hot path never leaves the runtime. The
repo's default sparse path is host-side Python (SURVEY §7 named the
in-graph form the trickiest native piece); this module closes it for
CPU backends: ``kv_gather``/``kv_apply_adam`` lower to XLA custom
calls (native/kv_ffi.cc), so a jitted train step runs lookup → dense
tower → backward → sparse Adam with ZERO Python in the loop.

On TPU the table stays host-side by design — an unbounded hash table
cannot live in device HBM, and XLA:TPU does not execute user C++ —
so the FFI targets register for the "cpu" platform and the TPU flow
keeps the host lookup + on-chip dense tower split. That is the same
division of labor the reference reaches with parameter servers.

Lifetime contract: the compiled program captures the table's raw
pointer as a call attribute. Keep the ``KvEmbeddingTable`` alive for
as long as any jitted function built from it can run — the helpers
here close over the table precisely so Python's GC enforces that.
"""

from __future__ import annotations

import ctypes
import threading

import numpy as np

from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)

_registered = False
_reg_lock = threading.Lock()


def ffi_available() -> bool:
    """True when the native lib exports the FFI handlers (it was built
    with the jaxlib headers) and this process can register them."""
    try:
        from dlrover_tpu.embedding.kv_table import _load_lib

        lib = _load_lib()
        ctypes.cast(getattr(lib, "KvGather"), ctypes.c_void_p)
        return True
    except (AttributeError, OSError, RuntimeError):
        return False


def register_targets() -> bool:
    """Register the FFI targets for the CPU platform (idempotent)."""
    global _registered
    with _reg_lock:
        if _registered:
            return True
        if not ffi_available():
            return False
        import jax.ffi

        from dlrover_tpu.embedding.kv_table import _load_lib

        lib = _load_lib()
        # both id widths: jax without jax_enable_x64 lowers integer
        # arrays to i32, so that variant is the common jitted path;
        # the S64 one serves x64-enabled processes
        for name, sym in (
            ("dlrover_kv_gather", lib.KvGather),
            ("dlrover_kv_gather_i32", lib.KvGather32),
            ("dlrover_kv_apply_adam", lib.KvApplyAdam),
            ("dlrover_kv_apply_adam_i32", lib.KvApplyAdam32),
        ):
            jax.ffi.register_ffi_target(
                name, jax.ffi.pycapsule(sym), platform="cpu",
            )
        _registered = True
        logger.info("kv FFI targets registered (cpu)")
        return True


def make_ingraph_lookup(table, init_missing: bool = True):
    """A jittable ``ids [*] -> values [*, dim]`` over ``table``.

    The returned callable closes over the table (lifetime contract
    above). Works under jit/scan on the CPU backend; no autodiff rule
    on purpose — gradients w.r.t. the gathered rows flow to the sparse
    optimizer through :func:`make_ingraph_train_step` or the host-side
    ``table.apply`` path, never through a dense dL/dtable.
    """
    if not register_targets():
        raise RuntimeError("kv FFI targets unavailable "
                           "(native lib built without jax headers?)")
    import jax

    dim = table.dim
    handle = int(table._handle)

    def lookup(ids):
        import jax.numpy as jnp

        ids = jnp.asarray(ids)
        wide = jnp.issubdtype(ids.dtype, jnp.int64)
        ids = ids.astype(jnp.int64 if wide else jnp.int32)
        out_shape = (*ids.shape, dim)
        call = jax.ffi.ffi_call(
            "dlrover_kv_gather" if wide else "dlrover_kv_gather_i32",
            jax.ShapeDtypeStruct(out_shape, jnp.float32),
        )
        return call(ids.reshape(-1), table=np.int64(handle),
                    init_missing=bool(init_missing)).reshape(out_shape)

    # keep the table reachable from the closure (lifetime contract)
    lookup._table = table
    return lookup


def make_ingraph_apply_adam(table, *, lr: float = 1e-3,
                            beta1: float = 0.9, beta2: float = 0.999,
                            eps: float = 1e-8, l2: float = 0.0,
                            group_lasso: float = 0.0):
    """A jittable ``(ids [*], grads [*, dim], step) -> rows`` applying
    the sparse Adam update inside the compiled program (the
    training_ops.cc analog). Marked side-effecting so XLA never CSEs or
    dead-code-eliminates the update."""
    if not register_targets():
        raise RuntimeError("kv FFI targets unavailable "
                           "(native lib built without jax headers?)")
    import jax

    handle = int(table._handle)

    def apply_adam(ids, grads, step):
        import jax.numpy as jnp

        ids = jnp.asarray(ids).reshape(-1)
        wide = jnp.issubdtype(ids.dtype, jnp.int64)
        idt = jnp.int64 if wide else jnp.int32
        ids = ids.astype(idt)
        grads = jnp.asarray(grads, jnp.float32)
        grads = grads.reshape(ids.shape[0], -1)
        # step is a TRACED scalar operand (an attribute would bake it
        # into the compiled program and force a per-step recompile)
        step = jnp.asarray(step, idt).reshape(1)
        call = jax.ffi.ffi_call(
            "dlrover_kv_apply_adam" if wide
            else "dlrover_kv_apply_adam_i32",
            jax.ShapeDtypeStruct((1,), idt),
            has_side_effect=True,
        )
        return call(ids, grads, step, table=np.int64(handle),
                    lr=np.float32(lr), beta1=np.float32(beta1),
                    beta2=np.float32(beta2), eps=np.float32(eps),
                    l2=np.float32(l2),
                    group_lasso=np.float32(group_lasso))[0]

    apply_adam._table = table
    return apply_adam


def make_ingraph_train_step(table, tower_loss_fn, *, lr: float = 1e-3,
                            tower_lr: float = 0.1,
                            init_missing: bool = True, **adam_kw):
    """One fully in-graph recsys train step: sparse gather → dense
    tower forward/backward → tower SGD + sparse Adam, all inside ONE
    jitted program — what the host-side path pays a Python round trip
    per step for.

    ``tower_loss_fn(tower_params, emb, batch) -> scalar loss``; the
    embedding cotangent comes from ``jax.grad`` w.r.t. the gathered
    block, then feeds the in-graph sparse apply. ``step`` (Adam bias
    correction) is a traced scalar, so one compiled program serves the
    whole run.
    """
    import jax

    lookup = make_ingraph_lookup(table, init_missing=init_missing)
    apply_ = make_ingraph_apply_adam(table, lr=lr, **adam_kw)

    def train_step(tower, ids, batch, step):
        emb = lookup(ids)

        def loss_of(tw, e):
            return tower_loss_fn(tw, e, batch)

        loss, (tower_g, emb_g) = jax.value_and_grad(
            loss_of, argnums=(0, 1))(tower, emb)
        tower = jax.tree.map(lambda p, g: p - tower_lr * g,
                             tower, tower_g)
        rows = apply_(ids, emb_g, step)
        return tower, loss, rows

    train_step._table = table
    return train_step
