"""WSAM: Sharpness-Aware Minimization with a weighted flat-minima term.

Reference analog: atorch/atorch/optimizers/wsam.py:138 (KDD '23, "Sharpness-
Aware Minimization Revisited: Weighted Sharpness as a Regularization
Term"). SAM perturbs params to the worst case within an L2 ball
(rho * g/|g|), evaluates the gradient there, and steps from the original
point; WSAM mixes the base and perturbed gradients with weight ``gamma``
so sharpness acts as a tunable regularizer instead of replacing the loss.

Functional JAX form: the two-gradient structure becomes a wrapper that owns
the loss function (SAM needs a second forward/backward at the perturbed
point — not expressible as a pure optax transform on one gradient).
``wsam(...)`` returns (init_fn, update_fn) where update_fn takes
(params, state, batch) and does the full two-step computation under jit.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import chex
import jax
import jax.numpy as jnp
import optax


class WSAMState(NamedTuple):
    base: Any  # inner optimizer state


def wsam(
    loss_fn: Callable[[Any, Any], chex.Array],
    base_optimizer: optax.GradientTransformation,
    rho: float = 0.05,
    gamma: float = 0.9,
):
    """Build (init, step) for WSAM around ``base_optimizer``.

    step(params, state, batch) -> (params, state, loss). The effective
    gradient is ``(1-gamma)*g + gamma*g_adv`` with ``g_adv`` taken at the
    rho-normalized ascent point (gamma=1 recovers SAM, gamma=0 the base
    optimizer).
    """

    def init(params) -> WSAMState:
        return WSAMState(base=base_optimizer.init(params))

    def step(params, state: WSAMState, batch):
        loss, g = jax.value_and_grad(loss_fn)(params, batch)
        gnorm = optax.global_norm(g)
        scale = rho / (gnorm + 1e-12)
        adv_params = jax.tree.map(lambda p, gi: p + scale * gi, params, g)
        g_adv = jax.grad(loss_fn)(adv_params, batch)
        mixed = jax.tree.map(
            lambda gi, ga: (1.0 - gamma) * gi + gamma * ga, g, g_adv
        )
        updates, base_state = base_optimizer.update(
            mixed, state.base, params
        )
        params = optax.apply_updates(params, updates)
        return params, WSAMState(base=base_state), loss

    return init, step
