"""8-bit Adam: blockwise-quantized optimizer states, pure JAX.

Reference analog: atorch/atorch/optimizers/low_bit/ (4/8-bit optimizer
states backed by CUDA quantization kernels, ops/csrc/quantization). On TPU
the same memory win — optimizer moments stored at 1 byte/element — needs
no custom kernel: blockwise absmax quantization is a handful of vector
ops XLA fuses into the update, trading a little ALU for a 4x cut in
optimizer-state HBM (8 bytes -> 2 bytes per param for Adam's m+v).

Quantization scheme (matching the 8-bit Adam literature): states are
flattened and split into fixed-size blocks; each block stores int8 codes
plus one f32 absmax scale. m is signed-linear, v (non-negative) is
unsigned-linear in the int8 range.
"""

from __future__ import annotations

from typing import NamedTuple

import chex
import jax
import jax.numpy as jnp
import optax


def _pad_len(n: int, block: int) -> int:
    return (n + block - 1) // block * block


def _quantize(x: jax.Array, block: int, signed: bool
              ) -> tuple[jax.Array, jax.Array]:
    """Flatten -> [n_blocks, block] int8 codes + per-block f32 scales."""
    flat = x.reshape(-1)
    padded = jnp.zeros((_pad_len(flat.size, block),), x.dtype)
    padded = padded.at[: flat.size].set(flat)
    blocks = padded.reshape(-1, block)
    absmax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax, 1.0)
    limit = 127.0 if signed else 255.0
    codes = jnp.round(blocks / scale * limit)
    if signed:
        codes = jnp.clip(codes, -127, 127).astype(jnp.int8)
    else:
        # store unsigned range in int8 by offsetting to [-128, 127]
        codes = (jnp.clip(codes, 0, 255) - 128).astype(jnp.int8)
    return codes, scale[:, 0].astype(jnp.float32)


def _dequantize(codes: jax.Array, scales: jax.Array, shape, block: int,
                signed: bool) -> jax.Array:
    limit = 127.0 if signed else 255.0
    vals = codes.astype(jnp.float32)
    if not signed:
        vals = vals + 128.0
    blocks = vals / limit * scales[:, None]
    n = 1
    for d in shape:
        n *= d
    return blocks.reshape(-1)[:n].reshape(shape)


class _Quantized(NamedTuple):
    codes: jax.Array   # int8 [n_blocks, block]
    scales: jax.Array  # f32 [n_blocks]


class Adam8bitState(NamedTuple):
    count: chex.Array
    mu: optax.Updates   # tree of _Quantized
    nu: optax.Updates   # tree of _Quantized


def adam_8bit(
    learning_rate: float | optax.Schedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    block_size: int = 256,
    min_8bit_size: int = 4096,
) -> optax.GradientTransformation:
    """Adam whose m/v live as int8 blockwise-quantized tensors.

    Leaves smaller than ``min_8bit_size`` keep fp32 moments (bitsandbytes
    convention): padding a (3,) bias to a 256-wide int8 block would COST
    memory, and norm/bias leaves are precisely where moment precision
    matters most.
    """

    def small(p) -> bool:
        return p.size < min_8bit_size

    def q_zero(p):
        if small(p):
            return jnp.zeros(p.shape, jnp.float32)
        n_blocks = _pad_len(p.size, block_size) // block_size
        return _Quantized(
            codes=jnp.zeros((n_blocks, block_size), jnp.int8),
            scales=jnp.zeros((n_blocks,), jnp.float32),
        )

    def init_fn(params):
        return Adam8bitState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(q_zero, params),
            nu=jax.tree.map(q_zero, params),
        )

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1

        def leaf_update(g, mu_q, nu_q):
            if not isinstance(mu_q, _Quantized):
                m, v = mu_q, nu_q  # small leaf: fp32 moments
            else:
                m = _dequantize(mu_q.codes, mu_q.scales, g.shape,
                                block_size, signed=True)
                # v is stored in the sqrt domain: its raw dynamic range
                # spans many orders of magnitude within a block, and
                # linear int8 would crush small entries to 0 (vhat ~ 0 ->
                # exploding steps); sqrt halves the log-range, bounding
                # the relative error of the Adam denominator
                r = _dequantize(nu_q.codes, nu_q.scales, g.shape,
                                block_size, signed=False)
                v = r * r
            g32 = g.astype(jnp.float32)
            m = b1 * m + (1.0 - b1) * g32
            v = b2 * v + (1.0 - b2) * g32 * g32
            mhat = m / (1.0 - b1 ** count.astype(jnp.float32))
            vhat = v / (1.0 - b2 ** count.astype(jnp.float32))
            # schedules evaluate at the PRE-increment step, matching
            # optax.adam (step 0 first)
            lr = (
                learning_rate(count - 1)
                if callable(learning_rate) else learning_rate
            )
            step = (-lr * mhat / (jnp.sqrt(vhat) + eps)).astype(g.dtype)
            if not isinstance(mu_q, _Quantized):
                return step, m, v
            m_q = _Quantized(*_quantize(m, block_size, signed=True))
            v_q = _Quantized(
                *_quantize(jnp.sqrt(v), block_size, signed=False)
            )
            return step, m_q, v_q

        flat_g, treedef = jax.tree_util.tree_flatten(updates)
        flat_mu = treedef.flatten_up_to(state.mu)
        flat_nu = treedef.flatten_up_to(state.nu)
        out = [leaf_update(g, mq, nq)
               for g, mq, nq in zip(flat_g, flat_mu, flat_nu)]
        steps = jax.tree_util.tree_unflatten(
            treedef, [o[0] for o in out]
        )
        mu = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
        nu = jax.tree_util.tree_unflatten(treedef, [o[2] for o in out])
        return steps, Adam8bitState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)


# ------------------------------------------------------------------- 4-bit


def _codebook(signed: bool) -> jax.Array:
    """16-level quadratic codebook on [-1, 1] (signed) or [0, 1].

    Optimizer moments cluster near zero within a block; quadratic code
    spacing spends most of the 4-bit budget there (the reference's 4-bit
    states use a dynamic-exponent mapping for the same reason —
    atorch/atorch/optimizers/low_bit/). Signed uses 15 symmetric levels
    so zero is exactly representable.
    """
    if signed:
        idx = jnp.arange(-7, 8, dtype=jnp.float32)
        return jnp.sign(idx) * (jnp.abs(idx) / 7.0) ** 2
    return (jnp.arange(16, dtype=jnp.float32) / 15.0) ** 2


def _quantize4(x: jax.Array, block: int, signed: bool
               ) -> tuple[jax.Array, jax.Array]:
    """Flatten -> packed nibble codes [n_blocks, block//2] + f32 scales."""
    flat = x.reshape(-1)
    padded = jnp.zeros((_pad_len(flat.size, block),), x.dtype)
    padded = padded.at[: flat.size].set(flat)
    blocks = padded.reshape(-1, block).astype(jnp.float32)
    absmax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    scale = jnp.where(absmax > 0, absmax, 1.0)
    normed = blocks / scale
    book = _codebook(signed)
    codes = jnp.argmin(
        jnp.abs(normed[..., None] - book), axis=-1
    ).astype(jnp.int32)  # [n_blocks, block] in [0, 15]
    hi, lo = codes[:, 0::2], codes[:, 1::2]
    packed = ((hi << 4) | lo).astype(jnp.int8)
    return packed, scale[:, 0].astype(jnp.float32)


def _dequantize4(packed: jax.Array, scales: jax.Array, shape, block: int,
                 signed: bool) -> jax.Array:
    u = packed.astype(jnp.int32) & 0xFF
    hi, lo = (u >> 4) & 0xF, u & 0xF
    codes = jnp.stack([hi, lo], axis=-1).reshape(u.shape[0], -1)
    book = _codebook(signed)
    blocks = book[jnp.clip(codes, 0, book.size - 1)] * scales[:, None]
    n = 1
    for d in shape:
        n *= d
    return blocks.reshape(-1)[:n].reshape(shape)


class Adam4bitState(NamedTuple):
    count: chex.Array
    mu: optax.Updates
    nu: optax.Updates


def adam_4bit(
    learning_rate: float | optax.Schedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    block_size: int = 128,
    min_quant_size: int = 4096,
) -> optax.GradientTransformation:
    """Adam whose m/v live as packed 4-bit codes: 0.5 byte/element state
    (16x less optimizer HBM than fp32 Adam; 2x less than adam_8bit).

    Same scaffold as adam_8bit: blockwise absmax scales, sqrt-domain v,
    fp32 moments for small leaves. The smaller default block (128) offsets
    the coarser codes with tighter scales.
    """

    def small(p) -> bool:
        return p.size < min_quant_size

    def q_zero(p):
        if small(p):
            return jnp.zeros(p.shape, jnp.float32)
        n_blocks = _pad_len(p.size, block_size) // block_size
        return _Quantized(
            codes=jnp.zeros((n_blocks, block_size // 2), jnp.int8),
            scales=jnp.zeros((n_blocks,), jnp.float32),
        )

    def init_fn(params):
        return Adam4bitState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(q_zero, params),
            nu=jax.tree.map(q_zero, params),
        )

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1

        def leaf_update(g, mu_q, nu_q):
            if not isinstance(mu_q, _Quantized):
                m, v = mu_q, nu_q
            else:
                m = _dequantize4(mu_q.codes, mu_q.scales, g.shape,
                                 block_size, signed=True)
                r = _dequantize4(nu_q.codes, nu_q.scales, g.shape,
                                 block_size, signed=False)
                v = r * r
            g32 = g.astype(jnp.float32)
            m = b1 * m + (1.0 - b1) * g32
            v = b2 * v + (1.0 - b2) * g32 * g32
            mhat = m / (1.0 - b1 ** count.astype(jnp.float32))
            vhat = v / (1.0 - b2 ** count.astype(jnp.float32))
            lr = (
                learning_rate(count - 1)
                if callable(learning_rate) else learning_rate
            )
            step = (-lr * mhat / (jnp.sqrt(vhat) + eps)).astype(g.dtype)
            if not isinstance(mu_q, _Quantized):
                return step, m, v
            m_q = _Quantized(*_quantize4(m, block_size, signed=True))
            v_q = _Quantized(
                *_quantize4(jnp.sqrt(v), block_size, signed=False)
            )
            return step, m_q, v_q

        flat_g, treedef = jax.tree_util.tree_flatten(updates)
        flat_mu = treedef.flatten_up_to(state.mu)
        flat_nu = treedef.flatten_up_to(state.nu)
        out = [leaf_update(g, mq, nq)
               for g, mq, nq in zip(flat_g, flat_mu, flat_nu)]
        steps = jax.tree_util.tree_unflatten(
            treedef, [o[0] for o in out]
        )
        mu = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
        nu = jax.tree_util.tree_unflatten(treedef, [o[2] for o in out])
        return steps, Adam4bitState(count=count, mu=mu, nu=nu)

    return optax.GradientTransformation(init_fn, update_fn)
