"""AGD: Auto-switchable optimizer with Gradient-Difference preconditioning.

Reference analog: atorch/atorch/optimizers/agd.py:155 (AGD, NeurIPS '23,
"AGD: an Auto-switchable Optimizer using Stepwise Gradient Difference as
Preconditioning Matrix"). The preconditioner's second-moment accumulates
the stepwise gradient DIFFERENCE (g_t - g_{t-1}) instead of the gradient,
and the update auto-switches between SGD-like and Adam-like behavior via
``delta``: where the preconditioned curvature estimate is small the step
degrades toward plain momentum.

Implemented as an optax ``GradientTransformation``; compose with
``optax.chain`` / weight decay the usual way.
"""

from __future__ import annotations

from typing import NamedTuple

import chex
import jax
import jax.numpy as jnp
import optax


class AGDState(NamedTuple):
    count: chex.Array
    mu: optax.Updates        # first moment of gradients
    bu: optax.Updates        # second moment of gradient differences
    prev_grad: optax.Updates


def agd(
    learning_rate: float | optax.Schedule = 1e-3,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    delta: float = 1e-5,
) -> optax.GradientTransformation:
    """AGD gradient transformation.

    ``delta`` is the switching threshold: dimensions whose preconditioner
    sqrt falls below ``delta`` take momentum-SGD-style steps (divide by
    ``delta``), others take Adam-style preconditioned steps.
    """

    def init_fn(params):
        zeros = jax.tree.map(jnp.zeros_like, params)
        return AGDState(
            count=jnp.zeros((), jnp.int32),
            mu=jax.tree.map(jnp.zeros_like, params),
            bu=jax.tree.map(jnp.zeros_like, params),
            prev_grad=zeros,
        )

    def update_fn(updates, state, params=None):
        del params
        count = state.count + 1
        # gradient difference; first step uses the gradient itself
        # (reference: diff = grad on step 1)
        is_first = count == 1
        diff = jax.tree.map(
            lambda g, pg: jnp.where(is_first, g, g - pg),
            updates, state.prev_grad,
        )
        # optax 0.2.x exposes these under tree_utils (optax.tree.* is 0.2.4+)
        tu = optax.tree_utils
        mu = tu.tree_update_moment(updates, state.mu, b1, 1)
        bu = tu.tree_update_moment_per_elem_norm(diff, state.bu, b2, 2)
        mu_hat = tu.tree_bias_correction(mu, b1, count)
        bu_hat = tu.tree_bias_correction(bu, b2, count)
        # auto-switch: max(sqrt(bu_hat), delta) — small curvature
        # estimates degrade to momentum / delta (SGD regime)
        scaled = jax.tree.map(
            lambda m, b: m / jnp.maximum(jnp.sqrt(b) + eps, delta),
            mu_hat, bu_hat,
        )
        # schedules evaluate at the PRE-increment step (optax convention)
        lr = (
            learning_rate(count - 1)
            if callable(learning_rate) else learning_rate
        )
        new_updates = jax.tree.map(lambda u: -lr * u, scaled)
        return new_updates, AGDState(
            count=count, mu=mu, bu=bu, prev_grad=updates
        )

    return optax.GradientTransformation(init_fn, update_fn)
