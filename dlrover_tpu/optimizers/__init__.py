from dlrover_tpu.optimizers.agd import agd  # noqa: F401
from dlrover_tpu.optimizers.low_bit import (  # noqa: F401
    adam_4bit,
    adam_8bit,
)
from dlrover_tpu.optimizers.wsam import wsam  # noqa: F401
