from dlrover_tpu.optimizers.agd import agd  # noqa: F401
from dlrover_tpu.optimizers.low_bit import adam_8bit  # noqa: F401
from dlrover_tpu.optimizers.wsam import wsam  # noqa: F401
