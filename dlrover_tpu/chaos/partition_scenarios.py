"""Partition-tolerance acceptance scenarios (DESIGN.md §30).

Three drills over REAL subprocesses and the declarative
``net_partition`` chaos point, each ending in the trail-invariant
auditor (telemetry/audit.py):

- **zombie sub-master**: SIGSTOP a rack sub-master, replace it, then
  SIGCONT the original. The zombie resumes with buffered state and a
  superseded epoch; its first (keepalive) push must bounce off the
  root's push-direction fence — ``push_fenced`` journaled, zero agent
  acts on anything the zombie held, zero trainer restarts, and the
  trail replay-identical across two seeded runs.

- **asymmetric agent<->root split**: a one-way request-drop window
  followed by a response-loss window on the same link. A lost request
  queues the report; a lost RESPONSE queues a report the root already
  applied — redelivery replays both with their original rids and the
  root's dedup proves single application (exactly one ``persist_ack``
  journal line per report).

- **rack-wide split during a rendezvous round**: the rack's upstream
  link opens mid-round with a 1-second lease. The sub-master's lease
  lapses and it fails closed (``lease_expired`` tier="rack", agents
  redirected); the agents complete the round through the
  direct-to-root fallback; on heal the root lazily expires the rack
  (``lease_expired`` tier="root") and the same incarnation's next
  push re-admits it — lease loss is not epoch loss.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import subprocess
import sys
import threading
import time

from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger

logger = get_logger(__name__)


def _setup(work_dir: str, seed: int, tag: str,
           extra_env: dict | None = None):
    """Shared scaffolding: dirs, subprocess env (shared journal,
    seeded trace streams), and the parent-process env swap the typed
    clients resolve their port files through."""
    os.makedirs(work_dir, exist_ok=True)
    state_dir = os.path.join(work_dir, "state")
    journal_dir = os.path.join(work_dir, "journal")
    port_file = os.path.join(work_dir, "master.port")
    log_path = os.path.join(work_dir, f"{tag}.log")
    os.makedirs(state_dir, exist_ok=True)

    from dlrover_tpu.chaos.scenario import REPO

    env = dict(os.environ)
    env.update({
        EnvKey.JOURNAL_DIR: journal_dir,
        EnvKey.TRACE_ID: f"{tag}{seed}",
        EnvKey.TRACE_SEED: f"{tag}:{seed}",
        "PYTHONPATH": env.get("PYTHONPATH", "") + os.pathsep + REPO,
    })
    env.pop(EnvKey.CHAOS, None)
    env.update(extra_env or {})
    swap_keys = (EnvKey.MASTER_PORT_FILE, EnvKey.JOURNAL_DIR)
    prev_env = {k: os.environ.get(k) for k in swap_keys}
    os.environ[EnvKey.MASTER_PORT_FILE] = port_file
    os.environ[EnvKey.JOURNAL_DIR] = journal_dir
    return state_dir, journal_dir, port_file, log_path, env, prev_env


def _restore_env(prev_env: dict) -> None:
    for key, value in prev_env.items():
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = value


def _spawn_master(env: dict, log, procs: list, state_dir: str,
                  port_file: str, *, min_nodes: int = 2,
                  max_nodes: int = 2, prev_port: str = "") -> str:
    from dlrover_tpu.chaos.scenario import REPO

    proc = subprocess.Popen(
        [sys.executable, "-m", "dlrover_tpu.master.job_master",
         "--job-name", "pt", "--min-nodes", str(min_nodes),
         "--max-nodes", str(max_nodes), "--rdzv-timeout", "60",
         "--state-dir", state_dir, "--port-file", port_file],
        env=env, cwd=REPO, stdout=log, stderr=log,
    )
    procs.append(proc)
    return _await_port(proc, port_file, prev_port, "master")


def _spawn_submaster(env: dict, log, procs: list, root_addr: str,
                     rack_port_file: str, *, rack_id: str = "rackA",
                     prev_port: str = "") -> str:
    from dlrover_tpu.chaos.scenario import REPO

    proc = subprocess.Popen(
        [sys.executable, "-m", "dlrover_tpu.master.submaster",
         "--rack-id", rack_id, "--master-addr", root_addr,
         "--port-file", rack_port_file, "--flush-interval", "0.1"],
        env=env, cwd=REPO, stdout=log, stderr=log,
    )
    procs.append(proc)
    return _await_port(proc, rack_port_file, prev_port, "sub-master")


def _await_port(proc, port_file: str, prev_port: str, what: str,
                timeout: float = 30.0) -> str:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"{what} exited early rc={proc.returncode}"
            )
        try:
            with open(port_file) as f:
                text = f.read().strip()
            if text and text != prev_port:
                return text
        except OSError:
            pass
        time.sleep(0.05)
    raise TimeoutError(f"{what} never published its port")


def _kill_all(procs: list) -> None:
    for proc in procs:
        try:
            proc.send_signal(signal.SIGCONT)  # a stopped proc ignores 9
        except (ProcessLookupError, OSError):
            pass
        try:
            proc.kill()
            proc.wait(timeout=5)
        except (ProcessLookupError, subprocess.TimeoutExpired):
            pass


def _events(journal_dir: str, name: str) -> list[dict]:
    from dlrover_tpu.chaos.scenario import _read_journal

    return [e for e in _read_journal(journal_dir)
            if e.get("name") == name]


def _wait_event(journal_dir: str, name: str, pred=None,
                timeout: float = 20.0) -> dict:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        for e in _events(journal_dir, name):
            if pred is None or pred(e):
                return e
        time.sleep(0.1)
    raise TimeoutError(f"journal never showed a {name!r} event")


# ------------------------------------------------- zombie sub-master


@dataclasses.dataclass
class ZombieScenarioResult:
    rack_epochs: list[int]      # agent-observed: original, replacement
    fenced: list[tuple]         # (rack, stale_epoch, current) journaled
    rounds: tuple[int, int]     # (round through original, through repl)
    restart_actions: int
    trail: dict

    def assert_invariants(self) -> None:
        assert self.rack_epochs[1] > self.rack_epochs[0], (
            f"replacement epoch not above the zombie's: "
            f"{self.rack_epochs}"
        )
        assert len(self.fenced) >= 1, \
            "the resumed zombie's push was never fenced"
        for rack, stale, current in self.fenced:
            assert stale == self.rack_epochs[0] \
                and current == self.rack_epochs[1], (
                    f"fence fired on unexpected epochs: "
                    f"{(rack, stale, current)} vs {self.rack_epochs}"
                )
        assert self.restart_actions == 0, (
            f"{self.restart_actions} restart actions reached agents "
            "across a pure control-plane incident"
        )
        assert self.rounds == (1, 2), \
            f"unexpected rendezvous rounds {self.rounds}"


def zombie_trail(journal_dir: str) -> dict:
    """Canonical, wall-clock-free trail for replay comparison."""
    from dlrover_tpu.chaos.scenario import _read_journal

    failovers, fenced, rounds, leases = [], [], [], []
    for e in _read_journal(journal_dir):
        name = e.get("name")
        if name == "submaster_failover":
            failovers.append((e.get("rack"), int(e.get("old_epoch", 0)),
                              int(e.get("new_epoch", 0))))
        elif name == "push_fenced":
            fenced.append((e.get("rack"), int(e.get("epoch", 0)),
                           int(e.get("current", 0))))
        elif name == "rdzv_round" and e.get("ev") != "b":
            rounds.append(int(e.get("round", 0)))
        elif name == "lease_expired":
            leases.append((e.get("tier"), e.get("rack")))
    return {"failovers": failovers, "fenced": fenced,
            "rounds": rounds, "leases": sorted(set(leases))}


def run_zombie_submaster_scenario(work_dir: str, *, seed: int = 4242
                                  ) -> ZombieScenarioResult:
    """SIGSTOP a rack sub-master mid-life, register a replacement
    (the root mints a higher rack epoch), complete a round through the
    replacement, then SIGCONT the original. The zombie resumes with a
    live socket and buffered state; its first keepalive push carries
    its superseded epoch and must be rejected whole by the root's
    push-direction fence — no agent acts on anything the zombie held,
    and trainers never restart."""
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.common.rpc import RpcClient
    from dlrover_tpu.telemetry.audit import assert_clean

    # a generous lease keeps wall-clock lease expiry out of this
    # trail: the drill is about EPOCH fencing, and the replayed trail
    # must not depend on how long the SIGSTOP window happened to last
    state_dir, journal_dir, port_file, log_path, env, prev_env = \
        _setup(work_dir, seed, "zb",
               extra_env={EnvKey.RACK_LEASE_S: "60"})
    rack_port_file = os.path.join(work_dir, "rack.port")
    sub_env = dict(env)
    sub_env[EnvKey.MASTER_PORT_FILE] = port_file
    log = open(log_path, "ab")
    procs: list[subprocess.Popen] = []
    agents: list[MasterClient] = []
    actions: list[str] = []
    try:
        port = _spawn_master(env, log, procs, state_dir, port_file)
        root_addr = f"127.0.0.1:{port}"
        rack_port = _spawn_submaster(sub_env, log, procs, root_addr,
                                     rack_port_file)

        def make_rack_agent(nid: int) -> MasterClient:
            rack_addr = f"127.0.0.1:{rack_port}"
            agent = MasterClient(
                rack_addr, nid,
                transport=RpcClient(rack_addr, retries=2,
                                    deadline_s=4.0,
                                    backoff_base_s=0.05,
                                    backoff_max_s=0.2),
                port_file=rack_port_file,
                fallback_port_file=port_file,
            )
            agents.append(agent)
            return agent

        def reconnect(agent: MasterClient, timeout: float = 20.0):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                agent.maybe_redial()
                try:
                    actions.append(agent.report_heartbeat(0))
                    return
                except (ConnectionError, TimeoutError, OSError):
                    time.sleep(0.1)
            raise TimeoutError("agent could not reconnect")

        ra0, ra1 = make_rack_agent(0), make_rack_agent(1)
        actions.append(ra0.report_heartbeat(0))
        actions.append(ra1.report_heartbeat(0))
        ra0.join_rendezvous("127.0.0.1:7770", 4)
        ra1.join_rendezvous("127.0.0.1:7771", 4)
        round1 = ra0.wait_comm_world(timeout=30).round
        ra1.wait_comm_world(timeout=30)
        epoch_a = ra0.master_epoch

        # freeze — not kill — the sub-master: a zombie keeps its
        # sockets, its registration, and everything it buffered
        zombie = procs[-1]
        zombie_port = rack_port
        os.kill(zombie.pid, signal.SIGSTOP)
        rack_port = _spawn_submaster(sub_env, log, procs, root_addr,
                                     rack_port_file,
                                     prev_port=rack_port)
        reconnect(ra0)
        reconnect(ra1)
        # the replacement lost the zombie's join floors: re-join
        # (idempotent at the root) and complete a round through it
        ra0.join_rendezvous("127.0.0.1:7770", 4)
        ra1.join_rendezvous("127.0.0.1:7771", 4)
        rw0 = ra0.wait_comm_world(timeout=30)
        rw1 = ra1.wait_comm_world(timeout=30)
        assert rw0.round == rw1.round, \
            "agents disagree on the post-replacement round"
        epoch_b = ra0.master_epoch

        # resume the zombie. Under lease-keepalive gating (§30) an idle
        # zombie would sit out a third of its 60s lease before pushing
        # anything; a straggler agent that never heard about the
        # replacement gives it real traffic to flush, which carries its
        # stale epoch straight into the root's fence. Heartbeats
        # neither journal nor yield actions here, so the replayed
        # trail is unchanged.
        os.kill(zombie.pid, signal.SIGCONT)
        zombie_addr = f"127.0.0.1:{zombie_port}"
        straggler = MasterClient(
            zombie_addr, 0,
            transport=RpcClient(zombie_addr, retries=2,
                                deadline_s=2.0,
                                backoff_base_s=0.05,
                                backoff_max_s=0.2),
        )
        agents.append(straggler)
        deadline = time.monotonic() + 15.0
        while True:
            try:
                straggler.report_heartbeat(0)
                break
            except (ConnectionError, TimeoutError, OSError):
                if time.monotonic() >= deadline:
                    raise TimeoutError(
                        "straggler could not reach the resumed zombie"
                    )
                time.sleep(0.1)
        _wait_event(journal_dir, "push_fenced")
        # the fenced zombie must step down, not retry: give it a few
        # flush ticks and require the fence fired exactly once
        time.sleep(1.0)
        actions.append(ra0.report_heartbeat(0))
        actions.append(ra1.report_heartbeat(0))
    finally:
        _kill_all(procs)
        for agent in agents:
            agent.close()
        log.close()
        _restore_env(prev_env)

    fenced = [(e.get("rack"), int(e.get("epoch", 0)),
               int(e.get("current", 0)))
              for e in _events(journal_dir, "push_fenced")]
    assert len(fenced) == 1, (
        f"a superseded sub-master must push exactly once before "
        f"stepping down, got {len(fenced)} fenced pushes"
    )
    assert_clean(journal_dir, context="zombie sub-master scenario")
    return ZombieScenarioResult(
        rack_epochs=[epoch_a, epoch_b],
        fenced=fenced,
        rounds=(round1, rw0.round),
        restart_actions=sum(1 for a in actions if a == "restart"),
        trail=zombie_trail(journal_dir),
    )


# ------------------------------------------- asymmetric agent<->root


@dataclasses.dataclass
class AsymSplitScenarioResult:
    acked_steps: list[int]      # steps with a persist_ack journal line
    ack_events: int             # total persist_ack lines (dedup proof)
    transitions: list[tuple]    # (src, dst, state) in append order
    trail: dict

    def assert_invariants(self) -> None:
        assert self.acked_steps == [1, 2, 3, 4, 5], (
            f"not every report survived the split: {self.acked_steps}"
        )
        assert self.ack_events == 5, (
            f"rid dedup failed: {self.ack_events} persist_ack lines "
            "for 5 distinct reports (the response-loss replay "
            "double-applied)"
        )
        assert self.transitions == [
            ("agent", "root", "open"), ("agent", "root", "heal"),
            ("root", "agent", "open"), ("root", "agent", "heal"),
        ], f"unexpected partition transitions: {self.transitions}"


def run_asym_split_scenario(work_dir: str, *, seed: int = 4242
                            ) -> AsymSplitScenarioResult:
    """One-way splits on the agent<->root link, one direction at a
    time. The request-drop window queues reports the root never saw;
    the response-loss window queues a report the root DID apply.
    Redelivery replays all of them with their original rids and the
    root's dedup keeps the trail at exactly one ``persist_ack`` per
    report — the §30 'redelivery through an asymmetric split is
    idempotent' proof."""
    from dlrover_tpu import chaos
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.chaos import partition
    from dlrover_tpu.common.rpc import RpcClient
    from dlrover_tpu.telemetry.audit import assert_clean

    state_dir, journal_dir, port_file, log_path, env, prev_env = \
        _setup(work_dir, seed, "as")
    log = open(log_path, "ab")
    procs: list[subprocess.Popen] = []
    agent = None
    try:
        port = _spawn_master(env, log, procs, state_dir, port_file,
                             min_nodes=1, max_nodes=1)
        addr = f"127.0.0.1:{port}"
        agent = MasterClient(
            addr, 0,
            # retries=1 → exactly one link crossing per call, so the
            # occurrence windows below land on known crossings and two
            # seeded runs produce the identical transition trail
            transport=RpcClient(addr, retries=1, deadline_s=4.0,
                                backoff_base_s=0.05,
                                backoff_max_s=0.2),
        )
        # crossing ledger (requests m1..m8, responses m1..m6):
        #   ack1 req m1 pass, resp m1 pass
        #   ack2 req m2 FIRE (open agent>root)  -> queued
        #   ack3 req m3 FIRE                    -> queued
        #   ack4 req m4 pass (heal), resp m2 pass
        #   ack5 req m5 pass, resp m3 FIRE (open root>agent) -> queued
        #        (the root APPLIED ack5 — its response was lost)
        #   flush: ack2 m6/m4, ack3 m7/m5 (heal root>agent),
        #          ack5 m8/m6 -> rid-deduped at the root
        chaos.install({"seed": seed, "faults": [
            {"point": "net_partition", "action": "drop",
             "match": {"src": "agent", "dst": "root"},
             "after": 1, "times": 2},
            {"point": "net_partition", "action": "drop",
             "match": {"src": "root", "dst": "agent"},
             "after": 2, "times": 1},
        ]})
        for step in range(1, 6):
            agent.report_persist_ack(step, 1, {"crc32": step,
                                               "bytes": 8})
        assert agent.redelivery_pending == 3, (
            f"expected acks 2,3,5 queued, have "
            f"{agent.redelivery_pending}"
        )
        replayed = agent.flush_redelivery()
        assert replayed == 3, f"redelivery replayed {replayed} of 3"
    finally:
        chaos.uninstall()
        partition.reset()
        _kill_all(procs)
        if agent is not None:
            agent.close()
        log.close()
        _restore_env(prev_env)

    # the master journals persist_ack once per UNIQUE rid: ack5 was
    # applied twice on the wire but must land once in the trail
    acks = [e for e in _events(journal_dir, "persist_ack")
            if int(e.get("node", -1)) == 0]
    transitions = [(e.get("src"), e.get("dst"), e.get("state"))
                   for e in _events(journal_dir, "net_partition")]
    assert_clean(journal_dir, context="asymmetric split scenario")
    return AsymSplitScenarioResult(
        acked_steps=sorted({int(e.get("step", -1)) for e in acks}),
        ack_events=len(acks),
        transitions=transitions,
        trail={"transitions": transitions,
               "acked": sorted({int(e.get("step", -1)) for e in acks}),
               "ack_events": len(acks)},
    )


# ------------------------------------------------- rack-wide split


@dataclasses.dataclass
class RackSplitScenarioResult:
    completed_round: int
    rack_lease_expired: int     # lease_expired tier="rack" events
    root_lease_expired: int     # lease_expired tier="root" events
    redirected: bool            # agents finished via direct-to-root
    readmitted: bool            # same incarnation pushed again post-heal
    restart_actions: int
    # wall seconds from the link opening to the rack's re-admission
    # (the bench's partition-recovery headline; a measurement, not
    # part of any replay-compared trail)
    recovery_s: float = 0.0

    def assert_invariants(self) -> None:
        assert self.completed_round >= 1, \
            "the round never completed through the fallback"
        assert self.rack_lease_expired >= 1, \
            "the sub-master never failed closed (no rack lease_expired)"
        assert self.root_lease_expired >= 1, \
            "the root never expired the partitioned rack"
        assert self.redirected, \
            "agents were never redirected to the direct-to-root fallback"
        assert self.readmitted, (
            "the healed sub-master was not re-admitted (lease loss "
            "must not be epoch loss)"
        )
        assert self.restart_actions == 0, (
            f"{self.restart_actions} restart actions during a pure "
            "network incident"
        )


def run_rack_split_scenario(work_dir: str, *, seed: int = 4242
                            ) -> RackSplitScenarioResult:
    """Open the rack->root link mid-rendezvous with a 1-second rack
    lease. The sub-master's merge ticks fail for ~3s, its lease lapses
    and it fails closed — agents polling ``wait_comm_world`` get
    ``redirect`` and complete the round against the root directly. On
    heal, the root lazily expires the rack's lease at the sub-master's
    first post-heal push, then accepts that same push (the epoch never
    changed) and re-admits the rack."""
    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.common.rpc import RpcClient
    from dlrover_tpu.telemetry.audit import assert_clean

    state_dir, journal_dir, port_file, log_path, env, prev_env = \
        _setup(work_dir, seed, "rs",
               extra_env={EnvKey.RACK_LEASE_S: "1.0"})
    prev_lease = os.environ.get(EnvKey.RACK_LEASE_S)
    os.environ[EnvKey.RACK_LEASE_S] = "1.0"
    rack_port_file = os.path.join(work_dir, "rack.port")
    sub_env = dict(env)
    sub_env[EnvKey.MASTER_PORT_FILE] = port_file
    # the partition lives in the SUB-MASTER's process, where every
    # upstream crossing (the flush's explicit check AND the transport's
    # request-direction check) matches src=rack,dst=root. after=3 lets
    # the registration and the first merge tick through — the rack gets
    # a real epoch and a lease before the link opens; times=80 then
    # holds the link open for a few seconds of merge ticks against the
    # 1-second lease.
    sub_env[EnvKey.CHAOS] = json.dumps({"seed": seed, "faults": [
        {"point": "net_partition", "action": "drop",
         "match": {"src": "rack", "dst": "root"},
         "after": 3, "times": 80},
    ]})
    log = open(log_path, "ab")
    procs: list[subprocess.Popen] = []
    agents: list[MasterClient] = []
    actions: list[str] = []
    redirected = False
    try:
        port = _spawn_master(env, log, procs, state_dir, port_file)
        root_addr = f"127.0.0.1:{port}"
        rack_port = _spawn_submaster(sub_env, log, procs, root_addr,
                                     rack_port_file)

        def make_rack_agent(nid: int) -> MasterClient:
            rack_addr = f"127.0.0.1:{rack_port}"
            agent = MasterClient(
                rack_addr, nid,
                transport=RpcClient(rack_addr, retries=2,
                                    deadline_s=4.0,
                                    backoff_base_s=0.05,
                                    backoff_max_s=0.2),
                port_file=rack_port_file,
                fallback_port_file=port_file,
            )
            agents.append(agent)
            return agent

        ra0, ra1 = make_rack_agent(0), make_rack_agent(1)
        actions.append(ra0.report_heartbeat(0))
        actions.append(ra1.report_heartbeat(0))

        def join_and_wait(agent: MasterClient, comm_addr: str,
                          timeout: float = 40.0):
            """The agent loop under a failing rack: join once, honor
            the fail-closed redirect (re-joining through the root —
            the lapsed rack dropped its buffered joins), and poll the
            world wherever the client currently points. The join is
            NOT refreshed on every poll: §26 reads a re-join after
            completion as a node restart and would invalidate the
            very round this agent is waiting to read."""
            nonlocal redirected
            deadline = time.monotonic() + timeout
            joined = False
            while time.monotonic() < deadline:
                try:
                    if not joined:
                        agent.join_rendezvous(comm_addr, 4)
                        joined = True
                    resp = agent.get_comm_world()
                except (ConnectionError, TimeoutError, OSError):
                    agent.maybe_redial()
                    joined = False
                    time.sleep(0.2)
                    continue
                if resp.completed:
                    return resp
                if getattr(resp, "redirect", False):
                    redirected = True
                    agent.maybe_redial(prefer_fallback=True)
                    joined = False
                time.sleep(0.2)
            raise TimeoutError("round never completed through the "
                               "fallback")

        results: dict[int, object] = {}

        def drive(agent, nid, comm_addr):
            results[nid] = join_and_wait(agent, comm_addr)

        threads = [
            threading.Thread(target=drive,
                             args=(ra0, 0, "127.0.0.1:7770")),
            threading.Thread(target=drive,
                             args=(ra1, 1, "127.0.0.1:7771")),
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60.0)
        assert results.get(0) is not None and results.get(1) is not None
        rw0, rw1 = results[0], results[1]
        assert rw0.round == rw1.round, \
            "agents disagree on the fallback-completed round"

        # heal: the split closes after its 30 dropped ticks; the
        # sub-master's next accepted push re-admits the rack at the
        # root under its ORIGINAL epoch
        heal_t = _wait_event(
            journal_dir, "net_partition",
            pred=lambda e: e.get("state") == "heal"
            and e.get("src") == "rack",
            timeout=30.0,
        ).get("t", 0)
        _wait_event(journal_dir, "lease_expired",
                    pred=lambda e: e.get("tier") == "root",
                    timeout=20.0)
        deadline = time.monotonic() + 15.0
        readmitted = False
        readmit_t = 0.0
        while time.monotonic() < deadline and not readmitted:
            post_heal = [
                e.get("t", 0)
                for e in _events(journal_dir, "rack_merge")
                if e.get("ev") == "e" and e.get("t", 0) > heal_t
            ]
            if post_heal:
                readmitted = True
                readmit_t = min(post_heal)
            else:
                time.sleep(0.2)
        actions.append(ra0.report_heartbeat(0))
        actions.append(ra1.report_heartbeat(0))
    finally:
        _kill_all(procs)
        for agent in agents:
            agent.close()
        log.close()
        _restore_env(prev_env)
        if prev_lease is None:
            os.environ.pop(EnvKey.RACK_LEASE_S, None)
        else:
            os.environ[EnvKey.RACK_LEASE_S] = prev_lease

    rack_exp = [e for e in _events(journal_dir, "lease_expired")
                if e.get("tier") == "rack"]
    root_exp = [e for e in _events(journal_dir, "lease_expired")
                if e.get("tier") == "root"]
    opens = [e.get("t", 0)
             for e in _events(journal_dir, "net_partition")
             if e.get("state") == "open" and e.get("src") == "rack"]
    recovery_s = (readmit_t - min(opens)
                  if readmitted and opens else 0.0)
    assert_clean(journal_dir, context="rack split scenario")
    return RackSplitScenarioResult(
        completed_round=rw0.round,
        rack_lease_expired=len(rack_exp),
        root_lease_expired=len(root_exp),
        redirected=redirected,
        readmitted=readmitted,
        restart_actions=sum(1 for a in actions if a == "restart"),
        recovery_s=recovery_s,
    )
