"""Sustained network partitions as a declarative chaos domain.

The per-RPC chaos actions (``rpc_call`` drop/reset/garble) sever one
call; a *partition* severs a **link** — every message crossing a
src-tier -> dst-tier edge fails until the rule's occurrence window
closes (DESIGN.md §30). Rules ride the ordinary chaos plan under the
``net_partition`` point, so they inherit the whole replay contract
(seeded per-rule streams, count-based ``after``/``every``/``times``
windows, per-process counters from the inherited env):

    {"point": "net_partition", "action": "drop",
     "match": {"src": "rack", "dst": "root"}, "after": 3, "times": 10}

opens the rack->root edge at its 4th crossing and heals it after 10
dropped crossings. ``match: {"link": "agent|root"}`` matches BOTH
directions of an edge (``link`` is the sorted pair) — a symmetric
split; matching ``src``/``dst`` makes it one-way. Enforcement sites
(``RpcClient.call`` request and response directions, the sub-master
upstream merge tick, the embedding ``service._call`` framing) call
``check(src, dst, ...)`` per crossing and raise ``ConnectionError``
when a fault fires, so the ordinary degraded-mode machinery —
retries, redelivery queues, port-file re-dial, rack leases — is what
a partition exercises.

Every open and heal is journaled once (``net_partition`` instants) and
counted; since transitions derive only from per-rule occurrence
counts, a seeded replay produces the identical open/heal trail.
"""

from __future__ import annotations

import threading

from dlrover_tpu import chaos
from dlrover_tpu.chaos.injector import Fault
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_transitions_total = registry().counter(
    "dlrover_tpu_partition_transitions_total",
    "net_partition link-state transitions: 'open' at the first "
    "dropped crossing, 'heal' at the first crossing that passes again",
    label_names=("link", "state"),
)
_drops_total = registry().counter(
    "dlrover_tpu_partition_drops_total",
    "messages dropped by an open net_partition link, by directed edge",
    label_names=("link",),
)

_lock = threading.Lock()
# (src, dst) -> seq of the fault that opened this directed edge
_open: dict[tuple[str, str], int] = {}


def canonical_link(src: str, dst: str) -> str:
    """Direction-free edge name (``"agent|root"``): what symmetric
    rules match and what the metrics/journal label links with."""
    return "|".join(sorted((str(src), str(dst))))


def reset() -> None:
    """Forget link states (scenario/test hygiene between plans)."""
    with _lock:
        _open.clear()


def check(src: str, dst: str, **ctx) -> Fault | None:
    """Evaluate the ``net_partition`` point for one message crossing
    ``src -> dst``. Returns the fired fault (the site must fail the
    message with ``ConnectionError``) or None (link healthy). Journals
    the open/heal transitions exactly once per episode."""
    if not chaos.ENABLED:
        if _open:
            with _lock:
                _open.clear()
        return None
    edge = f"{src}>{dst}"
    fault = chaos.fire(
        "net_partition", src=src, dst=dst,
        link=canonical_link(src, dst), **ctx
    )
    key = (src, dst)
    if fault is not None:
        _drops_total.labels(edge).inc()
        with _lock:
            newly = key not in _open
            if newly:
                _open[key] = fault.seq
        if newly:
            _transitions_total.labels(edge, "open").inc()
            get_journal().emit("net_partition", state="open",
                               src=src, dst=dst, seq=fault.seq)
            logger.warning("chaos: net partition OPEN on %s (seq %d)",
                           edge, fault.seq)
        return fault
    with _lock:
        opened = _open.pop(key, None)
    if opened is not None:
        _transitions_total.labels(edge, "heal").inc()
        get_journal().emit("net_partition", state="heal",
                           src=src, dst=dst, seq=opened)
        logger.warning("chaos: net partition HEALED on %s", edge)
    return None
