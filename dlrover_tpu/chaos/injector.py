"""Deterministic, seeded fault injection core.

The chaos analog of the reference's chaosblade experiments
(docs/tech_report/fault_tolerance_exps.md): instead of an external tool
randomly killing pods, named injection points are compiled into the
three trust boundaries (RPC transport, checkpoint storage, agent
process management) and a *plan* — a seed plus a list of count-matched
fault rules — decides which firings happen. Count matching (``after`` /
``every`` / ``times`` over rule matches) rather than wall-clock
triggers is what makes a chaos run replayable: two runs of the same
job with the same plan inject the same fault sequence, so the
fault/recovery journal trail is comparable across runs.

A rule fires when its ``point`` matches the injection site, its
``match`` conditions hold against the site's context, its occurrence
window (``after``/``every``/``times``) admits this match, and its
``prob`` coin (per-rule seeded RNG stream, independent of other rules)
lands. Every firing is journaled (``chaos_fault``) and counted
(``dlrover_tpu_chaos_faults_total{point}``), so PR 3's timeline renders
chaos runs with the faults on them.

The module is inert unless a plan is installed (normally from the
``DLROVER_TPU_CHAOS`` env var — a JSON file path or inline JSON); see
``dlrover_tpu/chaos/__init__.py`` for the zero-overhead gating
contract.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import threading

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_faults_total = registry().counter(
    "dlrover_tpu_chaos_faults_total",
    "injected chaos faults by injection point",
    label_names=("point",),
)

# ctx fields that would collide with the journal event envelope
_RESERVED = frozenset(
    {"t", "trace", "span", "name", "ev", "proc", "pid", "parent",
     "point", "action", "seq"}
)


@dataclasses.dataclass
class FaultRule:
    """One fault in a plan.

    ``match`` keys are compared against the injection site's context:
    a plain key means equality; ``<key>_gte`` / ``<key>_lte`` compare
    numerically; ``<key>_suffix`` / ``<key>_contains`` compare as
    strings. A missing context key never matches.
    """

    point: str
    action: str
    args: dict = dataclasses.field(default_factory=dict)
    match: dict = dataclasses.field(default_factory=dict)
    prob: float = 1.0
    after: int = 0   # skip the first N matches
    every: int = 1   # then admit every k-th match
    times: int = 1   # max firings (0 = unlimited)

    @classmethod
    def from_dict(cls, d: dict) -> "FaultRule":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown fault-rule fields: {sorted(unknown)}")
        return cls(**d)

    def matches(self, ctx: dict) -> bool:
        for key, want in self.match.items():
            for suffix in ("_gte", "_lte", "_suffix", "_contains"):
                if key.endswith(suffix):
                    base = key[: -len(suffix)]
                    break
            else:
                suffix, base = "", key
            if base not in ctx:
                return False
            have = ctx[base]
            if suffix == "_gte":
                if not have >= want:
                    return False
            elif suffix == "_lte":
                if not have <= want:
                    return False
            elif suffix == "_suffix":
                if not str(have).endswith(str(want)):
                    return False
            elif suffix == "_contains":
                if str(want) not in str(have):
                    return False
            elif have != want:
                return False
        return True


@dataclasses.dataclass
class Fault:
    """A fired fault, as handed to the injection site. ``rand`` is a
    pre-drawn uniform [0,1) from the rule's own seeded stream — sites
    use it for deterministic choices (which byte to flip) instead of
    reaching for a global RNG."""

    point: str
    action: str
    args: dict
    seq: int
    rand: float


class ChaosController:
    """Evaluates a plan's rules at injection points (thread-safe).

    Per-rule RNG streams are seeded from ``(seed, rule index)``, so one
    rule's coin flips never depend on how often other rules were
    consulted — the property that keeps multi-rule plans replayable.
    Counters are per process: each process in the job tree loads the
    plan from the inherited env and counts its own matches.
    """

    def __init__(self, rules: list[FaultRule], seed: int = 0):
        self.rules = list(rules)
        self.seed = int(seed)
        self._rngs = [
            random.Random((self.seed << 16) ^ (i + 1))
            for i in range(len(self.rules))
        ]
        self._match_counts = [0] * len(self.rules)
        self._fire_counts = [0] * len(self.rules)
        self._seq = 0
        self._lock = threading.Lock()

    # ------------------------------------------------------------- loading

    @classmethod
    def from_spec(cls, spec: dict) -> "ChaosController":
        rules = [FaultRule.from_dict(d) for d in spec.get("faults", [])]
        return cls(rules, seed=int(spec.get("seed", 0)))

    @classmethod
    def from_env(cls, env_value: str) -> "ChaosController":
        """``DLROVER_TPU_CHAOS``: inline JSON (starts with ``{``) or a
        path to a JSON plan file."""
        text = env_value.strip()
        if not text.startswith("{"):
            with open(text, encoding="utf-8") as f:
                text = f.read()
        return cls.from_spec(json.loads(text))

    # -------------------------------------------------------------- firing

    def fire(self, point: str, **ctx) -> Fault | None:
        """The first rule for ``point`` that matches and is admitted
        fires; returns the ``Fault`` (or None). The journal line and
        metric land here so every injected fault leaves a trail."""
        for i, rule in enumerate(self.rules):
            if rule.point != point or not rule.matches(ctx):
                continue
            with self._lock:
                mc = self._match_counts[i]
                self._match_counts[i] = mc + 1
                if mc < rule.after:
                    continue
                if (mc - rule.after) % max(1, rule.every) != 0:
                    continue
                if rule.times and self._fire_counts[i] >= rule.times:
                    continue
                rand = self._rngs[i].random()
                if rule.prob < 1.0 and rand >= rule.prob:
                    continue
                self._fire_counts[i] += 1
                seq = self._seq
                self._seq += 1
            fault = Fault(point=point, action=rule.action,
                          args=dict(rule.args), seq=seq, rand=rand)
            _faults_total.labels(point).inc()
            fields = {
                k: v for k, v in ctx.items()
                if k not in _RESERVED
                and isinstance(v, (str, int, float, bool))
            }
            get_journal().emit("chaos_fault", point=point,
                               action=rule.action, seq=seq, **fields)
            logger.warning("chaos: %s -> %s (seq %d, ctx %s)",
                           point, rule.action, seq, fields)
            return fault
        return None

    def fire_counts(self) -> list[int]:
        with self._lock:
            return list(self._fire_counts)


def controller_from_environ() -> ChaosController | None:
    """Build the process controller from ``DLROVER_TPU_CHAOS`` (one env
    read, at import time — never on a hot path). A malformed plan
    disables injection rather than taking the process down, but loudly:
    a silently-ignored chaos plan would turn a failed drill green."""
    from dlrover_tpu.common.constants import EnvKey

    raw = os.environ.get(EnvKey.CHAOS, "")
    if not raw:
        return None
    try:
        return ChaosController.from_env(raw)
    except (OSError, ValueError, TypeError) as e:
        logger.error("ignoring malformed %s plan (%s); chaos DISABLED",
                     EnvKey.CHAOS, e)
        return None
