"""Deterministic fault injection (chaos harness).

Gating contract — the part hot paths rely on:

- ``chaos.ENABLED`` is a plain module bool. Injection sites guard every
  ``chaos.fire(...)`` with ``if chaos.ENABLED:``, so with chaos off the
  cost on a hot path is one attribute read — no env lookups, no
  function calls, no allocation.
- ``ENABLED`` is computed ONCE at import from ``DLROVER_TPU_CHAOS``
  (a JSON plan file path, or inline JSON). Subprocesses inherit the env
  and boot their own controller, so one plan covers the whole job tree
  (master, agents, trainers) with independent per-process counters.
- Tests flip it in-process with ``install(plan)`` / ``uninstall()``.

See ``chaos/injector.py`` for rule semantics and ``chaos/scenario.py``
for the scenario spec + runner that drives whole jobs through fault
schedules and checks recovery invariants.
"""

from __future__ import annotations

from dlrover_tpu.chaos.injector import (  # noqa: F401
    ChaosController,
    Fault,
    FaultRule,
    controller_from_environ,
)

ENABLED = False
_controller: ChaosController | None = None


def install(plan) -> ChaosController:
    """Install a controller (``ChaosController`` or a plan dict) and
    enable injection for this process."""
    global ENABLED, _controller
    if not isinstance(plan, ChaosController):
        plan = ChaosController.from_spec(plan)
    _controller = plan
    ENABLED = True
    return plan


def uninstall() -> None:
    global ENABLED, _controller
    ENABLED = False
    _controller = None


def fire(point: str, **ctx) -> Fault | None:
    """Consult the installed plan at a named injection point. Returns
    the fired ``Fault`` or None. Sites must guard the call with
    ``if chaos.ENABLED:`` — calling with no controller is a safe no-op,
    but costs a function call the gate exists to avoid."""
    controller = _controller
    if controller is None:
        return None
    return controller.fire(point, **ctx)


def controller() -> ChaosController | None:
    return _controller


_boot = controller_from_environ()
if _boot is not None:
    install(_boot)
del _boot
