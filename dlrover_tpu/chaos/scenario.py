"""Chaos scenario spec + runner: drive a local job through a scheduled
fault sequence and check recovery invariants.

A ``Scenario`` is a seed plus job *legs*; each leg runs the elastic
example under ``dlrover_tpu.run --standalone`` with that leg's fault
plan installed through ``DLROVER_TPU_CHAOS`` (inherited by the master,
agent, and trainer processes). Legs share one checkpoint directory and
one journal, so a later leg restores what an earlier, sabotaged leg
persisted — the cross-restart corruption cases (bit-flipped newest
shard, torn tracker) that can't be exercised inside a single process
tree, because a respawned-in-place trainer restores from shared memory
and never touches storage.

Recovery invariants checked by ``ScenarioResult.assert_invariants``:

- every leg reaches its target step with its expected exit code
  (zero lost data shards: the at-least-once sharding re-runs whatever
  the faults rolled back, and the run still completes);
- the checkpoint directory's newest VERIFIED step equals the final
  step (restore-time verification would accept exactly what the job
  durably committed — nothing corrupt is reachable);
- recovery after the injected kill is bounded (``max_recovery_s``);
- every injected fault left a ``chaos_fault`` journal line
  (``trail["faults"]`` length matches the plan's firing budget).

The canonical *trail* is replay-comparable: two runs of the same
scenario with the same seed must produce an identical trail (the
tier-1 determinism assertion in tests/test_chaos.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Any

from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.storage import atomic_write_file

logger = get_logger(__name__)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_EXAMPLE = os.path.join(REPO, "examples", "train_transformer.py")

# journal names treated as recovery evidence in the canonical trail
RECOVERY_EVENTS = (
    "node_restart", "ckpt_verify_failed", "ckpt_rollback",
    "ckpt_shard_rollback", "state_rollback", "degraded_mode", "reshard",
    "embedding_scale", "embedding_restore",
)


@dataclasses.dataclass
class JobLeg:
    """One elastic job run inside a scenario."""

    name: str
    max_steps: int
    faults: list[dict] = dataclasses.field(default_factory=list)
    cli_args: list[str] = dataclasses.field(default_factory=list)
    train_args: list[str] = dataclasses.field(default_factory=list)
    expect_rc: int = 0


@dataclasses.dataclass
class Scenario:
    name: str
    seed: int
    legs: list[JobLeg]
    max_recovery_s: float = 120.0

    def planned_firings(self) -> int:
        """Upper bound on chaos_fault lines this scenario should emit
        (only rules with a finite ``times`` budget are countable)."""
        total = 0
        for leg in self.legs:
            for rule in leg.faults:
                total += int(rule.get("times", 1)) or 0
        return total


@dataclasses.dataclass
class LegResult:
    name: str
    rc: int
    result: dict | None     # the trainer's --result-file payload
    tail: str
    elapsed_s: float


@dataclasses.dataclass
class ScenarioResult:
    scenario: Scenario
    legs: list[LegResult]
    trail: dict
    recovery_seconds: float | None
    verified_step: int | None
    goodput: float | None
    work_dir: str

    @property
    def completed(self) -> bool:
        return all(
            leg.rc == spec.expect_rc
            and (spec.expect_rc != 0 or (
                leg.result is not None
                and leg.result.get("final_step") == spec.max_steps))
            for leg, spec in zip(self.legs, self.scenario.legs)
        )

    def assert_invariants(self) -> None:
        for leg, spec in zip(self.legs, self.scenario.legs):
            assert leg.rc == spec.expect_rc, (
                f"leg {leg.name}: rc {leg.rc} != {spec.expect_rc}\n"
                f"{leg.tail}"
            )
            if spec.expect_rc == 0:
                assert leg.result is not None, \
                    f"leg {leg.name}: no result file\n{leg.tail}"
                assert leg.result["final_step"] == spec.max_steps, (
                    f"leg {leg.name}: lost progress — final step "
                    f"{leg.result['final_step']} != {spec.max_steps}"
                )
        final = self.legs[-1].result
        if final is not None:
            assert self.verified_step == final["final_step"], (
                f"newest verified step {self.verified_step} != final "
                f"step {final['final_step']} (lost or corrupt shards)"
            )
        planned = self.scenario.planned_firings()
        assert len(self.trail["faults"]) == planned, (
            f"{len(self.trail['faults'])} chaos_fault journal lines for "
            f"{planned} planned firings: {self.trail['faults']}"
        )
        if self.recovery_seconds is not None:
            assert self.recovery_seconds <= self.scenario.max_recovery_s, (
                f"recovery took {self.recovery_seconds:.1f}s "
                f"(bound {self.scenario.max_recovery_s:.0f}s)"
            )


# ------------------------------------------------------------------ journal


def _read_journal(journal_dir: str) -> list[dict]:
    events: list[dict] = []
    base = os.path.join(journal_dir, "events.jsonl")
    for path in (base + ".1", base):
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    try:
                        events.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn final line of a killed writer
        except OSError:
            continue
    return events


def fault_trail(journal_dir: str) -> dict:
    """Canonical, replay-comparable fault/recovery trail.

    Chaos firings are reduced to sorted ``(point, action, k)`` triples
    (k = per-(point,action) occurrence index): invariant to journal
    interleaving across processes/threads, sensitive to any change in
    what actually fired. Recovery events keep their deterministic
    fields (verify kind + step, rollback from/to, restart kind) and are
    sorted the same way.
    """
    events = _read_journal(journal_dir)
    fault_counts: dict[tuple[str, str], int] = {}
    faults: list[list[Any]] = []
    recovery: list[list[Any]] = []
    for e in events:
        name = e.get("name")
        if name == "chaos_fault":
            key = (e.get("point", "?"), e.get("action", "?"))
            k = fault_counts.get(key, 0)
            fault_counts[key] = k + 1
            faults.append([key[0], key[1], k])
        elif name == "node_restart" and e.get("ev") == "b":
            recovery.append(["node_restart", e.get("kind", "")])
        elif name == "ckpt_verify_failed":
            recovery.append(["ckpt_verify_failed", e.get("kind", ""),
                             e.get("step", -1)])
        elif name == "ckpt_rollback":
            recovery.append(["ckpt_rollback", e.get("from_step", -1),
                             e.get("to_step", -1)])
        elif name == "ckpt_shard_rollback":
            recovery.append(["ckpt_shard_rollback", e.get("step", -1),
                             e.get("writer", ""), e.get("kind", "")])
        elif name == "state_rollback":
            recovery.append(["state_rollback"])
        elif name == "degraded_mode":
            recovery.append(["degraded_mode", e.get("state", "")])
        elif name == "reshard":
            # the reshard-recovery choice (agent) and the state remap
            # (mesh) share the name; keep only the deterministic fields
            recovery.append(["reshard", e.get("nodes", 0),
                             bool(e.get("shrink", False))])
        elif name == "embedding_scale":
            # ring scale events are deterministic given stable member
            # ids + seeded rows: moved counts replay exactly (§25)
            recovery.append(["embedding_scale", e.get("from_n", 0),
                             e.get("to_n", 0), e.get("moved", -1),
                             bool(e.get("ok", False))])
        elif name == "embedding_restore":
            recovery.append(["embedding_restore", e.get("step", -1),
                             e.get("rows", -1), e.get("from_w", 0),
                             e.get("to_w", 0)])
    return {"faults": sorted(faults), "recovery": sorted(recovery)}


def _recovery_seconds(journal_dir: str) -> float | None:
    """Injected trainer kill -> the respawned trainer's restore."""
    events = _read_journal(journal_dir)
    t_kill = None
    for e in events:
        if e.get("name") == "chaos_fault" \
                and e.get("point") == "agent_kill_trainer":
            t_kill = e["t"]
            break
    if t_kill is None:
        return None
    restores = [
        e["t"] for e in events
        if e.get("name") == "ckpt_restore" and e.get("t", 0) > t_kill
    ]
    return min(restores) - t_kill if restores else None


# ------------------------------------------------------------------- runner


def run_scenario(scenario: Scenario, work_dir: str, *,
                 env_extra: dict | None = None,
                 example: str = DEFAULT_EXAMPLE,
                 deadline_s: float = 600.0,
                 goodput_leg: int = 0) -> ScenarioResult:
    """Run every leg, then assemble the trail + invariant inputs.

    The runner owns all shared paths (ckpt dir, journal, per-leg plan
    files, IPC dirs — each leg gets a FRESH IPC dir, so a later leg's
    trainer cannot shortcut recovery through the previous leg's shm
    snapshot and must exercise the storage restore path).
    """
    os.makedirs(work_dir, exist_ok=True)
    ckpt_dir = os.path.join(work_dir, "ckpt")
    journal_dir = os.path.join(work_dir, "journal")
    goodput_log = os.path.join(work_dir, "goodput.jsonl")
    deadline = time.monotonic() + deadline_s
    legs: list[LegResult] = []
    ipc_dirs: list[str] = []
    try:
        for i, leg in enumerate(scenario.legs):
            plan_path = os.path.join(work_dir, f"plan_{leg.name}.json")
            # the leg subprocess reads this via DLROVER_TPU_CHAOS:
            # publish atomically (a torn plan would silently disable
            # injection and desync the replay trail)
            atomic_write_file(
                json.dumps({"seed": scenario.seed, "faults": leg.faults}),
                plan_path,
            )
            env = dict(os.environ)
            env.update(env_extra or {})
            env.setdefault(EnvKey.PLATFORM, "cpu")
            env.setdefault(EnvKey.DEVICE_COUNT_OVERRIDE, "1")
            # hermetic compile cache, shared across this scenario's legs
            # (the satellite shared-dir contract) but never across
            # scenarios/test runs — a stale /tmp hit would silently turn
            # a cold-compile assertion warm
            env.setdefault(EnvKey.COMPILE_CACHE_SHARED_DIR,
                           os.path.join(work_dir, "compile_cache"))
            # IPC dirs hold AF_UNIX sockets, whose path limit (~108
            # chars) a nested work_dir easily exceeds: keep them short
            # and top-level, removed in the finally below
            ipc_dir = tempfile.mkdtemp(prefix=f"chaos{i}_")
            ipc_dirs.append(ipc_dir)
            env.update({
                EnvKey.CHAOS: plan_path,
                EnvKey.JOURNAL_DIR: journal_dir,
                EnvKey.IPC_DIR: ipc_dir,
                "PYTHONPATH": (env.get("PYTHONPATH", "")
                               + os.pathsep + REPO),
            })
            result_file = os.path.join(work_dir,
                                       f"result_{leg.name}.json")
            cmd = [
                sys.executable, "-m", "dlrover_tpu.run", "--standalone",
                "--monitor-interval", "0.3", "--max-restarts", "3",
                *leg.cli_args,
                example, "--",
                "--model", "tiny", "--global-batch", "8", "--seq", "128",
                "--log-interval", "5",
                "--ckpt-dir", ckpt_dir,
                "--result-file", result_file,
                "--max-steps", str(leg.max_steps),
                *([] if i != goodput_leg
                  else ["--goodput-log", goodput_log]),
                *leg.train_args,
            ]
            budget = deadline - time.monotonic()
            if budget <= 10:
                legs.append(LegResult(leg.name, -1, None,
                                      "scenario deadline exhausted", 0.0))
                break
            t0 = time.monotonic()
            logger.info("chaos leg %s: %d faults, %d steps",
                        leg.name, len(leg.faults), leg.max_steps)
            try:
                proc = subprocess.run(
                    cmd, env=env, cwd=REPO, timeout=budget,
                    capture_output=True, text=True,
                )
                rc, tail = proc.returncode, (proc.stdout
                                             + proc.stderr)[-3000:]
            except subprocess.TimeoutExpired as e:
                rc = -2
                tail = ((e.stdout or b"")[-3000:].decode(errors="replace")
                        if isinstance(e.stdout, bytes)
                        else str(e.stdout or "")[-3000:])
            result = None
            if os.path.exists(result_file):
                try:
                    with open(result_file, encoding="utf-8") as f:
                        result = json.load(f)
                except (OSError, json.JSONDecodeError):
                    pass
            legs.append(LegResult(leg.name, rc, result, tail,
                                  time.monotonic() - t0))
    finally:
        # never leak a detached standalone master or wedged trainer
        subprocess.run(["pkill", "-9", "-f", example],
                       capture_output=True)
        subprocess.run(
            ["pkill", "-9", "-f", "dlrover_tpu.master.job_master"],
            capture_output=True,
        )
        for d in ipc_dirs:
            shutil.rmtree(d, ignore_errors=True)

    # snapshot the trail BEFORE the verification pass below, which can
    # emit its own journal events if the caller journals to the same dir
    trail = fault_trail(journal_dir)
    recovery_s = _recovery_seconds(journal_dir)

    from dlrover_tpu.checkpoint.integrity import resolve_restore_step
    from dlrover_tpu.common.storage import PosixDiskStorage

    verified = resolve_restore_step(PosixDiskStorage(), ckpt_dir)
    goodput = None
    if os.path.exists(goodput_log):
        try:
            from dlrover_tpu.utils.goodput import compute_goodput

            goodput = compute_goodput(goodput_log).goodput
        except Exception:  # noqa: BLE001 - diagnostics only
            logger.exception("goodput aggregation failed")
    return ScenarioResult(
        scenario=scenario,
        legs=legs,
        trail=trail,
        recovery_seconds=recovery_s,
        verified_step=verified[0] if verified else None,
        goodput=goodput,
        work_dir=work_dir,
    )


# ------------------------------------------------------------------- canned


def canned_sharded_scenario(seed: int = 4242) -> dict:
    """The sharded-persist acceptance schedule (DESIGN.md §20): N=3
    hosts save step 4 (committed, one primary + one ring twin per
    shard), then step 8's save loses host 2 mid-write (injected ENOSPC
    = the host died before its shard landed — no done marker, no ack,
    no commit), step 4's primary shard 0 is bit-flipped on its way to
    disk, and a restore-time read of shard 1 is slowed
    (``storage_read``). ``run_sharded_scenario`` replays it: the
    restore on M=N−1 hosts must land on step 4 — the newest FULLY
    verified step — bit-exactly, through a per-shard twin rollback.
    """
    return {
        "seed": seed,
        "faults": [
            # host 2 dies mid-sharded-save of step 8
            {"point": "storage_write", "action": "enospc",
             "match": {"path_contains": "step-8/",
                       "path_suffix": "node_2.bin"},
             "times": 1},
            # the committed step's primary shard 0 rots on disk
            {"point": "storage_write", "action": "bit_flip",
             "match": {"path_contains": "step-4/",
                       "path_suffix": "node_0.bin"},
             "times": 1},
            # a sick disk slows one verification read at restore
            {"point": "storage_read", "action": "slow",
             "args": {"s": 0.05},
             "match": {"path_suffix": "node_1.bin"},
             "times": 1},
        ],
    }


@dataclasses.dataclass
class ShardedScenarioResult:
    restored_step: int | None
    bad_writers: list[str]
    restored_crc: int           # crc32 over the assembled restored rows
    expected_crc: int           # crc32 over the step-4 source rows
    trail: dict

    @property
    def bit_exact(self) -> bool:
        return self.restored_crc == self.expected_crc

    def assert_invariants(self) -> None:
        assert self.restored_step == 4, (
            f"restore landed on {self.restored_step}, not the newest "
            "fully-verified step 4"
        )
        assert self.bit_exact, "restored rows are not bit-exact"
        assert "0" in self.bad_writers, (
            "the bit-flipped shard 0 was not excluded via per-shard "
            f"rollback (bad={self.bad_writers})"
        )


def run_sharded_scenario(work_dir: str, *, seed: int = 4242,
                         hosts: int = 3, rows: int = 24,
                         cols: int = 16) -> ShardedScenarioResult:
    """Drive the canned sharded-save schedule IN PROCESS.

    Multi-host persist is simulated with ``hosts`` solo-mode
    ``ShardedCheckpointEngine`` instances sharing one checkpoint dir
    (the jax CPU backend cannot run true multi-process collectives in
    this container; the storage/commit/verify path under test is
    process-count-agnostic). Host ``i`` owns rows ``[i*k, (i+1)*k)`` as
    replica 0 and carries host ``i-1``'s rows as the replica-1 ring
    twin (``DLROVER_TPU_CKPT_PERSIST_REPLICAS=2``).
    """
    import zlib

    import numpy as np

    from dlrover_tpu import chaos
    from dlrover_tpu.checkpoint.integrity import resolve_restore_plan
    from dlrover_tpu.checkpoint.sharded import (
        ShardedCheckpointEngine,
        assemble,
        storage_piece_registry,
    )
    from dlrover_tpu.common.storage import PosixDiskStorage

    assert rows % hosts == 0
    k = rows // hosts
    os.makedirs(work_dir, exist_ok=True)
    ckpt_dir = os.path.join(work_dir, "ckpt")
    journal_dir = os.path.join(work_dir, "journal")
    spec = canned_sharded_scenario(seed)
    spec["faults"] = [dict(r) for r in spec["faults"]]

    def state_at(step: int) -> np.ndarray:
        rng = np.random.default_rng(seed + step)
        return rng.standard_normal((rows, cols)).astype(np.float32)

    def host_pieces(data: np.ndarray, i: int) -> tuple[dict, dict]:
        pieces, index = {}, {}
        for replica, owner in ((0, i), (1, (i - 1) % hosts)):
            key = f"w::piece{replica}"
            pieces[key] = data[owner * k:(owner + 1) * k]
            index[key] = {
                "path": "w", "global_shape": [rows, cols],
                "dtype": "float32",
                "index": [[owner * k, (owner + 1) * k], [0, cols]],
                "replica": replica, "persist": True,
            }
        return pieces, index

    prev_env = os.environ.get(EnvKey.CKPT_PERSIST_REPLICAS)
    prev_journal = os.environ.get(EnvKey.JOURNAL_DIR)
    os.environ[EnvKey.CKPT_PERSIST_REPLICAS] = "2"
    os.environ[EnvKey.JOURNAL_DIR] = journal_dir
    chaos.install({"seed": seed, "faults": spec["faults"]})
    engines = []
    try:
        engines = [
            ShardedCheckpointEngine(
                ckpt_dir, node_id=i, node_rank=i, world_size=hosts,
            )
            for i in range(hosts)
        ]
        for step in (4, 8):
            data = state_at(step)
            for i, eng in enumerate(engines):
                pieces, index = host_pieces(data, i)
                eng.snapshot_pieces(step, pieces, index)
                try:
                    # rank-0 last so its commit wait sees the peers
                    if i != 0:
                        eng._solo_saver._persist_step(step)
                except OSError as e:
                    logger.warning("host %d lost mid-save of step %d: "
                                   "%s", i, step, e)
            try:
                # join the commit only for the step that CAN commit:
                # step 8's waiter must not stall the schedule (it polls
                # in the background and dies with the saver, exactly
                # like a real agent outliving a dead peer)
                engines[0]._solo_saver._persist_step(
                    step, commit_block_s=20.0 if step == 4 else 0.0
                )
            except OSError as e:
                logger.warning("host 0 lost mid-save of step %d: %s",
                               step, e)
        # restore on M = N-1 fresh hosts, storage only
        storage = PosixDiskStorage()
        plan = resolve_restore_plan(storage, ckpt_dir)
        restored_step = plan.step if plan else None
        bad = sorted(plan.bad_pieces) if plan else []
        restored_crc = -1
        if plan is not None:
            registry = storage_piece_registry(
                storage, ckpt_dir, plan.step, plan.num_shards,
                bad_pieces=plan.bad_pieces,
            )
            m = hosts - 1
            parts = []
            bounds = [round(rows * j / m) for j in range(m + 1)]
            for j in range(m):  # each surviving host pulls its slice
                parts.append(assemble(
                    [[bounds[j], bounds[j + 1]], [0, cols]],
                    np.dtype("float32"), registry["w"],
                ))
            restored = np.concatenate(parts, axis=0)
            restored_crc = zlib.crc32(restored.tobytes()) & 0xFFFFFFFF
    finally:
        chaos.uninstall()
        for eng in engines:
            try:
                eng.shm_handler.close(unlink=True)
                eng.close()
            except Exception:  # noqa: BLE001 - cleanup best-effort
                pass
        if prev_env is None:
            os.environ.pop(EnvKey.CKPT_PERSIST_REPLICAS, None)
        else:
            os.environ[EnvKey.CKPT_PERSIST_REPLICAS] = prev_env
        if prev_journal is None:
            os.environ.pop(EnvKey.JOURNAL_DIR, None)
        else:
            os.environ[EnvKey.JOURNAL_DIR] = prev_journal
    expected = state_at(4)
    return ShardedScenarioResult(
        restored_step=restored_step,
        bad_writers=bad,
        restored_crc=restored_crc,
        expected_crc=zlib.crc32(expected.tobytes()) & 0xFFFFFFFF,
        trail=fault_trail(journal_dir),
    )


def canned_embedding_scenario(seed: int = 4242) -> dict:
    """The embedding-fabric acceptance schedule (DESIGN.md §25): a
    3-server ring persists step 4 (verified, replicas=2), then a scale
    3→4 loses the new shard server mid-migration — the first
    ``import_rows`` push lands, every later one hits a dead connection
    (``embedding_msg`` reset, enough firings to exhaust the migrate
    retries) — so the coordinator must roll the scale back zero-loss;
    a respawned destination re-runs the scale to completion. Step 8's
    save then bit-flips shard server emb-0's file on its way to disk
    (``storage_write``), and the restore must land on step 8 anyway via
    the per-shard twin rollback (emb-0's block verifies in its ring
    successor's file). ``run_embedding_scenario`` replays it.
    """
    return {
        "seed": seed,
        "faults": [
            # the new shard server dies mid-migration: the first row
            # push lands, then the wire goes dead — 3 firings cover
            # every migrate retry so phase 1 provably fails
            {"point": "embedding_msg", "action": "reset",
             "match": {"op": "import_rows"},
             "after": 1, "times": 3},
            # the newest step's primary shard rots on its way to disk
            {"point": "storage_write", "action": "bit_flip",
             "match": {"path_contains": "step-8/",
                       "path_suffix": "node_emb-0.bin"},
             "times": 1},
        ],
    }


@dataclasses.dataclass
class EmbeddingScenarioResult:
    moved: int                  # rows moved by the successful re-scale
    total_rows: int             # ring row count at the scale event
    restored_step: int | None
    restored_crc: int           # crc32 over the reassembled restored rows
    expected_crc: int           # crc32 over the pre-persist source rows
    rows_after_rollback: int    # ring rows right after the failed scale
    trail: dict

    @property
    def bit_exact(self) -> bool:
        return self.restored_crc == self.expected_crc

    @property
    def moved_frac(self) -> float:
        return self.moved / max(1, self.total_rows)

    def assert_invariants(self) -> None:
        assert self.rows_after_rollback == self.total_rows, (
            "the failed scale lost rows: "
            f"{self.rows_after_rollback} != {self.total_rows}"
        )
        assert 0 < self.moved_frac <= 1.6 / 4, (
            f"3→4 scale moved {self.moved_frac:.2f} of rows; the ring "
            "bound is ~1/N"
        )
        assert self.restored_step == 8, (
            f"restore landed on {self.restored_step}, not the newest "
            "verified step 8 (twin rollback should cover the bit flip)"
        )
        assert self.bit_exact, "restored rows are not row-exact"


def run_embedding_scenario(work_dir: str, *, seed: int = 4242,
                           dim: int = 8, rows: int = 96
                           ) -> EmbeddingScenarioResult:
    """Drive the canned embedding schedule IN PROCESS (CPU-only).

    A real multi-host fabric runs the same ``FabricShardServer``
    processes over TCP; in-process servers exercise the identical wire
    protocol (every call crosses a real socket), so the
    migration-rollback and twin-restore paths under test are
    deployment-agnostic.
    """
    import zlib

    import numpy as np

    from dlrover_tpu import chaos
    from dlrover_tpu.embedding.fabric import (
        FabricClient,
        FabricShardServer,
        start_local_fabric,
    )

    os.makedirs(work_dir, exist_ok=True)
    ckpt_dir = os.path.join(work_dir, "ckpt")
    journal_dir = os.path.join(work_dir, "journal")
    spec = canned_embedding_scenario(seed)

    prev_journal = os.environ.get(EnvKey.JOURNAL_DIR)
    os.environ[EnvKey.JOURNAL_DIR] = journal_dir
    coord = None
    servers: list = []
    client = None
    try:
        coord, servers = start_local_fabric(
            3, dim=dim, num_slots=2, seed=seed, replicas=2,
            ckpt_dir=ckpt_dir,
        )
        client = FabricClient(coordinator_addr=coord.addr, dim=dim,
                              async_apply=False, retry_window_s=20.0)
        rng = np.random.default_rng(seed)
        ids = rng.choice(1 << 20, size=rows, replace=False).astype(
            np.int64
        )
        client.lookup(ids)
        for _ in range(4):
            client.apply("adam", ids,
                         rng.standard_normal((rows, dim)).astype(
                             np.float32), lr=1e-2)
        client.persist(4)

        chaos.install({"seed": spec["seed"], "faults": spec["faults"]})
        # the destination that will die mid-migration
        doomed = FabricShardServer(dim=dim, num_slots=2,
                                   member="emb-3", seed=seed,
                                   host="127.0.0.1").start()
        members4 = {s.member: s.addr for s in servers}
        members4["emb-3"] = doomed.addr
        total = coord.total_rows()
        try:
            coord.scale(members4, migrate_retries=3)
            raise AssertionError(
                "scale survived the mid-migration kill"
            )
        except Exception:  # noqa: BLE001 - the injected failure
            pass
        # rollback left the OLD ring serving every row
        rows_after_rollback = coord.total_rows()
        # the "killed" server really dies; a respawn takes its place
        doomed.stop()
        respawn = FabricShardServer(dim=dim, num_slots=2,
                                    member="emb-3", seed=seed,
                                    host="127.0.0.1").start()
        servers.append(respawn)
        members4["emb-3"] = respawn.addr
        route = coord.scale(members4, migrate_retries=3)
        moved = int(_read_moved(journal_dir, version=route.version))
        client.refresh_route()
        for _ in range(4):
            client.apply("adam", ids,
                         rng.standard_normal((rows, dim)).astype(
                             np.float32), lr=1e-2)
        expected = client.export(with_slots=True)
        order = np.argsort(expected["keys"], kind="stable")
        expected_crc = zlib.crc32(
            expected["values"][order].tobytes()
        ) & 0xFFFFFFFF
        client.persist(8)    # emb-0's file bit-flips on the way down

        # sabotage the live tables so only a real restore can match
        for s in servers:
            if s.table is not None and len(s.table):
                snap = s.table.export(with_slots=False)
                s.table.remove(snap["keys"])
        restored = coord.restore()
        restored_step = restored["step"] if restored else None
        got = client.export(with_slots=True)
        order = np.argsort(got["keys"], kind="stable")
        restored_crc = zlib.crc32(
            got["values"][order].tobytes()
        ) & 0xFFFFFFFF
    finally:
        chaos.uninstall()
        if client is not None:
            client.close()
        if coord is not None:
            coord.stop()
        for s in servers:
            s.stop()
        if prev_journal is None:
            os.environ.pop(EnvKey.JOURNAL_DIR, None)
        else:
            os.environ[EnvKey.JOURNAL_DIR] = prev_journal
    return EmbeddingScenarioResult(
        moved=moved,
        total_rows=total,
        restored_step=restored_step,
        restored_crc=restored_crc,
        expected_crc=expected_crc,
        rows_after_rollback=rows_after_rollback,
        trail=fault_trail(journal_dir),
    )


def _read_moved(journal_dir: str, version: int) -> int:
    """Moved-row count of the ``embedding_scale`` event that committed
    ``version`` (the journal is the scale's evidence of record)."""
    for e in _read_journal(journal_dir):
        if e.get("name") == "embedding_scale" and e.get("ok") \
                and int(e.get("version", -1)) == version:
            return int(e.get("moved", -1))
    return -1


def canned_scenario(seed: int = 1234, *, kill_step: int = 7,
                    save_interval: int = 6, max_steps: int = 14,
                    resume_steps: int = 20) -> Scenario:
    """The acceptance schedule: trainer SIGKILLed mid-save (an injected
    slow fsync stretches the step-``save_interval`` persist so the kill
    provably lands inside it), the newest shard bit-flipped on its way
    to disk, and the master RPC flaking on the post-kill re-join. Leg 2
    restores from storage in a fresh process tree and must roll back to
    the newest verified step.
    """
    leg1 = JobLeg(
        name="train_kill_mid_save",
        max_steps=max_steps,
        faults=[
            {"point": "storage_write", "action": "slow_fsync",
             "args": {"s": 2.0},
             "match": {"path_contains": f"step-{save_interval}/",
                       "path_suffix": ".bin"},
             "times": 1},
            {"point": "agent_kill_trainer", "action": "kill",
             "args": {"sig": 9},
             "match": {"step_gte": kill_step}, "times": 1},
            {"point": "rpc_call", "action": "drop",
             "match": {"msg": "JoinRendezvousRequest"},
             "after": 1, "times": 1},
            {"point": "storage_write", "action": "bit_flip",
             "match": {"path_contains": f"step-{max_steps}/",
                       "path_suffix": ".bin"},
             "times": 1},
        ],
        train_args=["--ckpt-interval", str(save_interval),
                    "--mem-ckpt-interval", "2", "--step-delay", "0.15"],
    )
    leg2 = JobLeg(
        name="restore_verify_rollback",
        max_steps=resume_steps,
        faults=[],
        train_args=["--ckpt-interval", str(save_interval),
                    "--mem-ckpt-interval", "2"],
    )
    return Scenario(name="kill_flip_flake", seed=seed, legs=[leg1, leg2])
