"""Chaos scenario spec + runner: drive a local job through a scheduled
fault sequence and check recovery invariants.

A ``Scenario`` is a seed plus job *legs*; each leg runs the elastic
example under ``dlrover_tpu.run --standalone`` with that leg's fault
plan installed through ``DLROVER_TPU_CHAOS`` (inherited by the master,
agent, and trainer processes). Legs share one checkpoint directory and
one journal, so a later leg restores what an earlier, sabotaged leg
persisted — the cross-restart corruption cases (bit-flipped newest
shard, torn tracker) that can't be exercised inside a single process
tree, because a respawned-in-place trainer restores from shared memory
and never touches storage.

Recovery invariants checked by ``ScenarioResult.assert_invariants``:

- every leg reaches its target step with its expected exit code
  (zero lost data shards: the at-least-once sharding re-runs whatever
  the faults rolled back, and the run still completes);
- the checkpoint directory's newest VERIFIED step equals the final
  step (restore-time verification would accept exactly what the job
  durably committed — nothing corrupt is reachable);
- recovery after the injected kill is bounded (``max_recovery_s``);
- every injected fault left a ``chaos_fault`` journal line
  (``trail["faults"]`` length matches the plan's firing budget).

The canonical *trail* is replay-comparable: two runs of the same
scenario with the same seed must produce an identical trail (the
tier-1 determinism assertion in tests/test_chaos.py).
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Any

from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.storage import atomic_write_file

logger = get_logger(__name__)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_EXAMPLE = os.path.join(REPO, "examples", "train_transformer.py")

# journal names treated as recovery evidence in the canonical trail
RECOVERY_EVENTS = (
    "node_restart", "ckpt_verify_failed", "ckpt_rollback",
    "ckpt_shard_rollback", "state_rollback", "degraded_mode", "reshard",
    "embedding_scale", "embedding_restore",
)


@dataclasses.dataclass
class JobLeg:
    """One elastic job run inside a scenario."""

    name: str
    max_steps: int
    faults: list[dict] = dataclasses.field(default_factory=list)
    cli_args: list[str] = dataclasses.field(default_factory=list)
    train_args: list[str] = dataclasses.field(default_factory=list)
    expect_rc: int = 0


@dataclasses.dataclass
class Scenario:
    name: str
    seed: int
    legs: list[JobLeg]
    max_recovery_s: float = 120.0

    def planned_firings(self) -> int:
        """Upper bound on chaos_fault lines this scenario should emit
        (only rules with a finite ``times`` budget are countable)."""
        total = 0
        for leg in self.legs:
            for rule in leg.faults:
                total += int(rule.get("times", 1)) or 0
        return total


@dataclasses.dataclass
class LegResult:
    name: str
    rc: int
    result: dict | None     # the trainer's --result-file payload
    tail: str
    elapsed_s: float


@dataclasses.dataclass
class ScenarioResult:
    scenario: Scenario
    legs: list[LegResult]
    trail: dict
    recovery_seconds: float | None
    verified_step: int | None
    goodput: float | None
    work_dir: str

    @property
    def completed(self) -> bool:
        return all(
            leg.rc == spec.expect_rc
            and (spec.expect_rc != 0 or (
                leg.result is not None
                and leg.result.get("final_step") == spec.max_steps))
            for leg, spec in zip(self.legs, self.scenario.legs)
        )

    def assert_invariants(self) -> None:
        for leg, spec in zip(self.legs, self.scenario.legs):
            assert leg.rc == spec.expect_rc, (
                f"leg {leg.name}: rc {leg.rc} != {spec.expect_rc}\n"
                f"{leg.tail}"
            )
            if spec.expect_rc == 0:
                assert leg.result is not None, \
                    f"leg {leg.name}: no result file\n{leg.tail}"
                assert leg.result["final_step"] == spec.max_steps, (
                    f"leg {leg.name}: lost progress — final step "
                    f"{leg.result['final_step']} != {spec.max_steps}"
                )
        final = self.legs[-1].result
        if final is not None:
            assert self.verified_step == final["final_step"], (
                f"newest verified step {self.verified_step} != final "
                f"step {final['final_step']} (lost or corrupt shards)"
            )
        planned = self.scenario.planned_firings()
        assert len(self.trail["faults"]) == planned, (
            f"{len(self.trail['faults'])} chaos_fault journal lines for "
            f"{planned} planned firings: {self.trail['faults']}"
        )
        if self.recovery_seconds is not None:
            assert self.recovery_seconds <= self.scenario.max_recovery_s, (
                f"recovery took {self.recovery_seconds:.1f}s "
                f"(bound {self.scenario.max_recovery_s:.0f}s)"
            )


# ------------------------------------------------------------------ journal


def _read_journal(journal_dir: str) -> list[dict]:
    events: list[dict] = []
    base = os.path.join(journal_dir, "events.jsonl")
    for path in (base + ".1", base):
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    try:
                        events.append(json.loads(line))
                    except json.JSONDecodeError:
                        continue  # torn final line of a killed writer
        except OSError:
            continue
    return events


def fault_trail(journal_dir: str) -> dict:
    """Canonical, replay-comparable fault/recovery trail.

    Chaos firings are reduced to sorted ``(point, action, k)`` triples
    (k = per-(point,action) occurrence index): invariant to journal
    interleaving across processes/threads, sensitive to any change in
    what actually fired. Recovery events keep their deterministic
    fields (verify kind + step, rollback from/to, restart kind) and are
    sorted the same way.
    """
    events = _read_journal(journal_dir)
    fault_counts: dict[tuple[str, str], int] = {}
    faults: list[list[Any]] = []
    recovery: list[list[Any]] = []
    for e in events:
        name = e.get("name")
        if name == "chaos_fault":
            key = (e.get("point", "?"), e.get("action", "?"))
            k = fault_counts.get(key, 0)
            fault_counts[key] = k + 1
            faults.append([key[0], key[1], k])
        elif name == "node_restart" and e.get("ev") == "b":
            recovery.append(["node_restart", e.get("kind", "")])
        elif name == "ckpt_verify_failed":
            recovery.append(["ckpt_verify_failed", e.get("kind", ""),
                             e.get("step", -1)])
        elif name == "ckpt_rollback":
            recovery.append(["ckpt_rollback", e.get("from_step", -1),
                             e.get("to_step", -1)])
        elif name == "ckpt_shard_rollback":
            recovery.append(["ckpt_shard_rollback", e.get("step", -1),
                             e.get("writer", ""), e.get("kind", "")])
        elif name == "state_rollback":
            recovery.append(["state_rollback"])
        elif name == "degraded_mode":
            recovery.append(["degraded_mode", e.get("state", "")])
        elif name == "reshard":
            # the reshard-recovery choice (agent) and the state remap
            # (mesh) share the name; keep only the deterministic fields
            recovery.append(["reshard", e.get("nodes", 0),
                             bool(e.get("shrink", False))])
        elif name == "embedding_scale":
            # ring scale events are deterministic given stable member
            # ids + seeded rows: moved counts replay exactly (§25)
            recovery.append(["embedding_scale", e.get("from_n", 0),
                             e.get("to_n", 0), e.get("moved", -1),
                             bool(e.get("ok", False))])
        elif name == "embedding_restore":
            recovery.append(["embedding_restore", e.get("step", -1),
                             e.get("rows", -1), e.get("from_w", 0),
                             e.get("to_w", 0)])
    return {"faults": sorted(faults), "recovery": sorted(recovery)}


def _recovery_seconds(journal_dir: str) -> float | None:
    """Injected trainer kill -> the respawned trainer's restore."""
    events = _read_journal(journal_dir)
    t_kill = None
    for e in events:
        if e.get("name") == "chaos_fault" \
                and e.get("point") == "agent_kill_trainer":
            t_kill = e["t"]
            break
    if t_kill is None:
        return None
    restores = [
        e["t"] for e in events
        if e.get("name") == "ckpt_restore" and e.get("t", 0) > t_kill
    ]
    return min(restores) - t_kill if restores else None


# ------------------------------------------------------------------- runner


def run_scenario(scenario: Scenario, work_dir: str, *,
                 env_extra: dict | None = None,
                 example: str = DEFAULT_EXAMPLE,
                 deadline_s: float = 600.0,
                 goodput_leg: int = 0) -> ScenarioResult:
    """Run every leg, then assemble the trail + invariant inputs.

    The runner owns all shared paths (ckpt dir, journal, per-leg plan
    files, IPC dirs — each leg gets a FRESH IPC dir, so a later leg's
    trainer cannot shortcut recovery through the previous leg's shm
    snapshot and must exercise the storage restore path).
    """
    os.makedirs(work_dir, exist_ok=True)
    ckpt_dir = os.path.join(work_dir, "ckpt")
    journal_dir = os.path.join(work_dir, "journal")
    goodput_log = os.path.join(work_dir, "goodput.jsonl")
    deadline = time.monotonic() + deadline_s
    legs: list[LegResult] = []
    ipc_dirs: list[str] = []
    try:
        for i, leg in enumerate(scenario.legs):
            plan_path = os.path.join(work_dir, f"plan_{leg.name}.json")
            # the leg subprocess reads this via DLROVER_TPU_CHAOS:
            # publish atomically (a torn plan would silently disable
            # injection and desync the replay trail)
            atomic_write_file(
                json.dumps({"seed": scenario.seed, "faults": leg.faults}),
                plan_path,
            )
            env = dict(os.environ)
            env.update(env_extra or {})
            env.setdefault(EnvKey.PLATFORM, "cpu")
            env.setdefault(EnvKey.DEVICE_COUNT_OVERRIDE, "1")
            # hermetic compile cache, shared across this scenario's legs
            # (the satellite shared-dir contract) but never across
            # scenarios/test runs — a stale /tmp hit would silently turn
            # a cold-compile assertion warm
            env.setdefault(EnvKey.COMPILE_CACHE_SHARED_DIR,
                           os.path.join(work_dir, "compile_cache"))
            # IPC dirs hold AF_UNIX sockets, whose path limit (~108
            # chars) a nested work_dir easily exceeds: keep them short
            # and top-level, removed in the finally below
            ipc_dir = tempfile.mkdtemp(prefix=f"chaos{i}_")
            ipc_dirs.append(ipc_dir)
            env.update({
                EnvKey.CHAOS: plan_path,
                EnvKey.JOURNAL_DIR: journal_dir,
                EnvKey.IPC_DIR: ipc_dir,
                # deterministic span ids (§27): two runs of the same
                # seeded scenario assemble byte-identical trace trees.
                # The leg name is part of the seed — every leg restarts
                # its processes (resetting the per-process span counter),
                # so legs sharing a seed would repeat id streams into the
                # same journal and collide in the assembler's id map
                EnvKey.TRACE_SEED:
                    f"{scenario.name}:{leg.name}:{scenario.seed}",
                # each leg is its own JOB: pin a deterministic per-leg
                # trace id so the auditor's per-job invariant scoping
                # sees leg B's round 1 as a fresh job, not a reissue —
                # and so a trace id leaked into the harness process's
                # environ can never glue the legs together
                EnvKey.TRACE_ID:
                    f"{scenario.name}:{leg.name}:{scenario.seed}",
                "PYTHONPATH": (env.get("PYTHONPATH", "")
                               + os.pathsep + REPO),
            })
            result_file = os.path.join(work_dir,
                                       f"result_{leg.name}.json")
            cmd = [
                sys.executable, "-m", "dlrover_tpu.run", "--standalone",
                "--monitor-interval", "0.3", "--max-restarts", "3",
                *leg.cli_args,
                example, "--",
                "--model", "tiny", "--global-batch", "8", "--seq", "128",
                "--log-interval", "5",
                "--ckpt-dir", ckpt_dir,
                "--result-file", result_file,
                "--max-steps", str(leg.max_steps),
                *([] if i != goodput_leg
                  else ["--goodput-log", goodput_log]),
                *leg.train_args,
            ]
            budget = deadline - time.monotonic()
            if budget <= 10:
                legs.append(LegResult(leg.name, -1, None,
                                      "scenario deadline exhausted", 0.0))
                break
            t0 = time.monotonic()
            logger.info("chaos leg %s: %d faults, %d steps",
                        leg.name, len(leg.faults), leg.max_steps)
            try:
                proc = subprocess.run(
                    cmd, env=env, cwd=REPO, timeout=budget,
                    capture_output=True, text=True,
                )
                rc, tail = proc.returncode, (proc.stdout
                                             + proc.stderr)[-3000:]
            except subprocess.TimeoutExpired as e:
                rc = -2
                tail = ((e.stdout or b"")[-3000:].decode(errors="replace")
                        if isinstance(e.stdout, bytes)
                        else str(e.stdout or "")[-3000:])
            result = None
            if os.path.exists(result_file):
                try:
                    with open(result_file, encoding="utf-8") as f:
                        result = json.load(f)
                except (OSError, json.JSONDecodeError):
                    pass
            legs.append(LegResult(leg.name, rc, result, tail,
                                  time.monotonic() - t0))
    finally:
        # never leak a detached standalone master or wedged trainer
        subprocess.run(["pkill", "-9", "-f", example],
                       capture_output=True)
        subprocess.run(
            ["pkill", "-9", "-f", "dlrover_tpu.master.job_master"],
            capture_output=True,
        )
        for d in ipc_dirs:
            shutil.rmtree(d, ignore_errors=True)

    # snapshot the trail BEFORE the verification pass below, which can
    # emit its own journal events if the caller journals to the same dir
    trail = fault_trail(journal_dir)
    recovery_s = _recovery_seconds(journal_dir)

    from dlrover_tpu.checkpoint.integrity import resolve_restore_step
    from dlrover_tpu.common.storage import PosixDiskStorage

    verified = resolve_restore_step(PosixDiskStorage(), ckpt_dir)
    goodput = None
    if os.path.exists(goodput_log):
        try:
            from dlrover_tpu.utils.goodput import compute_goodput

            goodput = compute_goodput(goodput_log).goodput
        except Exception:  # noqa: BLE001 - diagnostics only
            logger.exception("goodput aggregation failed")
    # trail-invariant audit (§30): every chaos scenario ends by proving
    # the merged journals violate none of the safety invariants
    from dlrover_tpu.telemetry.audit import assert_clean

    assert_clean(journal_dir, context=f"scenario {scenario.name}")
    return ScenarioResult(
        scenario=scenario,
        legs=legs,
        trail=trail,
        recovery_seconds=recovery_s,
        verified_step=verified[0] if verified else None,
        goodput=goodput,
        work_dir=work_dir,
    )


# ------------------------------------------------------------------- canned


def canned_sharded_scenario(seed: int = 4242) -> dict:
    """The sharded-persist acceptance schedule (DESIGN.md §20): N=3
    hosts save step 4 (committed, one primary + one ring twin per
    shard), then step 8's save loses host 2 mid-write (injected ENOSPC
    = the host died before its shard landed — no done marker, no ack,
    no commit), step 4's primary shard 0 is bit-flipped on its way to
    disk, and a restore-time read of shard 1 is slowed
    (``storage_read``). ``run_sharded_scenario`` replays it: the
    restore on M=N−1 hosts must land on step 4 — the newest FULLY
    verified step — bit-exactly, through a per-shard twin rollback.
    """
    return {
        "seed": seed,
        "faults": [
            # host 2 dies mid-sharded-save of step 8
            {"point": "storage_write", "action": "enospc",
             "match": {"path_contains": "step-8/",
                       "path_suffix": "node_2.bin"},
             "times": 1},
            # the committed step's primary shard 0 rots on disk
            {"point": "storage_write", "action": "bit_flip",
             "match": {"path_contains": "step-4/",
                       "path_suffix": "node_0.bin"},
             "times": 1},
            # a sick disk slows one verification read at restore
            {"point": "storage_read", "action": "slow",
             "args": {"s": 0.05},
             "match": {"path_suffix": "node_1.bin"},
             "times": 1},
        ],
    }


@dataclasses.dataclass
class ShardedScenarioResult:
    restored_step: int | None
    bad_writers: list[str]
    restored_crc: int           # crc32 over the assembled restored rows
    expected_crc: int           # crc32 over the step-4 source rows
    trail: dict

    @property
    def bit_exact(self) -> bool:
        return self.restored_crc == self.expected_crc

    def assert_invariants(self) -> None:
        assert self.restored_step == 4, (
            f"restore landed on {self.restored_step}, not the newest "
            "fully-verified step 4"
        )
        assert self.bit_exact, "restored rows are not bit-exact"
        assert "0" in self.bad_writers, (
            "the bit-flipped shard 0 was not excluded via per-shard "
            f"rollback (bad={self.bad_writers})"
        )


def run_sharded_scenario(work_dir: str, *, seed: int = 4242,
                         hosts: int = 3, rows: int = 24,
                         cols: int = 16) -> ShardedScenarioResult:
    """Drive the canned sharded-save schedule IN PROCESS.

    Multi-host persist is simulated with ``hosts`` solo-mode
    ``ShardedCheckpointEngine`` instances sharing one checkpoint dir
    (the jax CPU backend cannot run true multi-process collectives in
    this container; the storage/commit/verify path under test is
    process-count-agnostic). Host ``i`` owns rows ``[i*k, (i+1)*k)`` as
    replica 0 and carries host ``i-1``'s rows as the replica-1 ring
    twin (``DLROVER_TPU_CKPT_PERSIST_REPLICAS=2``).
    """
    import zlib

    import numpy as np

    from dlrover_tpu import chaos
    from dlrover_tpu.checkpoint.integrity import resolve_restore_plan
    from dlrover_tpu.checkpoint.sharded import (
        ShardedCheckpointEngine,
        assemble,
        storage_piece_registry,
    )
    from dlrover_tpu.common.storage import PosixDiskStorage

    assert rows % hosts == 0
    k = rows // hosts
    os.makedirs(work_dir, exist_ok=True)
    ckpt_dir = os.path.join(work_dir, "ckpt")
    journal_dir = os.path.join(work_dir, "journal")
    spec = canned_sharded_scenario(seed)
    spec["faults"] = [dict(r) for r in spec["faults"]]

    def state_at(step: int) -> np.ndarray:
        rng = np.random.default_rng(seed + step)
        return rng.standard_normal((rows, cols)).astype(np.float32)

    def host_pieces(data: np.ndarray, i: int) -> tuple[dict, dict]:
        pieces, index = {}, {}
        for replica, owner in ((0, i), (1, (i - 1) % hosts)):
            key = f"w::piece{replica}"
            pieces[key] = data[owner * k:(owner + 1) * k]
            index[key] = {
                "path": "w", "global_shape": [rows, cols],
                "dtype": "float32",
                "index": [[owner * k, (owner + 1) * k], [0, cols]],
                "replica": replica, "persist": True,
            }
        return pieces, index

    prev_env = os.environ.get(EnvKey.CKPT_PERSIST_REPLICAS)
    prev_journal = os.environ.get(EnvKey.JOURNAL_DIR)
    os.environ[EnvKey.CKPT_PERSIST_REPLICAS] = "2"
    os.environ[EnvKey.JOURNAL_DIR] = journal_dir
    chaos.install({"seed": seed, "faults": spec["faults"]})
    engines = []
    try:
        engines = [
            ShardedCheckpointEngine(
                ckpt_dir, node_id=i, node_rank=i, world_size=hosts,
            )
            for i in range(hosts)
        ]
        for step in (4, 8):
            data = state_at(step)
            for i, eng in enumerate(engines):
                pieces, index = host_pieces(data, i)
                eng.snapshot_pieces(step, pieces, index)
                try:
                    # rank-0 last so its commit wait sees the peers
                    if i != 0:
                        eng._solo_saver._persist_step(step)
                except OSError as e:
                    logger.warning("host %d lost mid-save of step %d: "
                                   "%s", i, step, e)
            try:
                # join the commit only for the step that CAN commit:
                # step 8's waiter must not stall the schedule (it polls
                # in the background and dies with the saver, exactly
                # like a real agent outliving a dead peer)
                engines[0]._solo_saver._persist_step(
                    step, commit_block_s=20.0 if step == 4 else 0.0
                )
            except OSError as e:
                logger.warning("host 0 lost mid-save of step %d: %s",
                               step, e)
        # restore on M = N-1 fresh hosts, storage only
        storage = PosixDiskStorage()
        plan = resolve_restore_plan(storage, ckpt_dir)
        restored_step = plan.step if plan else None
        bad = sorted(plan.bad_pieces) if plan else []
        restored_crc = -1
        if plan is not None:
            registry = storage_piece_registry(
                storage, ckpt_dir, plan.step, plan.num_shards,
                bad_pieces=plan.bad_pieces,
            )
            m = hosts - 1
            parts = []
            bounds = [round(rows * j / m) for j in range(m + 1)]
            for j in range(m):  # each surviving host pulls its slice
                parts.append(assemble(
                    [[bounds[j], bounds[j + 1]], [0, cols]],
                    np.dtype("float32"), registry["w"],
                ))
            restored = np.concatenate(parts, axis=0)
            restored_crc = zlib.crc32(restored.tobytes()) & 0xFFFFFFFF
    finally:
        chaos.uninstall()
        for eng in engines:
            try:
                eng.shm_handler.close(unlink=True)
                eng.close()
            except Exception:  # noqa: BLE001 - cleanup best-effort
                pass
        if prev_env is None:
            os.environ.pop(EnvKey.CKPT_PERSIST_REPLICAS, None)
        else:
            os.environ[EnvKey.CKPT_PERSIST_REPLICAS] = prev_env
        if prev_journal is None:
            os.environ.pop(EnvKey.JOURNAL_DIR, None)
        else:
            os.environ[EnvKey.JOURNAL_DIR] = prev_journal
    expected = state_at(4)
    from dlrover_tpu.telemetry.audit import assert_clean

    assert_clean(journal_dir, context="sharded scenario")
    return ShardedScenarioResult(
        restored_step=restored_step,
        bad_writers=bad,
        restored_crc=restored_crc,
        expected_crc=zlib.crc32(expected.tobytes()) & 0xFFFFFFFF,
        trail=fault_trail(journal_dir),
    )


def canned_embedding_scenario(seed: int = 4242) -> dict:
    """The embedding-fabric acceptance schedule (DESIGN.md §25): a
    3-server ring persists step 4 (verified, replicas=2), then a scale
    3→4 loses the new shard server mid-migration — the first
    ``import_rows`` push lands, every later one hits a dead connection
    (``embedding_msg`` reset, enough firings to exhaust the migrate
    retries) — so the coordinator must roll the scale back zero-loss;
    a respawned destination re-runs the scale to completion. Step 8's
    save then bit-flips shard server emb-0's file on its way to disk
    (``storage_write``), and the restore must land on step 8 anyway via
    the per-shard twin rollback (emb-0's block verifies in its ring
    successor's file). ``run_embedding_scenario`` replays it.
    """
    return {
        "seed": seed,
        "faults": [
            # the new shard server dies mid-migration: the first row
            # push lands, then the wire goes dead — 3 firings cover
            # every migrate retry so phase 1 provably fails
            {"point": "embedding_msg", "action": "reset",
             "match": {"op": "import_rows"},
             "after": 1, "times": 3},
            # the newest step's primary shard rots on its way to disk
            {"point": "storage_write", "action": "bit_flip",
             "match": {"path_contains": "step-8/",
                       "path_suffix": "node_emb-0.bin"},
             "times": 1},
        ],
    }


@dataclasses.dataclass
class EmbeddingScenarioResult:
    moved: int                  # rows moved by the successful re-scale
    total_rows: int             # ring row count at the scale event
    restored_step: int | None
    restored_crc: int           # crc32 over the reassembled restored rows
    expected_crc: int           # crc32 over the pre-persist source rows
    rows_after_rollback: int    # ring rows right after the failed scale
    trail: dict

    @property
    def bit_exact(self) -> bool:
        return self.restored_crc == self.expected_crc

    @property
    def moved_frac(self) -> float:
        return self.moved / max(1, self.total_rows)

    def assert_invariants(self) -> None:
        assert self.rows_after_rollback == self.total_rows, (
            "the failed scale lost rows: "
            f"{self.rows_after_rollback} != {self.total_rows}"
        )
        assert 0 < self.moved_frac <= 1.6 / 4, (
            f"3→4 scale moved {self.moved_frac:.2f} of rows; the ring "
            "bound is ~1/N"
        )
        assert self.restored_step == 8, (
            f"restore landed on {self.restored_step}, not the newest "
            "verified step 8 (twin rollback should cover the bit flip)"
        )
        assert self.bit_exact, "restored rows are not row-exact"


def run_embedding_scenario(work_dir: str, *, seed: int = 4242,
                           dim: int = 8, rows: int = 96
                           ) -> EmbeddingScenarioResult:
    """Drive the canned embedding schedule IN PROCESS (CPU-only).

    A real multi-host fabric runs the same ``FabricShardServer``
    processes over TCP; in-process servers exercise the identical wire
    protocol (every call crosses a real socket), so the
    migration-rollback and twin-restore paths under test are
    deployment-agnostic.
    """
    import zlib

    import numpy as np

    from dlrover_tpu import chaos
    from dlrover_tpu.embedding.fabric import (
        FabricClient,
        FabricShardServer,
        start_local_fabric,
    )

    os.makedirs(work_dir, exist_ok=True)
    ckpt_dir = os.path.join(work_dir, "ckpt")
    journal_dir = os.path.join(work_dir, "journal")
    spec = canned_embedding_scenario(seed)

    prev_journal = os.environ.get(EnvKey.JOURNAL_DIR)
    os.environ[EnvKey.JOURNAL_DIR] = journal_dir
    coord = None
    servers: list = []
    client = None
    try:
        coord, servers = start_local_fabric(
            3, dim=dim, num_slots=2, seed=seed, replicas=2,
            ckpt_dir=ckpt_dir,
        )
        client = FabricClient(coordinator_addr=coord.addr, dim=dim,
                              async_apply=False, retry_window_s=20.0)
        rng = np.random.default_rng(seed)
        ids = rng.choice(1 << 20, size=rows, replace=False).astype(
            np.int64
        )
        client.lookup(ids)
        for _ in range(4):
            client.apply("adam", ids,
                         rng.standard_normal((rows, dim)).astype(
                             np.float32), lr=1e-2)
        client.persist(4)

        chaos.install({"seed": spec["seed"], "faults": spec["faults"]})
        # the destination that will die mid-migration
        doomed = FabricShardServer(dim=dim, num_slots=2,
                                   member="emb-3", seed=seed,
                                   host="127.0.0.1").start()
        members4 = {s.member: s.addr for s in servers}
        members4["emb-3"] = doomed.addr
        total = coord.total_rows()
        try:
            coord.scale(members4, migrate_retries=3)
            raise AssertionError(
                "scale survived the mid-migration kill"
            )
        except Exception:  # noqa: BLE001 - the injected failure
            pass
        # rollback left the OLD ring serving every row
        rows_after_rollback = coord.total_rows()
        # the "killed" server really dies; a respawn takes its place
        doomed.stop()
        respawn = FabricShardServer(dim=dim, num_slots=2,
                                    member="emb-3", seed=seed,
                                    host="127.0.0.1").start()
        servers.append(respawn)
        members4["emb-3"] = respawn.addr
        route = coord.scale(members4, migrate_retries=3)
        moved = int(_read_moved(journal_dir, version=route.version))
        client.refresh_route()
        for _ in range(4):
            client.apply("adam", ids,
                         rng.standard_normal((rows, dim)).astype(
                             np.float32), lr=1e-2)
        expected = client.export(with_slots=True)
        order = np.argsort(expected["keys"], kind="stable")
        expected_crc = zlib.crc32(
            expected["values"][order].tobytes()
        ) & 0xFFFFFFFF
        client.persist(8)    # emb-0's file bit-flips on the way down

        # sabotage the live tables so only a real restore can match
        for s in servers:
            if s.table is not None and len(s.table):
                snap = s.table.export(with_slots=False)
                s.table.remove(snap["keys"])
        restored = coord.restore()
        restored_step = restored["step"] if restored else None
        got = client.export(with_slots=True)
        order = np.argsort(got["keys"], kind="stable")
        restored_crc = zlib.crc32(
            got["values"][order].tobytes()
        ) & 0xFFFFFFFF
    finally:
        chaos.uninstall()
        if client is not None:
            client.close()
        if coord is not None:
            coord.stop()
        for s in servers:
            s.stop()
        if prev_journal is None:
            os.environ.pop(EnvKey.JOURNAL_DIR, None)
        else:
            os.environ[EnvKey.JOURNAL_DIR] = prev_journal
    from dlrover_tpu.telemetry.audit import assert_clean

    assert_clean(journal_dir, context="embedding scenario")
    return EmbeddingScenarioResult(
        moved=moved,
        total_rows=total,
        restored_step=restored_step,
        restored_crc=restored_crc,
        expected_crc=expected_crc,
        rows_after_rollback=rows_after_rollback,
        trail=fault_trail(journal_dir),
    )


def master_kill_trail(journal_dir: str) -> dict:
    """Canonical, replay-comparable trail of a master-kill scenario
    (DESIGN.md §26): master restarts (epoch sequence), agent epoch-fence
    reconciles, rendezvous rounds, autopilot retunes, snapshot
    rollbacks and rack sub-master failovers (§28) — occurrence-indexed
    and sorted like the chaos fault trail, so two seeded runs compare
    verbatim."""
    entries: list[list[Any]] = []
    for e in _read_journal(journal_dir):
        name = e.get("name")
        if name == "master_restore":
            entries.append(["master_restore", e.get("epoch", -1),
                            e.get("version", 0),
                            e.get("components", "")])
        elif name == "agent_reconcile":
            entries.append(["agent_reconcile", e.get("node", -1),
                            e.get("old_epoch", 0), e.get("new_epoch", 0)])
        elif name == "rdzv_round":
            entries.append(["rdzv_round", e.get("round", 0),
                            e.get("nodes", 0), bool(e.get("fast")),
                            bool(e.get("reshard"))])
        elif name == "autopilot_retune":
            entries.append(["autopilot_retune", e.get("from_plan", ""),
                            e.get("to_plan", ""), e.get("path", "")])
        elif name in ("state_rollback", "state_legacy_snapshot"):
            entries.append([name])
        elif name == "degraded_mode":
            entries.append(["degraded_mode", e.get("component", ""),
                            e.get("state", "")])
        elif name == "submaster_failover":
            entries.append(["submaster_failover", e.get("rack", ""),
                            e.get("old_epoch", 0),
                            e.get("new_epoch", 0)])
    counts: dict[str, int] = {}
    indexed: list[list[Any]] = []
    for entry in entries:
        key = json.dumps(entry)
        k = counts.get(key, 0)
        counts[key] = k + 1
        indexed.append(entry + [k])
    return {"events": sorted(indexed, key=json.dumps)}


@dataclasses.dataclass
class MasterKillScenarioResult:
    """What survived four SIGKILLs of the root master (§26
    acceptance) plus one SIGKILL of a rack sub-master (§28)."""

    epochs: list[int]              # epoch of each restarted master
    round_after_restart: int       # rendezvous round completed on M2
    commit_step: int | None        # newest verified step post-commit
    commit_writers: list[str]      # writers in the commit_w<W> manifest
    dense_writers: list[str]       # dense ledger writers (group "")
    embedding_writers: list[str]   # embedding ledger writers
    compile_cache_warm: bool       # CompileCacheGet hit after restart
    retune_events: int             # autopilot_retune journal lines
    retunes_used_final: int        # budget charged per the final state
    restart_actions: int           # "restart" actions agents received
    trail: dict
    # §28 sub-master kill leg: rack epoch before/after the SIGKILL and
    # the rendezvous round that completed THROUGH the respawned tier
    sub_epochs: list[int] = dataclasses.field(default_factory=list)
    sub_round: int = 0

    def assert_invariants(self) -> None:
        assert self.epochs == [2, 3, 4, 5], (
            f"master epochs not monotonic across restarts: {self.epochs}"
        )
        assert self.round_after_restart == 2, (
            "the mid-rendezvous restart did not continue the round "
            f"sequence (round {self.round_after_restart})"
        )
        assert self.commit_step == 4, (
            f"the in-flight step never committed (verified step "
            f"{self.commit_step})"
        )
        assert sorted(self.commit_writers) == ["0", "1"], (
            f"commit manifest incomplete: {self.commit_writers}"
        )
        assert sorted(self.dense_writers) == ["0", "1"] \
            and self.embedding_writers == ["emb-0"], (
            "restored ledger mixed the dense and embedding groups: "
            f"dense={self.dense_writers} emb={self.embedding_writers}"
        )
        assert self.compile_cache_warm, \
            "restarted master answered CompileCacheGet cold"
        assert self.retune_events == 1 and self.retunes_used_final == 1, (
            f"retune budget double-charged or phantom retune: "
            f"{self.retune_events} events, {self.retunes_used_final} used"
        )
        assert self.restart_actions == 0, (
            f"trainers were asked to restart {self.restart_actions} "
            "times during master failover"
        )
        # §28: the root mints the rack epoch above its own (5 after
        # four restarts), and the sub-master SIGKILL re-mints above the
        # predecessor — the fence the rack's agents reconcile on
        assert self.sub_epochs == [6, 7], (
            f"rack epochs not re-minted across the sub-master kill: "
            f"{self.sub_epochs}"
        )
        assert self.sub_round == 3, (
            "the round interrupted by the sub-master kill did not "
            f"complete through the respawned tier (round "
            f"{self.sub_round})"
        )


def run_master_kill_scenario(work_dir: str, *, seed: int = 4242
                             ) -> MasterKillScenarioResult:
    """SIGKILL a REAL master subprocess at three in-flight points —
    mid-rendezvous, mid-commit-wait, mid-autopilot-streak (plus once
    more post-retune to pin the budget) — and drive typed
    ``MasterClient`` agents through the §26 failover machinery: port
    re-resolve from the atomic port file, epoch-fence reconcile,
    redelivery replay, restored ack ledger/rendezvous/autopilot state.
    The kill points are state-based (the snapshot provably contains the
    in-flight mutation before the SIGKILL lands), so the trail is
    replay-identical across runs of the same seed.

    A fifth leg SIGKILLs a REAL rack sub-master (§28) mid-rendezvous-
    round: its agents re-resolve the rack's target-keyed port file,
    fence on the rack epoch the root re-mints, and the interrupted
    round completes through the respawned tier — zero trainer
    restarts, and the ``submaster_failover`` event lands in the same
    replay-comparable trail."""
    import zlib

    from dlrover_tpu.agent.master_client import MasterClient
    from dlrover_tpu.autopilot.planner import Plan
    from dlrover_tpu.checkpoint import integrity
    from dlrover_tpu.checkpoint.integrity import resolve_restore_step
    from dlrover_tpu.common.rpc import RpcClient
    from dlrover_tpu.common.storage import PosixDiskStorage

    os.makedirs(work_dir, exist_ok=True)
    state_dir = os.path.join(work_dir, "state")
    journal_dir = os.path.join(work_dir, "journal")
    ckpt_dir = os.path.join(work_dir, "ckpt")
    port_file = os.path.join(work_dir, "master.port")
    log_path = os.path.join(work_dir, "master.log")
    os.makedirs(state_dir, exist_ok=True)

    env = dict(os.environ)
    env.update({
        EnvKey.JOURNAL_DIR: journal_dir,
        EnvKey.TRACE_ID: f"mk{seed}",
        EnvKey.TRACE_SEED: f"mk:{seed}",
        # budget 1 makes "not double-charged" sharp: one retune total,
        # across however many master incarnations
        EnvKey.AUTOPILOT_MAX_RETUNES: "1",
        "PYTHONPATH": env.get("PYTHONPATH", "") + os.pathsep + REPO,
    })
    prev_env = {
        k: os.environ.get(k)
        for k in (EnvKey.MASTER_PORT_FILE, EnvKey.JOURNAL_DIR)
    }
    os.environ[EnvKey.MASTER_PORT_FILE] = port_file
    os.environ[EnvKey.JOURNAL_DIR] = journal_dir

    log = open(log_path, "ab")
    procs: list[subprocess.Popen] = []

    def spawn_master(prev_port: str) -> str:
        proc = subprocess.Popen(
            [sys.executable, "-m", "dlrover_tpu.master.job_master",
             "--job-name", "mk", "--min-nodes", "2", "--max-nodes", "2",
             "--rdzv-timeout", "60", "--state-dir", state_dir,
             "--port-file", port_file],
            env=env, cwd=REPO, stdout=log, stderr=log,
        )
        procs.append(proc)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"master exited early rc={proc.returncode}"
                )
            try:
                with open(port_file) as f:
                    text = f.read().strip()
                if text and text != prev_port:
                    return text
            except OSError:
                pass
            time.sleep(0.05)
        raise TimeoutError("master never published its port")

    def sigkill_master() -> None:
        proc = procs[-1]
        os.kill(proc.pid, 9)
        proc.wait(timeout=10)

    def read_state() -> dict:
        try:
            with open(os.path.join(state_dir, "mk.state.json")) as f:
                wrapped = json.load(f)
            return json.loads(wrapped["body"])
        except (OSError, ValueError, KeyError):
            return {}

    def wait_state(pred, what: str, timeout: float = 15.0) -> dict:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            state = read_state()
            if state and pred(state):
                return state
            time.sleep(0.05)
        raise TimeoutError(f"master snapshot never showed: {what}")

    actions: list[str] = []

    def reconnect(agent: MasterClient, timeout: float = 20.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            agent.maybe_redial()
            try:
                actions.append(agent.report_heartbeat(0))
                return
            except (ConnectionError, TimeoutError, OSError):
                time.sleep(0.1)
        raise TimeoutError("agent could not reconnect to the master")

    def trainer_push(agent: MasterClient, cum: list[float]) -> None:
        # one trainer-role snapshot whose step-histogram delta reads as
        # 1.0 s/step — 10x the armed plan's 0.1 s prediction
        cum[0] += 1.0
        cum[1] += 1
        agent.report_metrics([{
            "name": "dlrover_tpu_train_step_seconds",
            "type": "histogram", "help": "", "buckets": [],
            "samples": [{"labels": {}, "buckets": [],
                         "sum": cum[0], "count": int(cum[1])}],
        }], role="trainer")

    a0 = a1 = ra0 = ra1 = None
    try:
        port = spawn_master("")
        addr = f"127.0.0.1:{port}"

        def make_agent(nid: int) -> MasterClient:
            return MasterClient(
                addr, nid,
                transport=RpcClient(addr, retries=2, deadline_s=4.0,
                                    backoff_base_s=0.05,
                                    backoff_max_s=0.2),
            )

        a0, a1 = make_agent(0), make_agent(1)
        a0.join_rendezvous("127.0.0.1:7770", 4)
        a1.join_rendezvous("127.0.0.1:7771", 4)
        assert a0.wait_comm_world(timeout=30).round == 1
        actions.append(a0.report_heartbeat(0))
        actions.append(a1.report_heartbeat(0))
        # the artifact a restarted master must keep serving warm
        blob = (b"mkblob" * 11)[: 64]
        a0.compile_cache_put(f"n2t8/mk{seed % 100:02d}", blob,
                             {"seed": seed})

        # ---- kill 1: mid-rendezvous (a respawned node has re-joined,
        # its peer has not) -------------------------------------------
        a0.join_rendezvous("127.0.0.1:7770", 4)

        def _mid_rendezvous(s: dict) -> bool:
            # the kill must land with the FULL in-flight picture
            # durable: round 1 completed, node 0 re-joined (round
            # invalidated), and the compile-cache artifact spilled —
            # an earlier snapshot (round 0's join) also shows node 0
            # waiting and would make the trail non-deterministic
            rdzv = s.get("rendezvous", {}).get("training", {})
            return (
                int(rdzv.get("round", 0)) == 1
                and [int(w.get("node_id", -1))
                     for w in rdzv.get("waiting", ())] == [0]
                and bool(s.get("compile_cache"))
            )

        wait_state(_mid_rendezvous, "round 1 + node 0 re-joined + "
                                    "spilled compile cache")
        sigkill_master()
        spawn_master(port)
        reconnect(a1)
        a1.join_rendezvous("127.0.0.1:7771", 4)
        w0 = a0.wait_comm_world(timeout=30)
        w1 = a1.wait_comm_world(timeout=30)
        assert w0.round == w1.round, "agents disagree on the round"
        round_after_restart = w0.round
        epochs = [a0.master_epoch]
        warm = a0.compile_cache_get(f"n2t8/mk{seed % 100:02d}")
        compile_cache_warm = warm is not None and warm[0] == blob
        port = open(port_file).read().strip()

        # ---- kill 2: mid-commit-wait (one dense writer + the
        # embedding fabric have acked; the other dense writer has not) -
        sdir = os.path.join(ckpt_dir, "step-4")
        entries: dict[str, dict] = {}
        for nid in (0, 1):
            payload = bytes([seed % 256, nid]) * 64
            atomic_write_file(payload,
                              os.path.join(sdir, f"node_{nid}.bin"))
            atomic_write_file(json.dumps({"metas": {}}),
                              os.path.join(sdir,
                                           f"node_{nid}.meta.json"))
            entries[str(nid)] = {
                "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
                "bytes": len(payload), "pieces": {},
            }
        a0.report_persist_ack(4, 1, {"crc32": 1, "bytes": 8},
                              writer_id="emb-0", group="embedding")
        a1.report_persist_ack(4, 2, entries["1"])
        wait_state(
            lambda s: {
                (e["group"], w)
                for e in s.get("persist_acks", {}).get("acks", ())
                for w in e.get("shards", {})
            } >= {("embedding", "emb-0"), ("", "1")},
            "embedding + dense acks in the ledger",
        )
        sigkill_master()
        spawn_master(port)
        reconnect(a0)
        reconnect(a1)
        a0.report_persist_ack(4, 2, entries["0"])
        dense = a0.persist_status(4, 2)
        emb = a1.persist_status(4, 1, group="embedding")
        dense_writers = sorted(dense.shards)
        embedding_writers = sorted(emb.shards)
        commit_step = None
        commit_writers: list[str] = []
        if dense.complete:
            # rank-0's commit wait completes against the RESTORED
            # ledger: the terminal manifest lands, the tracker moves
            storage = PosixDiskStorage()
            integrity.write_commit(storage, sdir, 4, 2,
                                   dict(dense.shards))
            storage.write(json.dumps({"step": 4, "num_shards": 2}),
                          os.path.join(ckpt_dir, "latest"))
            got = resolve_restore_step(storage, ckpt_dir)
            if got is not None:
                commit_step = got[0]
            with open(os.path.join(sdir, "commit_w2")) as f:
                commit_writers = sorted(
                    json.load(f).get("shards", {}))
        epochs.append(a0.master_epoch)
        port = open(port_file).read().strip()

        # ---- kill 3: mid-autopilot-streak (armed plan + a building
        # contradiction streak, retune not yet fired) ------------------
        plan = Plan(name="mk-a", schedule="spmd",
                    mesh_axes={"data": 1}, pred_step_s=0.1,
                    source="history", fingerprint="mk-a", n_devices=1)
        alt = Plan(name="mk-b", schedule="spmd",
                   mesh_axes={"data": 1}, pred_step_s=0.1,
                   source="history", fingerprint="mk-b", n_devices=1,
                   rank=1)
        a0.report_autopilot_plan(plan.to_json(), [alt.to_json()],
                                 step_batch=8)
        cum = [0.0, 0.0]
        for _ in range(4):      # streak 2 of the 3 needed: mid-flight
            trainer_push(a0, cum)
        wait_state(lambda s: s.get("autopilot", {}).get("plan"),
                   "armed autopilot plan")
        sigkill_master()
        spawn_master(port)
        reconnect(a0)
        for _ in range(5):      # re-earn the contradiction: ONE retune
            trainer_push(a0, cum)
        cfg = a0.get_paral_config()
        assert cfg.autopilot_plan, "retune never reached paral config"
        for _ in range(4):      # budget spent: must NOT retune again
            trainer_push(a0, cum)
        state = wait_state(
            lambda s: s.get("autopilot", {}).get("retunes_used", 0) >= 1,
            "charged retune budget",
        )
        epochs.append(a0.master_epoch)
        port = open(port_file).read().strip()

        # ---- kill 4: post-retune — the restored budget must read as
        # SPENT (no phantom second retune) -----------------------------
        sigkill_master()
        spawn_master(port)
        reconnect(a0)
        for _ in range(5):
            trainer_push(a0, cum)
        state = wait_state(
            lambda s: s.get("autopilot", {}).get("retunes_used", 0) >= 1,
            "retune budget restored as spent",
        )
        retunes_used_final = int(
            state.get("autopilot", {}).get("retunes_used", 0))
        epochs.append(a0.master_epoch)

        # ---- kill 5 (§28): SIGKILL the rack SUB-MASTER mid-
        # rendezvous-round. The rack tier's own failover: agents
        # re-resolve the rack's target-keyed port file, fence on the
        # rack epoch the root re-mints, and the interrupted round
        # completes — with zero trainer restarts --------------------
        rack_port_file = os.path.join(work_dir, "rack.port")
        port = open(port_file).read().strip()
        root_addr = f"127.0.0.1:{port}"
        # the sub-master's upstream redial resolves the ROOT's port
        # file; the parent set it in os.environ after ``env`` was taken
        sub_env = dict(env)
        sub_env[EnvKey.MASTER_PORT_FILE] = port_file

        def spawn_submaster(prev_port: str) -> str:
            proc = subprocess.Popen(
                [sys.executable, "-m", "dlrover_tpu.master.submaster",
                 "--rack-id", "rackA", "--master-addr", root_addr,
                 "--port-file", rack_port_file,
                 "--flush-interval", "0.1"],
                env=sub_env, cwd=REPO, stdout=log, stderr=log,
            )
            procs.append(proc)
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"sub-master exited early rc={proc.returncode}"
                    )
                try:
                    with open(rack_port_file) as f:
                        text = f.read().strip()
                    if text and text != prev_port:
                        return text
                except OSError:
                    pass
                time.sleep(0.05)
            raise TimeoutError("sub-master never published its port")

        rack_port = spawn_submaster("")

        def make_rack_agent(nid: int) -> MasterClient:
            rack_addr = f"127.0.0.1:{rack_port}"
            return MasterClient(
                rack_addr, nid,
                transport=RpcClient(rack_addr, retries=2,
                                    deadline_s=4.0,
                                    backoff_base_s=0.05,
                                    backoff_max_s=0.2),
                port_file=rack_port_file,
                fallback_port_file=port_file,
            )

        ra0, ra1 = make_rack_agent(0), make_rack_agent(1)
        actions.append(ra0.report_heartbeat(0))
        actions.append(ra1.report_heartbeat(0))
        sub_epochs = [ra0.master_epoch]
        # node 0 re-joins THROUGH the rack: buffered at the sub-master
        # and pushed upstream as a RackJoinRequest batch at its flush
        ra0.join_rendezvous("127.0.0.1:7770", 4)

        def _rack_join_pushed(s: dict) -> bool:
            # the kill must land mid-round with the rack's join durable
            # at the ROOT (round 2 invalidated, node 0 waiting): the
            # in-flight picture the respawned tier completes from
            rdzv = s.get("rendezvous", {}).get("training", {})
            return (
                int(rdzv.get("round", 0)) == 2
                and [int(w.get("node_id", -1))
                     for w in rdzv.get("waiting", ())] == [0]
                and bool(s.get("racks", {}).get("epochs"))
            )

        wait_state(_rack_join_pushed,
                   "rack join pushed upstream mid-round")
        sub_proc = procs[-1]
        os.kill(sub_proc.pid, 9)
        sub_proc.wait(timeout=10)
        rack_port = spawn_submaster(rack_port)
        reconnect(ra0)
        reconnect(ra1)
        # the respawned incarnation lost its buffered join floors:
        # re-join (idempotent at the root — newest join wins) so the
        # sub serves these agents the NEW round, never a stale mirror
        ra0.join_rendezvous("127.0.0.1:7770", 4)
        ra1.join_rendezvous("127.0.0.1:7771", 4)
        rw0 = ra0.wait_comm_world(timeout=30)
        rw1 = ra1.wait_comm_world(timeout=30)
        assert rw0.round == rw1.round, \
            "rack agents disagree on the post-failover round"
        sub_round = rw0.round
        actions.append(ra0.report_heartbeat(0))
        actions.append(ra1.report_heartbeat(0))
        sub_epochs.append(ra0.master_epoch)
    finally:
        for proc in procs:
            try:
                proc.kill()
                proc.wait(timeout=5)
            except (ProcessLookupError, subprocess.TimeoutExpired):
                pass
        for agent in (a0, a1, ra0, ra1):
            if agent is not None:
                agent.close()
        log.close()
        for key, value in prev_env.items():
            if value is None:
                os.environ.pop(key, None)
            else:
                os.environ[key] = value

    retune_events = sum(
        1 for e in _read_journal(journal_dir)
        if e.get("name") == "autopilot_retune"
    )
    from dlrover_tpu.telemetry.audit import assert_clean

    assert_clean(journal_dir, context="master-kill scenario")
    return MasterKillScenarioResult(
        epochs=epochs,
        round_after_restart=round_after_restart,
        commit_step=commit_step,
        commit_writers=commit_writers,
        dense_writers=dense_writers,
        embedding_writers=embedding_writers,
        compile_cache_warm=compile_cache_warm,
        retune_events=retune_events,
        retunes_used_final=retunes_used_final,
        restart_actions=sum(1 for a in actions if a == "restart"),
        trail=master_kill_trail(journal_dir),
        sub_epochs=sub_epochs,
        sub_round=sub_round,
    )


def _read_moved(journal_dir: str, version: int) -> int:
    """Moved-row count of the ``embedding_scale`` event that committed
    ``version`` (the journal is the scale's evidence of record)."""
    for e in _read_journal(journal_dir):
        if e.get("name") == "embedding_scale" and e.get("ok") \
                and int(e.get("version", -1)) == version:
            return int(e.get("moved", -1))
    return -1


def canned_scenario(seed: int = 1234, *, kill_step: int = 7,
                    save_interval: int = 6, max_steps: int = 14,
                    resume_steps: int = 20) -> Scenario:
    """The acceptance schedule: trainer SIGKILLed mid-save (an injected
    slow fsync stretches the step-``save_interval`` persist so the kill
    provably lands inside it), the newest shard bit-flipped on its way
    to disk, and the master RPC flaking on the post-kill re-join. Leg 2
    restores from storage in a fresh process tree and must roll back to
    the newest verified step.
    """
    leg1 = JobLeg(
        name="train_kill_mid_save",
        max_steps=max_steps,
        faults=[
            {"point": "storage_write", "action": "slow_fsync",
             "args": {"s": 2.0},
             "match": {"path_contains": f"step-{save_interval}/",
                       "path_suffix": ".bin"},
             "times": 1},
            {"point": "agent_kill_trainer", "action": "kill",
             "args": {"sig": 9},
             "match": {"step_gte": kill_step}, "times": 1},
            {"point": "rpc_call", "action": "drop",
             "match": {"msg": "JoinRendezvousRequest"},
             "after": 1, "times": 1},
            {"point": "storage_write", "action": "bit_flip",
             "match": {"path_contains": f"step-{max_steps}/",
                       "path_suffix": ".bin"},
             "times": 1},
        ],
        train_args=["--ckpt-interval", str(save_interval),
                    "--mem-ckpt-interval", "2", "--step-delay", "0.15"],
    )
    leg2 = JobLeg(
        name="restore_verify_rollback",
        max_steps=resume_steps,
        faults=[],
        train_args=["--ckpt-interval", str(save_interval),
                    "--mem-ckpt-interval", "2"],
    )
    return Scenario(name="kill_flip_flake", seed=seed, legs=[leg1, leg2])
