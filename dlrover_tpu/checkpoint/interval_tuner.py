"""Young–Daly adaptive snapshot cadence.

The optimal interval between checkpoints that minimizes expected lost
work is the Young/Daly first-order optimum ``T* = sqrt(2 · C · MTBF)``
(C = cost of one checkpoint, MTBF = mean time between failures). A
fixed cadence is tuned for exactly one failure rate: at DLRover's
stressed bench rates (10+ failures/hr) a fixed interval of ~90 steps
redoes ~40% more steps per failure than the optimum, and at calm rates
it pays superfluous snapshot overhead.

``IntervalTuner`` closes the loop from telemetry the system already
records: the master feeds it failure reports (MTBF), the trainer-pushed
``dlrover_tpu_ckpt_snapshot_seconds`` histogram (C) and
``dlrover_tpu_train_step_seconds`` (to convert T* from seconds to the
step units trainers snapshot on). The recommendation is clamped to
``[min_steps, max_steps]``, moves at most ``max_move_factor``× per
retune, and is hysteretic (ignores moves smaller than ``hysteresis``
of the current value) so the cadence drifts deliberately instead of
chasing noise. Every applied retune journals a
``snapshot_interval_retune`` event carrying its full evidence.

Wiring: the master servicer owns one tuner when
``DLROVER_TPU_SNAPSHOT_INTERVAL=auto`` and pushes applied retunes to
trainers through the existing paral-config channel
(``ParalConfig.snapshot_interval``; agent mirrors the file, trainer
hot-reloads — no restart, the cadence is not compile-baked).
"""

from __future__ import annotations

import math
import threading
import time
from collections import deque

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.journal import (
    current_trace_id,
    format_ctx,
    get_journal,
)
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_interval_gauge = registry().gauge(
    "dlrover_tpu_snapshot_interval_steps",
    "current Young-Daly-tuned shm snapshot interval (steps); 0 until "
    "the first retune",
)
_retunes_total = registry().counter(
    "dlrover_tpu_snapshot_interval_retunes_total",
    "applied snapshot-interval retunes",
)

STEP_METRIC = "dlrover_tpu_train_step_seconds"
SNAPSHOT_METRIC = "dlrover_tpu_ckpt_snapshot_seconds"


def _histogram_mean(samples: list, name: str) -> float | None:
    """Mean of a histogram in a pushed registry snapshot (wire shape of
    ``MetricsRegistry.snapshot()``), or None when absent/empty."""
    for metric in samples:
        if not isinstance(metric, dict) or metric.get("name") != name:
            continue
        total = 0.0
        count = 0
        for sample in metric.get("samples", ()):
            total += float(sample.get("sum", 0.0))
            count += int(sample.get("count", 0))
        if count > 0:
            return total / count
        return None
    return None


class IntervalTuner:
    """Pure state machine: observations in, clamped/hysteretic interval
    out. Thread-safe; a fake ``clock`` makes it unit-testable."""

    def __init__(
        self,
        initial_steps: int = 0,
        min_steps: int = 1,
        max_steps: int = 1000,
        hysteresis: float = 0.25,
        max_move_factor: float = 2.0,
        min_failures: int = 2,
        window_s: float = 3600.0,
        ewma: float = 0.3,
        clock=time.monotonic,
    ):
        self.min_steps = max(1, min_steps)
        self.max_steps = max(self.min_steps, max_steps)
        self.hysteresis = hysteresis
        self.max_move_factor = max(1.0, max_move_factor)
        self.min_failures = max(1, min_failures)
        self.window_s = window_s
        self._ewma = ewma
        self._clock = clock
        self._lock = threading.Lock()
        self._failures: deque[float] = deque(maxlen=256)
        self._snap_cost_s: float | None = None
        self._step_s: float | None = None
        self._current = int(initial_steps)
        self._retunes = 0
        # span context (§27) of the most recent retune verdict
        self.last_retune_sctx = ""

    # -------------------------------------------------------- observations

    def observe_failure(self, t: float | None = None) -> None:
        with self._lock:
            self._failures.append(self._clock() if t is None else t)

    def observe_snapshot_cost(self, cost_s: float) -> None:
        if cost_s <= 0:
            return
        with self._lock:
            self._snap_cost_s = self._blend(self._snap_cost_s, cost_s)

    def observe_step_time(self, step_s: float) -> None:
        if step_s <= 0:
            return
        with self._lock:
            self._step_s = self._blend(self._step_s, step_s)

    def observe_metrics_snapshot(self, samples: list) -> None:
        """Convenience feed from a trainer's pushed registry snapshot."""
        step = _histogram_mean(samples, STEP_METRIC)
        if step is not None:
            self.observe_step_time(step)
        snap = _histogram_mean(samples, SNAPSHOT_METRIC)
        if snap is not None:
            self.observe_snapshot_cost(snap)

    def _blend(self, old: float | None, new: float) -> float:
        return new if old is None else (1 - self._ewma) * old \
            + self._ewma * new

    # -------------------------------------------- crash-failover state (§26)

    def export_state(self) -> dict:
        """MTBF window + blended costs for the master snapshot. Failure
        times are exported as AGES (now - t): the clock is monotonic
        and resets across a process restart, so absolute values would
        be meaningless in the restoring process."""
        with self._lock:
            now = self._clock()
            return {
                "failure_ages": [round(now - t, 3)
                                 for t in self._failures],
                "snap_cost_s": self._snap_cost_s,
                "step_s": self._step_s,
                "current": self._current,
                "retunes": self._retunes,
            }

    def restore_state(self, state: dict) -> None:
        with self._lock:
            now = self._clock()
            ages = sorted(
                (float(a) for a in state.get("failure_ages", ())),
                reverse=True,
            )
            self._failures.clear()
            self._failures.extend(now - a for a in ages)
            if state.get("snap_cost_s") is not None:
                self._snap_cost_s = float(state["snap_cost_s"])
            if state.get("step_s") is not None:
                self._step_s = float(state["step_s"])
            self._current = int(state.get("current", self._current))
            self._retunes = int(state.get("retunes", self._retunes))

    # ------------------------------------------------------------- tuning

    @property
    def current_steps(self) -> int:
        with self._lock:
            return self._current

    def mtbf_s(self, now: float | None = None) -> float | None:
        """Windowed MTBF estimate; None below ``min_failures``."""
        with self._lock:
            return self._mtbf_locked(self._clock() if now is None else now)

    def _mtbf_locked(self, now: float) -> float | None:
        while self._failures and now - self._failures[0] > self.window_s:
            self._failures.popleft()
        n = len(self._failures)
        if n < self.min_failures:
            return None
        # n failures over the span since the oldest one — the span is
        # open-ended at `now` so a quiet period after the last failure
        # properly stretches the estimate
        span = max(now - self._failures[0], 1e-6)
        return span / n

    def recommend(self, now: float | None = None) -> int | None:
        """Unclamped-by-current Young-Daly recommendation in steps, or
        None while any of (MTBF, snapshot cost, step time) is unknown."""
        now = self._clock() if now is None else now
        with self._lock:
            mtbf = self._mtbf_locked(now)
            if mtbf is None or not self._snap_cost_s or not self._step_s:
                return None
            t_opt_s = math.sqrt(2.0 * self._snap_cost_s * mtbf)
            steps = int(round(t_opt_s / self._step_s))
            return max(self.min_steps, min(self.max_steps, steps))

    def maybe_retune(self, now: float | None = None) -> int | None:
        """Apply hysteresis + move clamping; returns the NEW interval
        when it changed (journaled with evidence), else None."""
        now = self._clock() if now is None else now
        rec = self.recommend(now)
        if rec is None:
            return None
        with self._lock:
            current = self._current
            if current > 0:
                if abs(rec - current) < self.hysteresis * current:
                    return None
                # move slowly: one retune can at most double/halve
                lo = max(self.min_steps,
                         int(math.floor(current / self.max_move_factor)))
                hi = min(self.max_steps,
                         int(math.ceil(current * self.max_move_factor)))
                rec = max(lo, min(hi, rec))
                if rec == current:
                    return None
            self._current = rec
            self._retunes += 1
            mtbf = self._mtbf_locked(now)
            evidence = {
                "old_steps": current,
                "new_steps": rec,
                "mtbf_s": round(mtbf, 3) if mtbf else None,
                "snapshot_cost_s": round(self._snap_cost_s, 5),
                "step_s": round(self._step_s, 5),
                "failures_in_window": len(self._failures),
            }
        _interval_gauge.set(rec)
        _retunes_total.inc()
        verdict_span = get_journal().emit("snapshot_interval_retune",
                                          **evidence)
        # span context (§27) of this verdict: the servicer stamps it on
        # the ParalConfig push so the retune's application traces back
        self.last_retune_sctx = format_ctx(current_trace_id(),
                                           verdict_span)
        logger.info("snapshot interval retuned: %s", evidence)
        return rec
