"""Sharded flash checkpoint with reshard-on-load.

Reference analog: the FSDP DCP engine
(dlrover/trainer/torch/flash_checkpoint/fsdp_engine.py:158,224
SharedMemoryWriter/Reader implementing torch DCP storage over shm) and
ATorch's flat-param reshard-on-load (atorch/atorch/utils/fsdp_save_util.py:523
ShardTensorUtil). TPU-native design: every node snapshots only the array
shards it *addresses* (``jax.Array.addressable_shards``), each tagged with
its global index; restore rebuilds global arrays on ANY target mesh with
``jax.make_array_from_callback``, assembling each device's slice from
whichever saved pieces cover it. A checkpoint written on mesh A restores
onto mesh B — the elastic-membership-change case XLA's static world makes
mandatory.

Commit protocol: every node's agent writes ``node_<id>.bin/.meta.json`` +
``done_<id>``; rank-0's agent waits for ``num_shards`` done markers before
moving the ``latest`` tracker (agent/ckpt_saver.py:_maybe_commit).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Sequence

import numpy as np

from dlrover_tpu.common import envspec
from dlrover_tpu.common.constants import EnvKey
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry
from dlrover_tpu.checkpoint.engine import CheckpointEngine
from dlrover_tpu.checkpoint.shm_handler import _leaf_paths

logger = get_logger(__name__)

PIECE_SEP = "::piece"

_restore_parallel_seconds = registry().histogram(
    "dlrover_tpu_ckpt_restore_parallel_seconds",
    "per-host sharded storage restore duration (parallel piece reads "
    "+ assembly) — flat in host count by design",
)


def persist_replicas() -> int:
    """How many DP replica copies of each shard are persisted to
    storage. 1 = exactly-one-writer dedup (smallest checkpoint);
    2 = primary + twin, the redundancy the per-shard rollback needs."""
    return max(1, envspec.get_int(EnvKey.CKPT_PERSIST_REPLICAS))


class CoverageError(RuntimeError):
    """The available pieces do not cover a requested slice."""


def _norm_index(index: Sequence[slice], shape: Sequence[int]
                ) -> list[list[int]]:
    """Normalize a tuple of slices to [[start, stop], ...] (step 1 only)."""
    out = []
    for sl, dim in zip(index, shape):
        start, stop, step = sl.indices(dim)
        if step != 1:
            raise ValueError(f"strided shard index {sl} unsupported")
        out.append([start, stop])
    return out


class PieceSource:
    """One saved shard of one leaf + how to read its bytes."""

    def __init__(self, path: str, global_shape: tuple[int, ...],
                 dtype: np.dtype, index: list[list[int]],
                 read: Callable[[], np.ndarray], replica: int = 0):
        self.path = path
        self.global_shape = global_shape
        self.dtype = dtype
        self.index = index  # [[start, stop], ...] in the global array
        self.replica = replica  # DP replica rank of the saved copy
        self._read = read

    def data(self) -> np.ndarray:
        return self._read()


def assemble(target_index: list[list[int]], dtype: np.dtype,
             pieces: list[PieceSource]) -> np.ndarray:
    """Fill the target slice from overlapping pieces; error on gaps."""
    shape = tuple(stop - start for start, stop in target_index)
    out = np.empty(shape, dtype)
    filled = 0
    for p in pieces:
        dst, src = [], []
        empty = False
        for (t0, t1), (p0, p1) in zip(target_index, p.index):
            lo, hi = max(t0, p0), min(t1, p1)
            if lo >= hi:
                empty = True
                break
            dst.append(slice(lo - t0, hi - t0))
            src.append(slice(lo - p0, hi - p0))
        if empty:
            continue
        block = p.data()[tuple(src)]
        out[tuple(dst)] = block
        filled += block.size
    if filled < int(np.prod(shape)):
        raise CoverageError(
            f"pieces cover {filled} of {int(np.prod(shape))} elements for "
            f"target {target_index}"
        )
    return out


def _registry_entries(metas: dict, index_map: dict,
                      view: Callable[[dict], np.ndarray]
                      ) -> dict[str, list[PieceSource]]:
    registry: dict[str, list[PieceSource]] = {}
    for key, entry in index_map.items():
        info = metas.get(key)
        if info is None:
            continue
        registry.setdefault(entry["path"], []).append(
            PieceSource(
                path=entry["path"],
                global_shape=tuple(entry["global_shape"]),
                dtype=np.dtype(entry["dtype"]),
                index=[list(p) for p in entry["index"]],
                read=lambda info=info: view(info),
                replica=int(entry.get("replica", 0)),
            )
        )
    return registry


def storage_piece_registry(
    storage, ckpt_dir: str, step: int, num_shards: int,
    bad_pieces: dict[str, set | None] | None = None,
) -> dict[str, list[PieceSource]] | None:
    """Piece registry over the COMMITTED world's files for ``step``.

    Only node files named by a ``done_<id>_w<num_shards>`` marker are
    read: a step directory may also hold stale files from a previous
    incarnation with a different world size (same step re-reached after
    an elastic reshape), and blending those would restore divergent
    weights. ``bad_pieces`` (from the integrity RestorePlan) excludes
    shard files — or individual pieces — that failed verification, so
    their replica twins serve those slices instead.

    The per-node metadata reads run CONCURRENTLY (each inside a
    ``ckpt_restore_shard`` span): against an object store these are
    round trips, and a restore's setup must stay flat as the writer
    count grows. Piece BYTES stay lazy — memmap windows locally,
    ``read_range`` slices remotely — so a topology-changing restore
    pulls only the byte ranges the local mesh actually needs.
    """
    from concurrent.futures import ThreadPoolExecutor

    from dlrover_tpu.agent.ckpt_saver import step_dir
    from dlrover_tpu.common.storage import PosixDiskStorage

    sdir = step_dir(ckpt_dir, step)
    if not storage.exists(sdir):
        return None
    suffix = f"_w{num_shards}"
    node_ids = [
        f[len("done_"):-len(suffix)]
        for f in storage.listdir(sdir)
        if f.startswith("done_") and f.endswith(suffix)
    ]
    bad_pieces = bad_pieces or {}
    local = isinstance(storage, PosixDiskStorage)

    def _node_part(nid: str) -> dict[str, list[PieceSource]]:
        bad = bad_pieces.get(nid, set())
        if bad is None:
            return {}  # whole shard file failed; twins cover it
        meta_path = os.path.join(sdir, f"node_{nid}.meta.json")
        if not storage.exists(meta_path):
            return {}
        with get_journal().span("ckpt_restore_shard", step=step,
                                writer=str(nid)):
            header = json.loads(storage.read_text(meta_path))
            index_map = {
                k: v
                for k, v in (header.get("sharded_index") or {}).items()
                if k not in bad
            }
            if not index_map:
                return {}
            bin_path = os.path.join(sdir, f"node_{nid}.bin")
            if local:
                # memmap keeps restore lazy: only bytes a target slice
                # needs are paged in
                blob = np.memmap(bin_path, dtype=np.uint8, mode="r")

                def view(info, blob=blob):
                    return np.ndarray(
                        tuple(info["shape"]),
                        dtype=np.dtype(info["dtype"]),
                        buffer=blob, offset=info["offset"],
                    )
            else:
                # ranged reads: one GET per needed piece, never a
                # whole-file download
                def view(info, bin_path=bin_path):
                    raw = storage.read_range(
                        bin_path, int(info["offset"]),
                        int(info["nbytes"]),
                    )
                    return np.frombuffer(
                        raw, dtype=np.dtype(info["dtype"])
                    ).reshape(tuple(info["shape"]))
            return _registry_entries(header["metas"], index_map, view)

    registry: dict[str, list[PieceSource]] = {}
    ordered = sorted(nid for nid in node_ids)
    if len(ordered) > 1:
        with ThreadPoolExecutor(max_workers=min(8, len(ordered))) as pool:
            parts = list(pool.map(_node_part, ordered))
    else:
        parts = [_node_part(nid) for nid in ordered]
    for part in parts:
        for path, lst in part.items():
            registry.setdefault(path, []).extend(lst)
    # primary replicas first: overlapping twin pieces hold the same
    # bytes, but deterministic order keeps assembly stable
    for lst in registry.values():
        lst.sort(key=lambda p: p.replica)
    return registry or None


class ShardedCheckpointEngine(CheckpointEngine):
    """Per-node shard snapshots + any-mesh restore.

    ``owned`` decides which addressable shards this node snapshots. The
    default keeps, for every distinct shard index, this NODE's
    lowest-replica copy — i.e. replicas are deduplicated within a node
    but every node retains full coverage of the data its own devices
    hold. A global replica_id==0 policy would be smaller (exactly-once
    across the job) but leaves rank>0 nodes unable to restore
    REPLICATED leaves (the step counter, norms — everything, under pure
    dp) from their local shm: their restore would always fall through
    to storage, defeating restart-in-place AND buddy replication. The
    reference's per-rank shm snapshots make the same size-for-locality
    trade (ckpt_saver.py: each rank snapshots its own state view).
    """

    # async supersede semantics would break cross-node step agreement
    supports_async_snapshot = False

    def __init__(self, *args,
                 owned: Callable[[Any], bool] | None = None, **kwargs):
        kwargs.setdefault("replicated", False)
        super().__init__(*args, **kwargs)
        self._owned = owned  # None -> per-node replica dedup (default)

    @staticmethod
    def _node_owned_shards(leaf) -> list:
        """This node's lowest-replica copy of each distinct shard index."""
        best: dict = {}
        for s in leaf.addressable_shards:
            key = tuple(
                tuple(pair) for pair in _norm_index(s.index, leaf.shape)
            )
            cur = best.get(key)
            if cur is None or s.replica_id < cur.replica_id:
                best[key] = s
        return list(best.values())

    # ------------------------------------------------------------------ save

    def _prepare_state(self, state: Any) -> tuple[Any, dict]:
        """Split the pytree into this node's addressable pieces.

        Every piece carries its global index, its REPLICA rank, and a
        ``persist`` flag: the shm snapshot keeps full local coverage
        (restart-in-place, buddy replication), but the agent persister
        writes only flagged pieces — ``replica_id <
        DLROVER_TPU_CKPT_PERSIST_REPLICAS`` — so exactly one DP replica
        (or one primary + one twin at replicas=2) writes each shard to
        storage, with zero cross-host coordination: the writer
        assignment is a pure function of the sharding.
        """
        import jax

        keep = persist_replicas()
        pieces: dict[str, Any] = {}
        index_map: dict[str, dict] = {}
        for name, leaf in _leaf_paths(state):
            if isinstance(leaf, jax.Array):
                if self._owned is not None:
                    shards = [
                        s for s in leaf.addressable_shards
                        if self._owned(s)
                    ]
                else:
                    shards = self._node_owned_shards(leaf)
                for i, s in enumerate(shards):
                    key = f"{name}{PIECE_SEP}{i}"
                    pieces[key] = s.data
                    index_map[key] = {
                        "path": name,
                        "global_shape": list(leaf.shape),
                        "dtype": str(np.dtype(leaf.dtype)),
                        "index": _norm_index(s.index, leaf.shape),
                        "replica": int(s.replica_id),
                        "persist": bool(s.replica_id < keep),
                    }
            else:
                # host leaves are replicated on every node: the node
                # RANK is the replica rank, so rank 0 (and rank 1 at
                # replicas=2) persists and the rest dedup away
                arr = np.asarray(leaf)
                pieces[name] = arr
                index_map[name] = {
                    "path": name,
                    "global_shape": list(arr.shape),
                    "dtype": str(arr.dtype),
                    "index": _norm_index(
                        tuple(slice(None) for _ in arr.shape), arr.shape
                    ),
                    "replica": int(self.node_rank),
                    "persist": bool(self.node_rank < keep),
                }
        return pieces, {"sharded_index": index_map}

    def snapshot_pieces(self, step: int, pieces: dict[str, np.ndarray],
                        index_map: dict[str, dict]) -> None:
        """Install an explicit piece set as this node's shm snapshot
        (bench / chaos-scenario hosts simulated in one process, remote
        producers). ``index_map`` entries need path/global_shape/dtype/
        index; replica defaults to 0 (persisted)."""
        for key, entry in index_map.items():
            entry.setdefault("replica", 0)
            entry.setdefault("persist",
                             entry["replica"] < persist_replicas())
            if key not in pieces:
                raise KeyError(f"index_map key {key!r} has no piece")
        self.shm_handler.save_state_dict(
            step, dict(pieces),
            extra_meta={**self._extra_meta(),
                        "sharded_index": dict(index_map)},
        )

    # ------------------------------------------------------------------ load

    def _shm_pieces(self) -> tuple[int, dict[str, list[PieceSource]]] | None:
        """Zero-copy piece registry from this node's shm snapshot."""
        raw = self.shm_handler.read_raw()
        if raw is None:
            return None
        header, buf = raw
        index_map = header.get("sharded_index")
        if not index_map:
            return None
        return int(header["step"]), self._registry_from(
            header["metas"], index_map,
            lambda info: np.ndarray(
                tuple(info["shape"]), dtype=np.dtype(info["dtype"]),
                buffer=buf, offset=info["offset"],
            ),
        )

    def _storage_pieces(self, step: int, num_shards: int,
                        bad_pieces: dict[str, set | None] | None = None,
                        ) -> dict[str, list[PieceSource]] | None:
        return storage_piece_registry(
            self.storage, self.ckpt_dir, step, num_shards,
            bad_pieces=bad_pieces,
        )

    @staticmethod
    def _registry_from(metas: dict, index_map: dict,
                       view: Callable[[dict], np.ndarray]
                       ) -> dict[str, list[PieceSource]]:
        return _registry_entries(metas, index_map, view)

    def load_sharded(self, template: Any, shardings: Any
                     ) -> tuple[int, Any] | None:
        import time as _time

        from dlrover_tpu.checkpoint.engine import _record_restore
        from dlrover_tpu.parallel.compile_cache import launder

        start = _time.monotonic()
        loaded = self._load_sharded_impl(template, shardings)
        if loaded is not None:
            # every branch below builds the tree host-side (arena views
            # / storage pieces through device_put or
            # make_array_from_callback): re-stage before ANY cached AOT
            # executable can see it, or donation corrupts it in place
            # on the CPU backend (DESIGN.md §17.4)
            loaded = (loaded[0], launder(loaded[1]))
            _record_restore("sharded", start, loaded[0])
        return loaded

    def _load_sharded_impl(self, template: Any, shardings: Any
                           ) -> tuple[int, Any] | None:
        """Restore onto ``shardings`` (any mesh): (step, state) or None.

        ``template`` supplies structure/shape/dtype (concrete arrays or
        ``jax.eval_shape`` structs); ``shardings`` is a matching tree of
        target ``Sharding``s. shm fast path first (restart-in-place, same
        mesh) — only when every process's snapshot is at the SAME step
        (nodes killed mid-step may be one snapshot apart; mixing steps
        would silently blend divergent shards) — else the committed
        storage step, which the tracker guarantees is shard-complete.
        """
        snap = self._shm_pieces()
        # every process joins the step-agreement collective (a process
        # with nothing local reports -1), or the others deadlock in it;
        # the gathered vector is kept so every later branch decision is
        # computed identically on all processes (collective-uniform)
        steps = self._allgather_steps(snap[0] if snap else -1)
        use_shm = bool((steps >= 0).all() and (steps == steps[0]).all())
        built = None
        if use_shm:
            step, registry = snap
            try:
                built = self._build(template, shardings, registry)
            except CoverageError:
                logger.info(
                    "local shm pieces don't cover the target shardings "
                    "(mesh changed); assembling from storage"
                )
            # the shm-vs-storage choice must be collective: if ANY
            # process's local pieces can't cover its new shards, all
            # processes fall back to the committed storage step together
            # — half restoring step N from shm and half step M from
            # storage is silent divergence
            if not self._all_processes_agree(built is not None):
                built = None
            if built is not None:
                return step, built
        elif (steps >= 0).any():
            rolled = self._consensus_rollback(
                template, shardings, snap, steps
            )
            if rolled is not None:
                return rolled
            logger.info(
                "shm snapshot steps disagree across nodes and the oldest "
                "holder can't serve the full state; restoring the "
                "committed storage step instead"
            )
        import time as _time

        from dlrover_tpu.checkpoint.integrity import resolve_restore_plan

        # newest VERIFIED restore plan (crc manifest + COMMIT marker +
        # quorum over replica twins): every process resolves
        # independently but deterministically — same storage, same walk
        # — so the choice stays collective-uniform
        plan = resolve_restore_plan(self.storage, self.ckpt_dir)
        if plan is None:
            return None
        registry = self._storage_pieces(
            plan.step, plan.num_shards, bad_pieces=plan.bad_pieces
        )
        if registry is None:
            return None
        t0 = _time.monotonic()
        built = self._build(template, shardings, registry)
        _restore_parallel_seconds.observe(_time.monotonic() - t0)
        return plan.step, built

    @staticmethod
    def _allgather_steps(step: int) -> np.ndarray:
        """Every process's snapshot step (-1 = none), identical on all."""
        import jax

        if jax.process_count() == 1:
            return np.asarray([step], np.int64)
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(
            np.asarray(step, np.int64)
        )).reshape(-1)

    def _consensus_rollback(self, template: Any, shardings: Any,
                            snap, steps: np.ndarray
                            ) -> tuple[int, Any] | None:
        """Steps diverge across processes: roll every node back to the
        OLDEST snapshot if its holder can serve the full state.

        This is the zero-storage-read preemption recovery: the node that
        died was restored from its buddy one or two snapshots behind the
        survivors (the buddy copy lags by the replication cadence), and
        the survivors cannot rewind their own shm. When the oldest
        holder's local pieces cover every leaf in full — always true for
        replicated/dp layouts, where each node snapshots complete
        arrays — it broadcasts that state and the whole job resumes from
        the common step; at-least-once data sharding re-runs the few
        rolled-back steps. Truly sharded layouts return None (storage is
        the only consistent source there).
        """
        import jax

        valid = steps[steps >= 0]
        if valid.size == 0 or jax.process_count() == 1:
            return None
        consensus = int(valid.min())
        src = int(np.nonzero(steps == consensus)[0][0])
        i_am_src = jax.process_index() == src
        full = None
        if i_am_src and snap is not None:
            try:
                full = self._full_host_state(template, snap[1])
            except (CoverageError, ValueError) as e:
                logger.info("consensus rollback unavailable: %s", e)
        from jax.experimental import multihost_utils

        flags = np.asarray(multihost_utils.process_allgather(
            np.asarray(1 if full is not None else 0, np.int64)
        )).reshape(-1)
        if not flags[src]:
            return None
        if full is None:
            full = jax.tree.map(
                lambda l: np.zeros(tuple(l.shape), l.dtype), template
            )
        logger.info(
            "rolling back to step %d from process %d (steps were %s)",
            consensus, src, steps.tolist(),
        )
        state = multihost_utils.broadcast_one_to_all(
            full, is_source=i_am_src
        )
        state = jax.tree.map(jax.device_put, state, shardings)
        return consensus, state

    def _full_host_state(self, template: Any,
                         registry: dict[str, list[PieceSource]]) -> Any:
        """Materialize the COMPLETE state host-side from local pieces;
        raises CoverageError when any leaf isn't fully covered."""
        named = _leaf_paths(template)
        leaves = []
        for name, leaf in named:
            pieces = registry.get(name)
            if not pieces:
                raise CoverageError(f"no local pieces for {name!r}")
            shape = tuple(pieces[0].global_shape)
            if tuple(getattr(leaf, "shape", shape)) != shape:
                raise ValueError(
                    f"leaf {name!r}: snapshot shape {shape} != template "
                    f"{tuple(leaf.shape)}"
                )
            want_dtype = getattr(leaf, "dtype", None)
            if (want_dtype is not None
                    and np.dtype(want_dtype) != pieces[0].dtype):
                # non-source processes broadcast zeros of the TEMPLATE
                # dtype; a mismatched source tree would wedge the
                # recovery collective instead of falling back to storage
                raise ValueError(
                    f"leaf {name!r}: snapshot dtype {pieces[0].dtype} "
                    f"!= template {np.dtype(want_dtype)}"
                )
            leaves.append(assemble(
                [[0, s] for s in shape], pieces[0].dtype, pieces
            ))
        import jax

        return jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(template), leaves
        )

    @staticmethod
    def _all_processes_agree(ok: bool) -> bool:
        import jax

        if jax.process_count() == 1:
            return ok
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray(1 if ok else 0, np.int64)
        )
        return bool(flags.all())

    def _build(self, template: Any, shardings: Any,
               registry: dict[str, list[PieceSource]]) -> Any:
        import jax

        named = _leaf_paths(template)
        shard_of = dict(_leaf_paths(shardings))
        leaves = []
        for name, leaf in named:
            pieces = registry.get(name)
            if not pieces:
                raise CoverageError(f"checkpoint has no pieces for {name!r}")
            shape = tuple(pieces[0].global_shape)
            dtype = pieces[0].dtype
            if tuple(getattr(leaf, "shape", shape)) != shape:
                raise ValueError(
                    f"leaf {name!r}: checkpoint shape {shape} != template "
                    f"{tuple(leaf.shape)}"
                )
            sharding = shard_of[name]
            arr = jax.make_array_from_callback(
                shape, sharding,
                lambda idx, p=pieces, d=dtype, s=shape: assemble(
                    _norm_index(idx, s), d, p
                ),
            )
            leaves.append(arr)
        treedef = jax.tree_util.tree_structure(template)
        return jax.tree_util.tree_unflatten(treedef, leaves)
