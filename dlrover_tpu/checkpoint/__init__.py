from dlrover_tpu.checkpoint.engine import CheckpointEngine  # noqa: F401
from dlrover_tpu.checkpoint.shm_handler import (  # noqa: F401
    SharedMemoryHandler,
    restore_pytree,
)
