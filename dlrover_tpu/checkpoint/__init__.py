from dlrover_tpu.checkpoint.checkpointer import Checkpointer  # noqa: F401
from dlrover_tpu.checkpoint.engine import CheckpointEngine  # noqa: F401
