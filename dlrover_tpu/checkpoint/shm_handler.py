"""Shared-memory snapshot arena for JAX pytrees.

Reference analog: SharedMemoryHandler in
dlrover/python/elastic_agent/torch/ckpt_saver.py (:209): tensor metas in a
SharedDict, tensor bytes packed into one named shm block at precomputed
offsets. The arena outlives the training process, so the agent can persist
the last snapshot even after a crash, and a restarted process restores from
memory without touching storage.

JAX specifics: all D2H transfers are kicked off with
``copy_to_host_async`` before the first blocking ``device_get`` so they
overlap, then each host buffer is copied into its arena view. Restore hands
back numpy arrays; the caller ``device_put``s them with target shardings
(which may differ from the saving mesh — reshard-on-load).
"""

from __future__ import annotations

import math
import os
import struct
import threading
import time
from typing import Any, Callable

import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.multi_process import (
    SharedDict,
    SharedLock,
    SharedMemoryArena,
)

logger = get_logger(__name__)

_HEADER_KEY = "__snapshot__"


def _leaf_paths(tree: Any) -> list[tuple[str, Any]]:
    """Flatten a pytree into sorted (path, leaf) pairs with stable names."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        name = "/".join(_path_elem_str(p) for p in path) or "."
        out.append((name, leaf))
    return out


def _path_elem_str(p: Any) -> str:
    import jax

    if isinstance(p, jax.tree_util.DictKey):
        return str(p.key)
    if isinstance(p, jax.tree_util.SequenceKey):
        return str(p.idx)
    if isinstance(p, jax.tree_util.GetAttrKey):
        return str(p.name)
    if isinstance(p, jax.tree_util.FlattenedIndexKey):
        return str(p.key)
    return str(p)


def compute_layout(named_leaves: list[tuple[str, Any]]) -> tuple[dict, int]:
    """Per-leaf shm offsets (64-byte aligned) and the total arena size."""
    metas: dict[str, dict] = {}
    offset = 0
    for name, leaf in named_leaves:
        arr = np.asarray(leaf) if np.isscalar(leaf) else leaf
        nbytes = int(np.dtype(arr.dtype).itemsize * math.prod(arr.shape or (1,)))
        metas[name] = {
            "offset": offset,
            "shape": list(arr.shape),
            "dtype": str(np.dtype(arr.dtype)),
            "nbytes": nbytes,
        }
        offset += (nbytes + 63) & ~63
    return metas, max(offset, 64)


class SharedMemoryHandler:
    """One node's snapshot arena + meta dict + writer lock.

    ``owner=True`` in the agent process (hosts the meta dict and lock
    servers); ``owner=False`` in the training process (clients).
    """

    def __init__(self, node_id: int, owner: bool = False):
        self.node_id = node_id
        self._owner = owner
        name = f"ckpt_node{node_id}"
        self.meta_dict = SharedDict(name, create=owner)
        self.lock = SharedLock(name, create=owner)
        self._arena: SharedMemoryArena | None = None
        self._arena_name = f"ckpt_arena_{node_id}"
        self._local_lock = threading.Lock()
        self._pack_fn = None  # jitted per-dtype concat (packed fetch)

    # ---------------------------------------------------------------- write

    # total device bytes above which packed fetch falls back to per-leaf
    # (the pack's concat output transiently duplicates the state in HBM)
    PACK_LIMIT_BYTES = 4 << 30

    def save_state_dict(self, step: int, tree: Any,
                        extra_meta: dict | None = None) -> None:
        """Snapshot a pytree of device/host arrays into shared memory.

        Device leaves are fetched PACKED: a jitted per-dtype concat turns
        N arrays into one, so the host pays one fixed transfer overhead
        per dtype instead of per leaf. Measured on the 8-virtual-device
        CPU mesh: per-array fetch costs ~4-12 ms regardless of size
        (~0.4 s per snapshot for a 38-leaf state), packed ~10-30 ms
        total. Falls back to per-leaf (with overlapped async D2H) for
        host leaves or states too big to duplicate on device.
        """
        import jax

        named = _leaf_paths(tree)
        metas, total = compute_layout(named)
        fetched = self._fetch_packed(named)
        if fetched is None:
            # kick off all D2H copies before the first blocking read
            for _, leaf in named:
                if isinstance(leaf, jax.Array) and hasattr(
                    leaf, "copy_to_host_async"
                ):
                    try:
                        leaf.copy_to_host_async()
                    except RuntimeError:
                        pass
            fetched = {
                name: np.asarray(jax.device_get(leaf))
                for name, leaf in named
            }
        with self._local_lock:
            arena = self._ensure_arena(total)
            buf = arena.buf
            for name, _ in named:
                info = metas[name]
                host = fetched[name]
                view = np.ndarray(
                    host.shape, dtype=host.dtype,
                    buffer=buf, offset=info["offset"],
                )
                np.copyto(view, host)
        header = {
            "step": step,
            "total_size": total,
            "metas": metas,
        }
        if extra_meta:
            header.update(extra_meta)
        self.meta_dict.set(_HEADER_KEY, header)

    def save_state_dict_fork(self, step: int, tree: Any,
                             extra_meta: dict | None = None,
                             on_done: Callable[[bool, dict], None]
                             | None = None) -> dict:
        """Copy-on-write snapshot: device leaves are fetched in the caller
        (D2H must happen here — a forked child must never touch the device
        runtime), then the process forks and the CHILD copies the host
        buffers into the shared arena while the parent returns immediately.

        Blocking cost is the ``fork`` itself (page-table duplication —
        milliseconds even for multi-GB states, THP-backed heaps fork at
        ~2MB/PTE granularity), not the memcpy: on a single-core host the
        direct path is memcpy-roofline-bound (~7 GB/s measured, 1.6 s for
        12 GB) and no threadpool can beat that, but COW moves the copy off
        the training path entirely. The tax shifts to subsequent steps as
        COW faults when training rewrites the state — the goodput bench's
        snapshot-overhead accounting is where that shows up, honestly.

        The header is published ONLY after the child exits cleanly, by a
        watcher thread in the parent (the SharedDict/SharedLock clients are
        mutex-guarded, so cross-thread use is safe; the child itself never
        touches the socket clients — it inherits forked copies of their
        fds and writing would interleave frames with the parent).

        Returns ``{"pid", "fork_s", "total_bytes"}``; completion is
        signalled via ``on_done(ok, info)`` from the watcher thread.

        Fork-safety: the copy loop in the child runs over (view, host)
        ndarray pairs constructed BEFORE the fork, so the child performs
        no allocations beyond loop temporaries — minimizing the window
        for the classic fork-while-malloc-locked deadlock.
        """
        import jax

        named = _leaf_paths(tree)
        metas, total = compute_layout(named)
        fetched = self._fetch_packed(named)
        if fetched is None:
            for _, leaf in named:
                if isinstance(leaf, jax.Array) and hasattr(
                    leaf, "copy_to_host_async"
                ):
                    try:
                        leaf.copy_to_host_async()
                    except RuntimeError:
                        pass
            fetched = {
                name: np.asarray(jax.device_get(leaf))
                for name, leaf in named
            }
        with self._local_lock:
            arena = self._ensure_arena(total)
        buf = arena.buf
        pairs = []
        for name, _ in named:
            info = metas[name]
            host = fetched[name]
            view = np.ndarray(host.shape, dtype=host.dtype,
                              buffer=buf, offset=info["offset"])
            pairs.append((view, host))
        header = {"step": step, "total_size": total, "metas": metas}
        if extra_meta:
            header.update(extra_meta)

        r_fd, w_fd = os.pipe()
        t0 = time.monotonic()
        import warnings

        with warnings.catch_warnings():
            # the multithreaded-fork warning is acknowledged: the child
            # only runs the pre-built memcpy loop and _exit (see above)
            warnings.simplefilter("ignore", DeprecationWarning)
            pid = os.fork()
        if pid == 0:  # ---- child: memcpy + signal, nothing else
            try:
                os.close(r_fd)
                t_c = time.monotonic()
                for view, host in pairs:
                    np.copyto(view, host)
                os.write(w_fd, struct.pack("d", time.monotonic() - t_c))
                os._exit(0)
            except BaseException:  # noqa: BLE001 - no cleanup in the child
                os._exit(1)
        fork_s = time.monotonic() - t0
        os.close(w_fd)
        info = {"pid": pid, "fork_s": fork_s, "total_bytes": total}

        def _watch() -> None:
            # ok means "copied AND header published": a child that
            # copied but whose header publish failed must report
            # failure, or the engine would enqueue a persist against
            # the previous header believing this step landed
            ok = False
            try:
                payload = os.read(r_fd, 8)
                _, status = os.waitpid(pid, 0)
                child_ok = (os.waitstatus_to_exitcode(status) == 0
                            and len(payload) == 8)
                if child_ok:
                    info["copy_s"] = struct.unpack("d", payload)[0]
                    self.meta_dict.set(_HEADER_KEY, header)
                    ok = True
                else:
                    logger.error(
                        "COW snapshot child (pid %d) failed; header for "
                        "step %d not published", pid, step,
                    )
            except OSError:
                logger.exception("COW snapshot watcher failed")
            finally:
                os.close(r_fd)
                if on_done is not None:
                    on_done(ok, info)

        threading.Thread(target=_watch, name="cow-snapshot-watch",
                         daemon=True).start()
        return info

    def _fetch_packed(self, named: list[tuple[str, Any]]
                      ) -> dict[str, np.ndarray] | None:
        """One device fetch per dtype instead of per leaf, or None to
        fall back (host leaves present / state too large to duplicate)."""
        import jax
        import jax.numpy as jnp

        total = 0
        groups: dict[tuple, list[tuple[str, Any]]] = {}
        for name, leaf in named:
            if not isinstance(leaf, jax.Array):
                return None
            total += leaf.nbytes
            # group by (dtype, device set): an MPMD state's stages live
            # on disjoint submeshes and one jitted concat cannot span
            # device sets — per-group packing keeps the fast path
            devs = tuple(sorted(
                d.id for d in getattr(leaf.sharding, "device_set", ())
            ))
            groups.setdefault((str(leaf.dtype), devs),
                              []).append((name, leaf))
        if total > self.PACK_LIMIT_BYTES:
            return None
        if self._pack_fn is None:
            self._pack_fn = jax.jit(
                lambda leaves: jnp.concatenate(
                    [jnp.ravel(x) for x in leaves]
                )
            )
        out: dict[str, np.ndarray] = {}
        try:
            flats = {
                key: self._pack_fn([leaf for _, leaf in items])
                for key, items in groups.items()
            }
            for f in flats.values():
                f.copy_to_host_async()
            for key, items in groups.items():
                host = np.asarray(jax.device_get(flats[key]))
                off = 0
                for name, leaf in items:
                    n = int(np.prod(leaf.shape or (1,)))
                    out[name] = host[off:off + n].reshape(leaf.shape)
                    off += n
        except (RuntimeError, ValueError) as e:
            logger.warning("packed snapshot fetch failed (%s); "
                           "falling back to per-leaf", e)
            return None
        return out

    def _ensure_arena(self, size: int) -> SharedMemoryArena:
        if self._arena is None or self._arena.size < size:
            if self._arena is not None:
                self._arena.close()
            self._arena = SharedMemoryArena.open_or_create(
                self._arena_name, size
            )
        return self._arena

    # ----------------------------------------------------------------- read

    def header(self) -> dict | None:
        return self.meta_dict.get().get(_HEADER_KEY)

    def load_arrays(self, copy: bool = True
                    ) -> tuple[int, dict[str, np.ndarray]] | None:
        """Read the snapshot: (step, {path: array}). None if empty.

        ``copy=False`` returns zero-copy views into the arena — valid only
        until the next snapshot overwrites it. Use when a consumer reads the
        arrays immediately (``jax.device_put`` on restore) and skip the
        host-memory materialization cost.
        """
        header = self.header()
        if not header:
            return None
        arena = self._open_arena(min_size=int(header["total_size"]))
        if arena is None:
            return None
        out: dict[str, np.ndarray] = {}
        for name, info in header["metas"].items():
            view = np.ndarray(
                tuple(info["shape"]),
                dtype=np.dtype(info["dtype"]),
                buffer=arena.buf,
                offset=info["offset"],
            )
            out[name] = np.array(view) if copy else view
        return int(header["step"]), out

    def write_raw(self, header: dict, payload: bytes) -> None:
        """Install a snapshot received as raw bytes (buddy restore path:
        checkpoint/buddy.py fetch_snapshot -> this node's arena). The
        header becomes visible only after the bytes are in place, same
        ordering as save_state_dict."""
        total = int(header["total_size"])
        if len(payload) < total:
            raise ValueError(
                f"payload {len(payload)} bytes < header total {total}"
            )
        with self._local_lock:
            arena = self._ensure_arena(total)
            arena.buf[:total] = payload[:total]
        self.meta_dict.set(_HEADER_KEY, header)

    def read_raw(self) -> tuple[dict, memoryview] | None:
        """Agent-side zero-copy access: (header, raw buffer)."""
        header = self.header()
        if not header:
            return None
        arena = self._open_arena(min_size=int(header["total_size"]))
        if arena is None:
            return None
        return header, arena.buf

    def _open_arena(self, min_size: int = 0) -> SharedMemoryArena | None:
        """Open (or re-open) the arena mapping.

        The trainer unlinks and recreates the segment under the same name
        when a snapshot grows, so a cached mapping smaller than the header's
        ``total_size`` is stale — close it and map the new segment.
        """
        with self._local_lock:
            if self._arena is not None and self._arena.size < min_size:
                self._arena.close()
                self._arena = None
            if self._arena is None:
                self._arena = SharedMemoryArena.open(self._arena_name)
            return self._arena

    def clear(self) -> None:
        self.meta_dict.pop(_HEADER_KEY)

    def close(self, unlink: bool = False) -> None:
        with self._local_lock:
            if self._arena is not None:
                if unlink:
                    self._arena.unlink()
                self._arena.close()
                self._arena = None
        self.meta_dict.close()
        self.lock.close()


def restore_pytree(template: Any, arrays: dict[str, np.ndarray],
                   put: Callable[[str, np.ndarray], Any] | None = None) -> Any:
    """Rebuild a pytree shaped like ``template`` from named arrays.

    ``put`` maps (path, host_array) -> leaf (e.g. ``jax.device_put`` with a
    target sharding for reshard-on-load); identity by default.
    """
    import jax

    named = _leaf_paths(template)
    leaves = []
    for name, leaf in named:
        if name not in arrays:
            raise KeyError(f"checkpoint missing leaf {name!r}")
        arr = arrays[name]
        tmpl = np.asarray(leaf) if np.isscalar(leaf) else leaf
        if tuple(arr.shape) != tuple(tmpl.shape):
            raise ValueError(
                f"leaf {name!r} shape {arr.shape} != template {tmpl.shape}"
            )
        leaves.append(put(name, arr) if put else arr)
    treedef = jax.tree_util.tree_structure(template)
    return jax.tree_util.tree_unflatten(treedef, leaves)
