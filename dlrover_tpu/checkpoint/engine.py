"""Trainer-side flash-checkpoint engine.

Reference analog: dlrover/trainer/torch/flash_checkpoint/engine.py (:134
CheckpointEngine, :287 save_state_dict_to_memory) + full_ckpt_engine.py.

Save path: snapshot the pytree into this node's shm arena (sub-second), then
— for DISK saves — enqueue an event so the *agent's* AsyncCheckpointSaver
persists shm -> storage off the training path. Load path: shm fast-path if a
snapshot exists (restart-in-place), else read the committed step from
storage.

Runs in two modes:
- agent mode: the agent owns the shm primitives; this engine connects as a
  client (detected by the agent's IPC sockets existing).
- solo mode (no agent — notebooks, bench scripts): the engine owns the
  primitives and runs an in-process AsyncCheckpointSaver thread, keeping the
  same async behavior.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Callable

import numpy as np

from dlrover_tpu.common.constants import CheckpointStorageType, EnvKey
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.multi_process import SharedQueue, client_socket_ready
from dlrover_tpu.common.storage import CheckpointStorage, PosixDiskStorage
from dlrover_tpu.telemetry.journal import get_journal, spawn_ctx
from dlrover_tpu.telemetry.metrics import registry
from dlrover_tpu.checkpoint.shm_handler import (
    SharedMemoryHandler,
    restore_pytree,
)

logger = get_logger(__name__)

# shared by CheckpointEngine.load and ShardedCheckpointEngine.load_sharded
_restore_seconds = registry().histogram(
    "dlrover_tpu_ckpt_restore_seconds",
    "checkpoint restore duration by engine",
    label_names=("engine",),
)
_snapshot_seconds = registry().histogram(
    "dlrover_tpu_ckpt_snapshot_seconds",
    "in-memory (shm) snapshot duration on the training path — the C "
    "the Young-Daly interval tuner prices",
)


def _record_restore(engine: str, start_monotonic: float, step: int) -> None:
    dur = time.monotonic() - start_monotonic
    _restore_seconds.labels(engine).observe(dur)
    # spawn_ctx (§27): a restore in a child respawned during a recovery
    # incident journals under that incident's node_restart root
    get_journal().emit("ckpt_restore", dur=dur, step=step, engine=engine,
                       remote_parent=spawn_ctx())


@dataclasses.dataclass
class PersistWait:
    """Typed outcome of a durable-persist wait.

    Truthiness preserves the old bool contract, but ``kind`` makes a
    timeout distinguishable from "no checkpoint was ever requested" at
    every call site — the silent-False bug class where a caller shut
    down believing the step was durable. Every timeout is journaled
    (``ckpt_persist_timeout``), so the trail shows exactly which steps
    the job gave up waiting for.
    """

    ok: bool
    kind: str            # "ok" | "timeout"
    step: int
    waited_s: float
    persisted_step: int  # newest step durably committed when we stopped

    def __bool__(self) -> bool:
        return self.ok


def _journal_persist_timeout(what: str, step: int, waited_s: float,
                             **fields) -> None:
    get_journal().emit("ckpt_persist_timeout", what=what, step=step,
                       waited_s=waited_s, **fields)


def _read_storage_arrays(storage: CheckpointStorage, ckpt_dir: str,
                         node_id: int, step: int | None = None
                         ) -> tuple[int, dict[str, np.ndarray]] | None:
    """CRC-verified storage read: resolve the newest VERIFIED step (or a
    pinned one) and materialize its arrays. Pure function of storage
    state so it can run on the restore-prefetch thread concurrently
    with rendezvous/compile as well as inline."""
    from dlrover_tpu.agent.ckpt_saver import step_dir
    from dlrover_tpu.checkpoint.integrity import resolve_restore_step

    if step is None:
        # newest VERIFIED step: crc-checked against the COMMIT
        # manifest, rolling back past corrupt/incomplete steps —
        # a flipped bit must cost a checkpoint interval, never a
        # silent restore of bad bytes. An explicitly pinned `step`
        # (best-model reload) bypasses this by caller contract.
        committed = resolve_restore_step(storage, ckpt_dir)
        if committed is None:
            return None
        step, _ = committed
    sdir = step_dir(ckpt_dir, step)
    # replicated ckpt: one node file holds everything; prefer our own,
    # else the smallest node id present.
    metas = [
        f for f in storage.listdir(sdir) if f.endswith(".meta.json")
    ]
    if not metas:
        return None
    own = f"node_{node_id}.meta.json"
    meta_file = own if own in metas else sorted(metas)[0]
    header = json.loads(
        storage.read_text(os.path.join(sdir, meta_file))
    )
    if meta_file != own and not header.get("replicated", True):
        # Sharded checkpoint: another node's file holds a different
        # shard — loading it would silently install wrong weights.
        raise FileNotFoundError(
            f"sharded checkpoint at {sdir} is missing this node's "
            f"shard {own}; refusing to load another node's shard"
        )
    bin_file = meta_file.replace(".meta.json", ".bin")
    blob = storage.read(os.path.join(sdir, bin_file))
    arrays: dict[str, np.ndarray] = {}
    for name, info in header["metas"].items():
        arr = np.frombuffer(
            blob, dtype=np.dtype(info["dtype"]),
            count=max(1, int(np.prod(info["shape"] or [1]))),
            offset=info["offset"],
        ).reshape(info["shape"])
        arrays[name] = arr
    logger.info("restored step %d from storage %s", step, sdir)
    return step, arrays


def _storage_fallback_leaf(storage: CheckpointStorage, ckpt_dir: str,
                           name: str, leaf, registry_box: list
                           ) -> np.ndarray | None:
    """Assemble a full leaf from the newest VERIFIED storage step's
    piece registry — the path a MULTI-host reshard takes for shards
    whose only live copy died with a host. ``registry_box`` caches the
    resolved plan across leaves of one reshard (lazy: resolved on the
    first miss)."""
    from dlrover_tpu.checkpoint import sharded as sharded_mod
    from dlrover_tpu.checkpoint.integrity import resolve_restore_plan

    if not registry_box:
        plan = resolve_restore_plan(storage, ckpt_dir)
        registry_box.append(
            None if plan is None else
            sharded_mod.storage_piece_registry(
                storage, ckpt_dir, plan.step, plan.num_shards,
                bad_pieces=plan.bad_pieces,
            )
        )
    registry = registry_box[0]
    pieces = (registry or {}).get(name)
    if not pieces:
        return None
    shape = tuple(pieces[0].global_shape)
    if shape != tuple(getattr(leaf, "shape", shape)):
        return None
    return sharded_mod.assemble(
        [[0, s] for s in shape], pieces[0].dtype, pieces
    )


class RestorePrefetch:
    """Background storage restore: the read + integrity verification run
    on a daemon thread while the process is busy with rendezvous,
    ``jax.distributed.initialize`` or the first compile; ``join`` hands
    the verified arrays over before the first step needs them.

    Failure ordering is safe by construction: the thread runs the same
    ``resolve_restore_step`` rollback logic as the inline path, a
    raised error or timeout makes ``join`` return None (callers fall
    back to the synchronous read), and a consumer that pins a different
    step than the prefetch resolved discards the prefetched result.
    """

    def __init__(self, ckpt_dir: str, node_id: int,
                 storage: CheckpointStorage | None = None):
        self.ckpt_dir = ckpt_dir
        self.node_id = node_id
        self.storage = storage or PosixDiskStorage()
        self._result: tuple[int, dict[str, np.ndarray]] | None = None
        self._error: BaseException | None = None
        self.outcome = "pending"  # "ok"|"empty"|"error"|"timeout"
        self._done = threading.Event()
        self._started = time.monotonic()
        threading.Thread(
            target=self._run, name="restore-prefetch", daemon=True
        ).start()

    def _run(self) -> None:
        try:
            self._result = _read_storage_arrays(
                self.storage, self.ckpt_dir, self.node_id
            )
        except BaseException as e:  # noqa: BLE001 - reported via join()
            logger.warning("restore prefetch failed: %s", e)
            self._error = e
        finally:
            dur = time.monotonic() - self._started
            self._done.set()
            get_journal().emit(
                "restore_prefetch", dur=dur,
                step=self._result[0] if self._result else -1,
                ok=self._error is None, remote_parent=spawn_ctx(),
            )

    def join(self, timeout: float = 120.0
             ) -> tuple[int, dict[str, np.ndarray]] | None:
        """The verified (step, arrays), or None on no-checkpoint /
        error / timeout — None always means 'do the synchronous read'.
        ``outcome`` ("ok" | "empty" | "error" | "timeout") types WHY,
        and a timeout is journaled (``ckpt_persist_timeout``) — a
        prefetch thread wedged on sick storage must be visible, not a
        silently slower restore."""
        if not self._done.wait(timeout):
            self.outcome = "timeout"
            _journal_persist_timeout("restore_prefetch", -1, timeout,
                                     ckpt_dir=self.ckpt_dir)
            logger.warning("restore prefetch still running after %.0fs; "
                           "falling back to the synchronous read", timeout)
            return None
        if self._error is not None:
            self.outcome = "error"
            return None
        self.outcome = "ok" if self._result is not None else "empty"
        return self._result


_prefetch_lock = threading.Lock()
_prefetches: dict[tuple[str, int], RestorePrefetch] = {}


def start_restore_prefetch(ckpt_dir: str, node_id: int | None = None,
                           storage: CheckpointStorage | None = None
                           ) -> RestorePrefetch:
    """Begin the storage restore read + verification NOW (idempotent per
    (ckpt_dir, node)); the next ``CheckpointEngine`` load for the same
    checkpoint consumes it. Called by a parked standby trainer when the
    agent signals an imminent promotion (overlap with the rendezvous
    round) and by trainer mains before distributed init / compile."""
    nid = (node_id if node_id is not None
           else int(os.environ.get(EnvKey.NODE_ID, "0")))
    key = (os.path.abspath(ckpt_dir), nid)
    with _prefetch_lock:
        pf = _prefetches.get(key)
        if pf is None:
            pf = _prefetches[key] = RestorePrefetch(ckpt_dir, nid, storage)
        return pf


def take_restore_prefetch(ckpt_dir: str, node_id: int
                          ) -> RestorePrefetch | None:
    with _prefetch_lock:
        return _prefetches.pop((os.path.abspath(ckpt_dir), node_id), None)


class CheckpointEngine:
    # async snapshots supersede older pending ones, which is safe only
    # when one node's snapshot is the whole checkpoint; sharded engines
    # need cross-node step agreement and keep the sync path
    supports_async_snapshot = True

    def __init__(
        self,
        ckpt_dir: str,
        storage: CheckpointStorage | None = None,
        node_id: int | None = None,
        node_rank: int | None = None,
        world_size: int | None = None,
        replicated: bool = True,
        snapshot_mode: str = "direct",
    ):
        self.ckpt_dir = ckpt_dir
        self.storage = storage or PosixDiskStorage()
        self.node_id = (
            node_id if node_id is not None
            else int(os.environ.get(EnvKey.NODE_ID, "0"))
        )
        self.node_rank = (
            node_rank if node_rank is not None
            else int(os.environ.get(EnvKey.NODE_RANK, "0"))
        )
        self.world_size = (
            world_size if world_size is not None
            else int(os.environ.get(EnvKey.NODE_NUM, "1"))
        )
        # replicated: every node holds the full state (DP); only rank 0
        # persists to storage. Sharded engines set replicated=False and every
        # node persists its own shard.
        self.replicated = replicated
        # async-snapshot pipeline state (save_to_memory_async)
        self._pending_lock = threading.Lock()
        self._pending: tuple[int, int, Any] | None = None  # (seq, step, snap)
        self._async_seq = 0
        # sequence floor: a sync save lifts it so an older async snapshot
        # popped-but-unwritten can never overwrite the newer sync write
        self._async_floor = 0
        self._async_writing = False
        self._snap_wake = threading.Event()
        self._snap_stop = threading.Event()
        self._snap_thread: threading.Thread | None = None
        self._device_copy = None
        self._async_ok: bool | None = None
        # COW (fork) snapshot mode: save_to_memory returns after the fork
        # and a child process does the arena memcpy (shm_handler.
        # save_state_dict_fork). "direct" keeps the in-process copy.
        if snapshot_mode not in ("direct", "cow"):
            raise ValueError(f"snapshot_mode {snapshot_mode!r}")
        self.snapshot_mode = (
            snapshot_mode if hasattr(os, "fork") else "direct"
        )
        self._cow_done = threading.Event()
        self._cow_done.set()
        self._cow_info: dict = {}
        self._cow_ok: bool | None = None  # None = no COW save yet
        self._solo_saver = None
        agent_present = client_socket_ready(f"dict_ckpt_node{self.node_id}")
        if not agent_present:
            from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver

            self._solo_saver = AsyncCheckpointSaver.start(self.node_id)
            self.shm_handler = self._solo_saver.shm_handler
            self.event_queue = self._solo_saver.event_queue
        else:
            self.shm_handler = SharedMemoryHandler(self.node_id, owner=False)
            self.event_queue = SharedQueue(
                f"ckpt_event_{self.node_id}", create=False
            )

    # ------------------------------------------------------------------ save

    def _extra_meta(self) -> dict:
        return {
            "ckpt_dir": self.ckpt_dir,
            "storage": self.storage.class_meta().to_dict(),
            "node_rank": self.node_rank,
            "node_id": self.node_id,
            "world_size": self.world_size,
            "num_shards": 1 if self.replicated else self.world_size,
            "replicated": self.replicated,
        }

    def _prepare_state(self, state: Any) -> tuple[Any, dict]:
        """Hook: transform the pytree before snapshotting (sharded engines
        split leaves into addressable pieces here). Returns (tree, extra
        header metadata)."""
        return state, {}

    def save_to_memory(self, step: int, state: Any,
                       _async_seq: int | None = None) -> bool:
        """Sub-second snapshot into shm. Returns False if the saver is mid-
        persist (skip rather than block the training step).

        ``_async_seq`` is the snapshot-worker's ordering token: under the
        shm lock, an async write whose sequence a sync save has already
        superseded is dropped — otherwise a worker that popped step N and
        then got descheduled could overwrite a NEWER sync snapshot the
        persister is about to read.
        """
        # at most one COW child in flight: its arena write is guarded by
        # the shm lock the watcher releases, so a second save must wait
        # for that release rather than silently skip
        if self.snapshot_mode == "cow":
            self.wait_snapshot(timeout=300.0)
        if not self.shm_handler.lock.acquire(blocking=False):
            logger.warning(
                "skipping in-memory save at step %d: persister busy", step
            )
            return False
        release_lock = True
        try:
            with self._pending_lock:
                if _async_seq is not None:
                    if _async_seq <= self._async_floor:
                        return False  # superseded by a sync save
                else:
                    # sync write wins over anything async still in flight
                    self._async_floor = self._async_seq
                    self._pending = None
            start = time.monotonic()
            tree, extra = self._prepare_state(state)
            extra_meta = {**self._extra_meta(), **extra}
            if self.snapshot_mode == "cow":
                self._cow_done.clear()
                self._cow_ok = None

                def _on_done(ok: bool, info: dict) -> None:
                    # _cow_done MUST be set even if the lock release
                    # throws (dead lock-server socket): a missed set()
                    # wedges every later save/load behind 300s waits
                    try:
                        self._cow_info = info
                        self._cow_ok = ok
                        self.shm_handler.lock.release()
                    except Exception:  # noqa: BLE001 - see above
                        logger.exception(
                            "COW watcher completion cleanup failed")
                        self._cow_ok = False
                    finally:
                        self._cow_done.set()

                try:
                    info = self.shm_handler.save_state_dict_fork(
                        step, tree, extra_meta=extra_meta,
                        on_done=_on_done,
                    )
                except BaseException:
                    self._cow_done.set()
                    raise
                release_lock = False  # the watcher owns the release now
                logger.info(
                    "step %d COW-snapshot forked in %.3fs (child %d "
                    "copying %.2f GB)", step, info["fork_s"],
                    info["pid"], info["total_bytes"] / (1 << 30),
                )
                return True
            self.shm_handler.save_state_dict(
                step, tree, extra_meta=extra_meta
            )
            # a direct save supersedes any earlier failed COW verdict
            self._cow_ok = None
            snap_s = time.monotonic() - start
            # the training-path cost the Young-Daly tuner prices (C)
            _snapshot_seconds.observe(snap_s)
            logger.info(
                "step %d snapshotted to shm in %.3fs", step, snap_s,
            )
            return True
        finally:
            if release_lock:
                self.shm_handler.lock.release()

    def wait_snapshot(self, timeout: float = 60.0) -> bool:
        """Block until any in-flight COW snapshot child has finished.
        Returns False if it timed out OR the child FAILED (its header
        was never published — the previous snapshot still stands).
        True immediately in direct mode."""
        if not self._cow_done.wait(timeout=timeout):
            return False
        return self._cow_ok is not False

    @property
    def last_snapshot_info(self) -> dict:
        """Timing of the last completed COW snapshot ({fork_s, copy_s,
        total_bytes}); empty in direct mode."""
        return dict(self._cow_info)

    def _async_eligible(self) -> bool:
        """The gate lives HERE, not at call sites: sharded engines need
        cross-node step agreement (supersede would break it), and on the
        CPU backend a second host thread touching arrays mid-collective
        wedges XLA:CPU's in-process rendezvous."""
        if not self.supports_async_snapshot:
            return False
        if self._async_ok is None:
            import jax

            self._async_ok = jax.devices()[0].platform != "cpu"
        return self._async_ok

    def save_to_memory_async(self, step: int, state: Any) -> None:
        """Zero-stall snapshot: returns before any device sync.
        Falls back to the synchronous path where async is unsafe
        (sharded engine, CPU backend) — callers never need their own
        gate. The synchronous path's cost is NOT the arena write — it is the
        host blocking on ``device_get`` until every queued step finishes,
        charged to the training loop (measured 0.15-0.35s per snapshot in
        the goodput bench, 5-8% of steady step time at tuned cadences).
        Here the state is first duplicated ON DEVICE (a jitted identity —
        async dispatch, fresh buffers immune to the train step's buffer
        donation; a post-donation host read of the original would raise
        "Array has been deleted"), then a worker thread blocks and writes
        the arena while the main thread keeps dispatching steps.

        Costs one transient state copy in HBM; callers with states near
        the HBM limit (the 1B ckpt bench) use the sync path. Supersede
        semantics: only the newest pending snapshot is written.
        """
        if not self._async_eligible():
            self.save_to_memory(step, state)
            return
        import jax

        if self._device_copy is None:
            import jax.numpy as jnp

            self._device_copy = jax.jit(
                lambda t: jax.tree.map(jnp.copy, t)
            )
        snap = self._device_copy(state)
        with self._pending_lock:
            self._async_seq += 1
            self._pending = (self._async_seq, step, snap)
        if self._snap_thread is None:
            self._snap_thread = threading.Thread(
                target=self._snapshot_worker, name="snapshot-writer",
                daemon=True,
            )
            self._snap_thread.start()
        self._snap_wake.set()

    def _snapshot_worker(self) -> None:
        while not self._snap_stop.is_set():
            self._snap_wake.wait()
            if self._snap_stop.is_set():
                return
            self._snap_wake.clear()
            with self._pending_lock:
                pending, self._pending = self._pending, None
                if pending is not None:
                    self._async_writing = True
            if pending is None:
                continue
            seq, step, snap = pending
            try:
                self.save_to_memory(step, snap, _async_seq=seq)
            except Exception:  # noqa: BLE001 - snapshots are best-effort
                logger.exception("async snapshot at step %d failed", step)
            finally:
                with self._pending_lock:
                    self._async_writing = False

    def flush_async(self, timeout: float = 60.0) -> bool:
        """Wait until no snapshot is pending or mid-write."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._pending_lock:
                # _async_writing covers the pop-to-write gap: pending is
                # None the moment the worker claims it, before the shm
                # lock is even requested
                idle = self._pending is None and not self._async_writing
            if idle and not self._snap_wake.is_set():
                return True
            time.sleep(0.02)
        return False

    def save_to_storage(self, step: int, state: Any) -> bool:
        # a pending/mid-write async snapshot holds the shm lock across
        # its device fetch; without this flush the non-blocking acquire
        # below loses the race and the DURABLE save silently degrades
        if self._snap_thread is not None:
            self.flush_async()
        if not self.save_to_memory(step, state):
            return False
        # a COW child may still be copying; the persist event must not
        # race it or the saver would read the previous header. A FAILED
        # child (OOM-killed mid-memcpy) must not enqueue either — the
        # header still describes the previous step and the persister
        # would durably commit the wrong one. Fall back to the direct
        # in-process copy: slower, but the durable save semantics hold.
        if not self.wait_snapshot(timeout=300.0):
            logger.warning(
                "COW snapshot for step %d failed; falling back to the "
                "direct copy for the durable save", step,
            )
            mode, self.snapshot_mode = self.snapshot_mode, "direct"
            try:
                if not self.save_to_memory(step, state):
                    return False
            finally:
                self.snapshot_mode = mode
        if self._should_write_storage():
            self.event_queue.put({"kind": "save", "step": step})
        return True

    def _should_write_storage(self) -> bool:
        return (not self.replicated) or self.node_rank == 0

    def save(self, step: int, state: Any,
             storage_type: CheckpointStorageType =
             CheckpointStorageType.MEMORY) -> bool:
        if storage_type == CheckpointStorageType.MEMORY:
            return self.save_to_memory(step, state)
        return self.save_to_storage(step, state)

    # ------------------------------------------------------------------ load

    def load(self, template: Any,
             put: Callable[[str, np.ndarray], Any] | None = None,
             zero_copy: bool = False,
             step: int | None = None,
             ) -> tuple[int, Any] | None:
        """Restore the newest checkpoint: shm first, then storage.

        ``zero_copy=True`` hands shm arena views straight to ``put``, which
        must consume them immediately (device transfer, file write) and
        return something that does NOT alias the input — retained views are
        overwritten by the next snapshot and block arena growth. Requires
        ``put``; explicit opt-in because safety depends on the callback.

        ``step`` pins the restore to a specific persisted step (best-model
        reload) instead of the newest; the shm fast path only applies when
        its snapshot is exactly that step.
        """
        if zero_copy and put is None:
            raise ValueError("zero_copy=True requires a consuming `put`")
        start = time.monotonic()
        # a COW child mid-copy is overwriting the arena under the OLD
        # header: reading now would return a torn mix of two steps. A
        # FAILED child is fine (header untouched, previous snapshot
        # stands), but an in-flight one must finish first.
        if not self._cow_done.wait(timeout=300.0):
            raise RuntimeError(
                "COW snapshot child still copying after 300s; refusing "
                "a torn arena read"
            )
        loaded = self._load_from_memory(copy=not zero_copy)
        if loaded is not None and step is not None and loaded[0] != step:
            loaded = None
        if loaded is None:
            loaded = self._load_from_storage(step=step)
        else:
            # shm fast path won: release any overlapped storage prefetch
            # so its arrays don't linger for the process lifetime
            take_restore_prefetch(self.ckpt_dir, self.node_id)
        if loaded is None:
            return None
        step, arrays = loaded
        restored = step, restore_pytree(template, arrays, put=put)
        _record_restore("engine", start, step)
        return restored

    def load_raw(self) -> tuple[int, dict] | None:
        """(step, {leaf_path: array}) without a shape template — for
        states with data-dependent shapes (embedding tables, whose row
        count is only known from the checkpoint itself)."""
        if not self._cow_done.wait(timeout=300.0):
            raise RuntimeError(
                "COW snapshot child still copying after 300s; refusing "
                "a torn arena read"
            )
        loaded = self._load_from_memory()
        if loaded is None:
            loaded = self._load_from_storage()
        return loaded

    def _load_from_memory(self, copy: bool = True
                          ) -> tuple[int, dict[str, np.ndarray]] | None:
        try:
            header = self.shm_handler.header()
            if header and header.get("ckpt_dir") not in (
                None, self.ckpt_dir
            ):
                # the shm segment is keyed by node id only: a snapshot
                # left by ANOTHER job on this host must not shadow the
                # requested checkpoint directory
                logger.info(
                    "shm snapshot belongs to %s, not %s; reading storage",
                    header.get("ckpt_dir"), self.ckpt_dir,
                )
                return None
            snap = self.shm_handler.load_arrays(copy=copy)
        except Exception:  # noqa: BLE001 - fall back to storage on any damage
            logger.exception("shm restore failed; falling back to storage")
            return None
        if snap is not None:
            logger.info("restoring step %d from shared memory", snap[0])
        return snap

    def _load_from_storage(self, step: int | None = None
                           ) -> tuple[int, dict[str, np.ndarray]] | None:
        prefetch = take_restore_prefetch(self.ckpt_dir, self.node_id)
        if prefetch is not None:
            got = prefetch.join()
            if got is not None and (step is None or got[0] == step):
                logger.info(
                    "restored step %d from the overlapped prefetch", got[0]
                )
                return got
            # the prefetch lost its race (errored, resolved a different
            # step than the pinned one, or a later failure changed the
            # storage state it read): fall through to a fresh
            # synchronous read, which re-runs the rollback logic
            logger.info("restore prefetch discarded; reading storage")
        return _read_storage_arrays(
            self.storage, self.ckpt_dir, self.node_id, step=step
        )

    # ------------------------------------------------------------- reshard

    def reshard_state(self, old_mesh, new_mesh, state,
                      step: int | None = None):
        """Membership change as a resharding event, not a restart
        (ElasWave; DESIGN.md §17): remap the live state's DP/TP/PP
        shards onto a reshaped mesh through this node's shm snapshot.

        The state is snapshotted into the shm arena first (sub-second;
        the training cadence usually already did it), then every leaf
        is scattered host-side onto ``new_mesh`` under its remapped
        PartitionSpec — the surviving incarnation resumes on the
        pre-compiled fallback program without a cold ``pjit`` compile,
        and the snapshot doubles as the rollback point if the reshape
        itself dies. Falls back to a direct device gather for leaves
        the snapshot cannot serve.
        """
        import jax

        from dlrover_tpu.checkpoint.shm_handler import _leaf_paths
        from dlrover_tpu.parallel import mesh as mesh_mod

        if step is None:
            step_leaf = getattr(state, "step", None)
            step = int(jax.device_get(step_leaf)) \
                if step_leaf is not None else 0
        arrays: dict[str, np.ndarray] | None = None
        if self.save_to_memory(step, state) and self.wait_snapshot():
            snap = self._load_from_memory(copy=False)
            if snap is not None and snap[0] == step:
                arrays = snap[1]
        names = iter(n for n, _ in _leaf_paths(state))
        registry_box: list = []  # lazy plan cache for _storage_fallback_leaf

        def _put(leaf, new_sharding):
            name = next(names)
            host = arrays.get(name) if arrays is not None else None
            if host is None:
                try:
                    host = np.asarray(jax.device_get(leaf))
                except (RuntimeError, ValueError) as e:
                    # a live shard is gone (its host died): fall back
                    # to the committed storage step instead of aborting
                    # the reshard (DESIGN.md §20)
                    host = _storage_fallback_leaf(
                        self.storage, self.ckpt_dir, name, leaf,
                        registry_box,
                    )
                    if host is None:
                        raise RuntimeError(
                            f"reshard cannot source leaf {name!r}: no "
                            "shm snapshot, no live device copy, and no "
                            "verified storage piece covers it"
                        ) from e
                    get_journal().emit("ckpt_restore_shard", step=step,
                                       writer="storage", leaf=name)
            return jax.device_put(host, new_sharding)

        out = mesh_mod.reshard_state(old_mesh, new_mesh, state, put=_put)
        # the resharded state's whole purpose is to feed the
        # pre-compiled (donating) fallback executable: re-stage the
        # device_put-built leaves into proper per-device buffers
        # (compile_cache.launder) or the donation corrupts them in
        # place on the CPU backend
        from dlrover_tpu.parallel.compile_cache import launder

        return launder(out)

    def latest_persisted_step(self) -> int:
        from dlrover_tpu.agent.ckpt_saver import read_tracker

        committed = read_tracker(self.storage, self.ckpt_dir)
        return -1 if committed is None else committed[0]

    def wait_for_persist(self, step: int, timeout: float = 120.0
                         ) -> PersistWait:
        """Block until ``step`` is durably committed (tracker moved past
        it). Returns a truthy ``PersistWait``; on timeout the result is
        falsy with ``kind="timeout"`` and the journal carries a
        ``ckpt_persist_timeout`` record — callers must not treat the
        step as durable (shutdown paths, checkpoint rotation)."""
        start = time.monotonic()
        deadline = time.time() + timeout
        newest = -1
        while time.time() < deadline:
            newest = self.latest_persisted_step()
            if newest >= step:
                return PersistWait(
                    ok=True, kind="ok", step=step,
                    waited_s=time.monotonic() - start,
                    persisted_step=newest,
                )
            time.sleep(0.1)
        waited = time.monotonic() - start
        _journal_persist_timeout("persist", step, waited,
                                 persisted_step=newest)
        logger.warning(
            "persist of step %d not durable after %.0fs (newest "
            "committed: %d)", step, waited, newest,
        )
        return PersistWait(ok=False, kind="timeout", step=step,
                           waited_s=waited, persisted_step=newest)

    def close(self) -> None:
        self.wait_snapshot(timeout=30.0)
        if self._snap_thread is not None:
            self.flush_async(timeout=10.0)
            self._snap_stop.set()
            self._snap_wake.set()
            self._snap_thread.join(timeout=5.0)
        if self._solo_saver is not None:
            from dlrover_tpu.agent.ckpt_saver import AsyncCheckpointSaver

            AsyncCheckpointSaver.reset(self.node_id)
        else:
            self.shm_handler.close()
            self.event_queue.close()
