"""Checkpoint end-to-end integrity: CRC manifests, COMMIT markers,
restore-time verification with rollback.

Commit protocol (extends agent/ckpt_saver.py's done-marker scheme, in
the spirit of Orbax's distributed commit — every shard durable and
checksummed before the step becomes visible)::

    <ckpt_dir>/step-<N>/node_<id>.bin          shard bytes (atomic write)
    <ckpt_dir>/step-<N>/node_<id>.meta.json    leaf metas + crc32/bin_bytes
    <ckpt_dir>/step-<N>/done_<id>_w<W>         per-writer marker, now
                                               carrying {"crc32", "bytes"}
    <ckpt_dir>/step-<N>/commit_w<W>            terminal COMMIT marker:
                                               the full shard manifest,
                                               written by rank-0's agent
                                               AFTER all done markers
    <ckpt_dir>/latest                          tracker (unchanged)

Restore-time verification (``resolve_restore_step``) starts from the
tracker and accepts a step only when its COMMIT manifest is complete
and every listed shard's bytes match their recorded CRC32; a corrupt or
incomplete step is journaled (``ckpt_verify_failed``) and the search
rolls back through older step directories to the newest step that
verifies (``ckpt_rollback``). Before this layer, a flipped bit in a
shard restored silently; now it costs at most one checkpoint interval.

Pre-integrity checkpoints (no COMMIT marker, empty done markers) are
still accepted on done-marker completeness alone — they carry no CRCs
to check, and refusing them would strand every checkpoint written
before the upgrade.
"""

from __future__ import annotations

import json
import os
import re
import zlib

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry.journal import get_journal
from dlrover_tpu.telemetry.metrics import registry

logger = get_logger(__name__)

_verify_failed_total = registry().counter(
    "dlrover_tpu_ckpt_verify_failed_total",
    "checkpoint steps rejected by restore-time verification, by kind",
    label_names=("kind",),
)
_rollback_total = registry().counter(
    "dlrover_tpu_ckpt_rollback_total",
    "restores rolled back past a corrupt/incomplete newest step",
)

STEP_DIR_RE = re.compile(r"^step-(\d+)$")
_COMMIT_RE = re.compile(r"^commit_w(\d+)$")
_DONE_RE = re.compile(r"^done_(.+)_w(\d+)$")


def crc32_bytes(data: bytes) -> int:
    return zlib.crc32(data) & 0xFFFFFFFF


def commit_marker(num_shards: int) -> str:
    """Like done markers, the COMMIT is world-size-qualified: a re-save
    of the same step after an elastic reshape must not be validated
    against a previous incarnation's manifest."""
    return f"commit_w{num_shards}"


def write_commit(storage, sdir: str, step: int, num_shards: int,
                 shards: dict) -> None:
    """Terminal COMMIT: ``shards`` maps node id (str) -> {"crc32",
    "bytes"} as collected from the done markers. Atomic via the
    storage's tmp+fsync+rename write."""
    storage.write(
        json.dumps({"step": step, "num_shards": num_shards,
                    "shards": shards}),
        os.path.join(sdir, commit_marker(num_shards)),
    )


def _shard_crc(storage, path: str) -> tuple[int, int]:
    """(crc32, size). Streams local files so verifying a multi-GB shard
    never materializes it in memory."""
    from dlrover_tpu.common.storage import PosixDiskStorage

    if isinstance(storage, PosixDiskStorage):
        crc = 0
        size = 0
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                crc = zlib.crc32(chunk, crc)
                size += len(chunk)
        return crc & 0xFFFFFFFF, size
    blob = storage.read(path)
    return crc32_bytes(blob), len(blob)


def verify_step_dir(storage, sdir: str, num_shards: int) -> str | None:
    """None when the step verifies; else a short failure kind.

    With a COMMIT marker: the manifest must list ``num_shards`` shards
    and every one must exist with matching size and CRC32. Without one:
    legacy acceptance on done-marker count alone.
    """
    files = storage.listdir(sdir)
    marker = commit_marker(num_shards)
    if marker not in files:
        done = [
            f for f in files
            if f.startswith("done_") and f.endswith(f"_w{num_shards}")
        ]
        return None if len(done) >= num_shards else "missing_commit"
    try:
        manifest = json.loads(
            storage.read_text(os.path.join(sdir, marker))
        )
        shards = dict(manifest.get("shards", {}))
    except (ValueError, OSError, TypeError):
        return "corrupt_commit"
    if len(shards) < int(manifest.get("num_shards", num_shards)):
        return "incomplete_manifest"
    for nid, entry in shards.items():
        bin_path = os.path.join(sdir, f"node_{nid}.bin")
        meta_path = os.path.join(sdir, f"node_{nid}.meta.json")
        if not storage.exists(bin_path) or not storage.exists(meta_path):
            return "missing_shard"
        want = (entry or {}).get("crc32")
        if want is None:
            continue  # mixed-version writer: nothing to check against
        crc, size = _shard_crc(storage, bin_path)
        want_bytes = (entry or {}).get("bytes")
        if want_bytes is not None and size != int(want_bytes):
            return "truncated_shard"
        if crc != int(want):
            return "crc_mismatch"
    return None


def _dir_worlds(files: list[str]) -> list[int]:
    """Candidate writer world sizes recorded in a step dir's markers."""
    worlds = set()
    for f in files:
        m = _COMMIT_RE.match(f) or _DONE_RE.match(f)
        if m:
            worlds.add(int(m.group(m.lastindex)))
    return sorted(worlds, reverse=True)


def _reject(step: int, kind: str) -> None:
    _verify_failed_total.labels(kind).inc()
    get_journal().emit("ckpt_verify_failed", step=step, kind=kind)
    logger.error("checkpoint step %d failed verification: %s", step, kind)


def resolve_restore_step(storage, ckpt_dir: str
                         ) -> tuple[int, int] | None:
    """The newest VERIFIED (step, num_shards) to restore from.

    Starts at the tracker's step; if that step fails verification (or
    the tracker itself is torn), walks the step directories newest
    first and returns the first that verifies, journaling the rollback.
    Returns None when nothing restorable exists — the caller starts
    fresh, which beats silently installing corrupt weights.
    """
    from dlrover_tpu.agent.ckpt_saver import read_tracker, step_dir

    tracked: tuple[int, int] | None = None
    try:
        tracked = read_tracker(storage, ckpt_dir)
    except (ValueError, OSError):
        _reject(-1, "corrupt_tracker")
    steps = []
    for name in storage.listdir(ckpt_dir):
        m = STEP_DIR_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    steps.sort(reverse=True)

    checked: set[int] = set()
    candidates: list[tuple[int, int | None]] = []
    if tracked is not None:
        candidates.append(tracked)
    candidates.extend((s, None) for s in steps)
    for step, num_shards in candidates:
        if step in checked:
            continue
        checked.add(step)
        sdir = step_dir(ckpt_dir, step)
        if not storage.exists(sdir):
            _reject(step, "missing_dir")
            continue
        worlds = ([num_shards] if num_shards
                  else _dir_worlds(storage.listdir(sdir)))
        fail_kind = "unverifiable"
        for world in worlds:
            kind = verify_step_dir(storage, sdir, world)
            if kind is None:
                if tracked is not None and step != tracked[0]:
                    _rollback_total.inc()
                    get_journal().emit("ckpt_rollback",
                                       from_step=tracked[0], to_step=step)
                    logger.warning(
                        "rolling back restore: step %d failed "
                        "verification, using newest verified step %d",
                        tracked[0], step,
                    )
                return step, world
            fail_kind = kind
        _reject(step, fail_kind)
    return None
